"""Replica-set serving: health-routed failover router with tenant QoS.

One :class:`~parallel_convolution_tpu.serving.service.ConvolutionService`
is one engine on one mesh — a single transient fault, reshape, or queue
spike is a full outage.  This module is the front tier that owns N
INDEPENDENT replicas (in-process services for tier-1 and drills, HTTP
services for deployment — one transport protocol, two adapters) and
keeps serving through any single replica's failure, drain, or reshape.

Design points:

* **Consistent-hash routing by compile key.**  Requests hash by their
  compile-identity fields (:func:`route_key` — the ``EngineKey`` string
  proxy a router can compute without a mesh) onto a virtual-node hash
  ring, so each replica's warm-executable cache holds ITS shard of the
  key space instead of every replica compiling everything.  Adding or
  removing one replica remaps only that replica's keys (the classic
  consistent-hashing property, asserted in ``tests/test_router.py``).
* **Bounded-load spill.**  The home replica is skipped — and the next
  ring replica tried — when it is unready (``/readyz`` poll), its
  circuit is open, or it already carries more than ``load_factor×`` its
  fair share of in-flight requests (consistent hashing with bounded
  loads: one hot key cannot melt one replica while others idle).
* **Active + passive health.**  A poll thread hits every replica's
  ``readyz`` (reshape/queue-bound state, round 13's probe) on an
  interval; between polls, per-dispatch outcomes feed a per-replica
  :class:`~parallel_convolution_tpu.resilience.breaker.CircuitBreaker`
  (consecutive classified failures open it; half-open probes re-admit).
* **Failover re-submits only idempotent work.**  Convolution/Jacobi
  requests are pure; the router stamps a ``request_id`` so a hedged or
  re-submitted request is DEDUPLICATED at the replica (one device
  execution per id — ``service.submit``'s idempotency ledger) and never
  double-charged against tenant quota (the router charges once, at
  admission).
* **Tenant QoS.**  Per-tenant token buckets (wall-clock refill) admit
  requests before any routing; an exhausted bucket sheds a typed,
  retryable ``Rejected("tenant_quota")`` carrying the exact refill time
  — distinct from the replicas' global ``queue_full`` shedding, so one
  greedy tenant cannot starve another (asserted in tier-1).  Tokens are
  refunded when NO replica did work (shed/unavailable outcomes): quota
  meters work, not misfortune.
* **Progressive results.**  ``converge`` routes a convergence job the
  same way and streams the replica's snapshot rows through (chunked
  HTTP / iterator in-process); a job that dies mid-stream has already
  delivered its best-so-far image + diff trajectory, and the router
  fails over BEFORE the first row but never mid-stream (re-running a
  half-delivered job would duplicate device work the client already
  has).
* **Crash-safe control plane (round 19).**  With ``wal=`` armed, every
  admission / newest resume token / finalization / ring change / tenant
  debt level is journaled write-ahead (``serving/wal.py``); constructing
  a router over an existing WAL replays it — jobs resume from their
  newest durable token ACROSS a router restart, the exactly-once final
  gate survives, and a monotonic fencing ``epoch`` (bumped past the WAL's
  and every replica's own fence on each takeover, stamped on every
  router→replica request, ratcheted replica-side) guarantees a zombie
  predecessor is rejected typed ``stale_epoch`` instead of
  double-delivering a final.

stdlib + numpy only; jax stays inside the replicas.
"""

from __future__ import annotations

import bisect
import hashlib
import itertools
import json
import threading
import time

from parallel_convolution_tpu.obs import (
    events as obs_events, metrics as obs_metrics, trace as obs_trace,
)
from parallel_convolution_tpu.resilience.breaker import (
    OPEN, CircuitBreaker,
)
from parallel_convolution_tpu.serving import frames as frames_mod
from parallel_convolution_tpu.serving.frontend import (
    InProcessClient, drain_body, send_frames, send_frames_stream,
    send_json, send_ndjson_stream,
)
from parallel_convolution_tpu.serving.jobs import JobLedger, token_progress
from parallel_convolution_tpu.serving.service import ReleasingStream

__all__ = [
    "CorruptReplicaBody", "HTTPReplica", "HashRing", "InProcessReplica",
    "ReplicaRouter", "TenantQuotas", "TokenBucket",
    "make_router_http_server", "route_key",
]


class CorruptReplicaBody(ConnectionError):
    """A replica answered with bytes that do not parse as the protocol
    (corrupt/truncated JSON).  A ``ConnectionError`` subclass ON
    PURPOSE: ``resilience.retry.classify`` already calls that transient,
    so the breaker/failover machinery treats a corrupting replica
    exactly like a dead one — it must never escape the router as an
    uncaught ``JSONDecodeError``.  The distinct type feeds the
    per-replica ``corrupt_responses`` counter (``/stats``)."""


# -- compile-key routing ------------------------------------------------------

# Every wire field that lands in the replica's EngineKey (the compile
# identity).  Image CONTENT is deliberately absent: equal configs share
# one warm executable, so they must share one home replica.  r17 adds
# col_mode/solver/mg_levels — they land in the EngineKey too (r15/r16),
# and the warm-placement observatory replays exactly these fields, so a
# field missing here would make a joining replica pre-warm the WRONG
# program for requests that set it.
ROUTE_KEY_FIELDS = ("rows", "cols", "mode", "filter", "iters", "backend",
                    "storage", "fuse", "boundary", "quantize", "overlap",
                    "tile", "check_every", "col_mode", "solver",
                    "mg_levels")


def route_key(body: dict) -> str:
    """The consistent-hash key of one wire request: a canonical string
    of its compile-identity fields (the ``EngineKey`` proxy)."""
    return "|".join(f"{k}={body.get(k)!r}" for k in ROUTE_KEY_FIELDS)


class HashRing:
    """Consistent-hash ring with virtual nodes.

    ``candidates(key)`` returns every member exactly once, in ring order
    from the key's point — index 0 is the HOME replica, the rest the
    spill/failover order.  Membership changes remap only the touched
    member's keys.
    """

    def __init__(self, names=(), vnodes: int = 64):
        if vnodes < 1:
            raise ValueError("vnodes >= 1 required")
        self.vnodes = int(vnodes)
        self._names: set[str] = set()
        # (points, owners, distinct-member count) swapped as ONE tuple
        # so a concurrent reader (the dispatch path, while the
        # autoscaler joins/leaves a member) can never see a half-rebuilt
        # table; the count rides along so the hot path stays O(1) on it.
        self._table: tuple[tuple[int, ...], tuple[str, ...], int] = (
            (), (), 0)
        self._mutate = threading.Lock()
        for n in names:
            self.add(n)

    @staticmethod
    def _hash(s: str) -> int:
        return int(hashlib.sha1(s.encode()).hexdigest()[:16], 16)

    def _rebuild(self) -> None:
        pairs = sorted(
            (self._hash(f"{name}#{i}"), name)
            for name in self._names for i in range(self.vnodes))
        self._table = (tuple(p for p, _ in pairs),
                       tuple(n for _, n in pairs),
                       len(self._names))

    def add(self, name: str) -> None:
        with self._mutate:
            self._names.add(str(name))
            self._rebuild()

    def remove(self, name: str) -> None:
        with self._mutate:
            self._names.discard(str(name))
            self._rebuild()

    def members(self) -> list[str]:
        with self._mutate:
            return sorted(self._names)

    def candidates(self, key: str) -> list[str]:
        """All members in ring order from ``key``'s point (home first)."""
        points, owners, distinct = self._table
        if not points:
            return []
        out: list[str] = []
        seen: set[str] = set()
        start = bisect.bisect_left(points, self._hash(key))
        n = len(owners)
        for i in range(n):
            owner = owners[(start + i) % n]
            if owner not in seen:
                seen.add(owner)
                out.append(owner)
                if len(seen) == distinct:
                    break
        return out


# -- tenant QoS ---------------------------------------------------------------

class TokenBucket:
    """Wall-clock-refilled token bucket (``rate`` tokens/s, ``burst``
    capacity).  ``rate <= 0`` means unlimited."""

    def __init__(self, rate: float, burst: float, clock=time.monotonic):
        self.rate = float(rate)
        # Burst must only be POSITIVE, not >= 1: under cost-priced
        # admission a bucket's unit is predicted device-seconds, and a
        # tenant's whole budget can legitimately be a fraction of one —
        # the old 1.0 floor silently re-minted such buckets 30x larger
        # (caught live by the greedy-tenant drill).
        self.burst = max(1e-9, float(burst))
        self._clock = clock
        self._tokens = self.burst
        self._last = clock()
        self._lock = threading.Lock()

    def _refill(self, now: float) -> None:
        if self.rate > 0:
            self._tokens = min(self.burst,
                               self._tokens + (now - self._last) * self.rate)
        self._last = now

    def try_take(self, n: float = 1.0, journal=None) -> tuple[bool, float]:
        """(granted, retry_after_s).  On refusal, ``retry_after_s`` is the
        exact wall time until the bucket can grant ``n`` again.

        A charge larger than the burst is granted once the bucket is
        FULL and drives the balance NEGATIVE (debt): with cost-priced
        admission one legitimate big job can cost more than the burst,
        and refusing it forever would make ``burst`` a silent per-job
        size cap instead of a smoothing window.  The debt refills at
        ``rate`` like any other deficit, so long-run fairness is
        untouched — the tenant just waits out its own big job.

        ``journal`` (the WAL hook) is called with the POST-charge
        balance UNDER this bucket's lock on a successful take: the
        journaled level is atomic with the balance change and
        same-tenant journal order equals charge order — a level read
        outside the lock could race a concurrent take and journal a
        stale balance that recovery would faithfully re-mint."""
        if self.rate <= 0:
            return True, 0.0
        need = min(float(n), self.burst)
        with self._lock:
            now = self._clock()
            self._refill(now)
            if self._tokens >= need:
                self._tokens -= float(n)
                if journal is not None:
                    journal(self._tokens)
                return True, 0.0
            return False, (need - self._tokens) / self.rate

    def refund(self, n: float = 1.0, journal=None) -> None:
        if self.rate <= 0:
            return
        with self._lock:
            self._tokens = min(self.burst, self._tokens + n)
            if journal is not None:
                journal(self._tokens)

    def absorb(self, delta: float) -> None:
        """Apply a PEER's replicated charge (+) or refund (−) to this
        bucket (round 21 fleet-wide quotas): the local balance moves by
        ``delta`` with the usual burst ceiling, but no journal hook runs
        — a replicated delta must never be re-journaled or re-replicated
        (echo), and debt below zero is legal exactly as in
        :meth:`try_take`."""
        if self.rate <= 0:
            return
        with self._lock:
            self._refill(self._clock())
            self._tokens = min(self.burst, self._tokens - float(delta))

    def level(self) -> float:
        with self._lock:
            self._refill(self._clock())
            return self._tokens

    def set_level(self, level: float) -> None:
        """Restore the balance to an absolute level (WAL recovery:
        the journal records post-charge levels, and a restarted router
        must not re-mint a drained tenant a full bucket).  Refill
        resumes from NOW — downtime refill is deliberately forfeited
        (conservative: a recovering control plane under-grants)."""
        if self.rate <= 0:
            return
        with self._lock:
            self._tokens = min(self.burst, float(level))
            self._last = self._clock()


class TenantQuotas:
    """Per-tenant admission buckets: one :class:`TokenBucket` per tenant
    (created on first sight, FIFO-bounded), all sharing a default
    (rate, burst) unless ``overrides[tenant] = (rate, burst)`` says
    otherwise.  Isolation is the point: tenant A's bucket emptying can
    never affect tenant B's — only the replicas' GLOBAL queue bound can,
    and that sheds a differently-typed reason."""

    def __init__(self, rate: float, burst: float, overrides=None,
                 max_tenants: int = 1024, clock=time.monotonic):
        from collections import OrderedDict

        self.rate, self.burst = float(rate), float(burst)
        self.overrides = dict(overrides or {})
        self.max_tenants = int(max_tenants)
        self._clock = clock
        self._buckets: "OrderedDict[str, TokenBucket]" = OrderedDict()
        self._lock = threading.Lock()

    def bucket(self, tenant: str) -> TokenBucket:
        with self._lock:
            b = self._buckets.get(tenant)
            if b is not None:
                self._buckets.move_to_end(tenant)   # LRU touch
                return b
            rate, burst = self.overrides.get(
                tenant, (self.rate, self.burst))
            b = TokenBucket(rate, burst, clock=self._clock)
            self._buckets[tenant] = b
            while len(self._buckets) > self.max_tenants:
                # Evict a FULL (idle, refilled) bucket when one exists:
                # evicting by age alone would let a drained tenant reset
                # its own quota by churning throwaway names until its
                # empty bucket ages out.  Churned fresh buckets are full,
                # so churn evicts churn, never a draining tenant.
                victim = next(
                    (t for t, bk in self._buckets.items()
                     if t != tenant and bk.level() >= bk.burst), None)
                if victim is None:
                    victim = next(t for t in self._buckets if t != tenant)
                self._buckets.pop(victim)
            return b

    def take(self, tenant: str, n: float = 1.0,
             journal=None) -> tuple[bool, float]:
        """Charge ``n`` work units (cost-priced admission passes the
        request's predicted device-seconds; the legacy request-count
        scheme is the degenerate ``n=1``).  ``journal`` rides through
        to the bucket (called with the post-charge balance under its
        lock)."""
        return self.bucket(tenant).try_take(n, journal=journal)

    def refund(self, tenant: str, n: float = 1.0, journal=None) -> None:
        self.bucket(tenant).refund(n, journal=journal)

    def absorb(self, tenant: str, delta: float) -> None:
        """Apply a peer's replicated debt delta (no journal, no echo —
        see :meth:`TokenBucket.absorb`)."""
        self.bucket(tenant).absorb(delta)

    def restore_level(self, tenant: str, level: float) -> None:
        """WAL-recovery seeding: set a tenant's balance to the level
        the journal last recorded for it."""
        self.bucket(tenant).set_level(level)

    def snapshot(self) -> dict:
        with self._lock:
            return {t: round(b.level(), 3) for t, b in self._buckets.items()}


# -- replica transports -------------------------------------------------------

class InProcessReplica:
    """One in-process service replica with kill/revive for drills.

    ``factory`` builds a fresh ``ConvolutionService`` (its own mesh, its
    own engine) — called at construction and on every :meth:`revive`.
    :meth:`kill` drains and closes the live service; requests against a
    killed replica raise ``ConnectionError`` exactly like a dead host,
    which is what the router's breaker/failover machinery keys on.
    """

    def __init__(self, factory, name: str = "r0"):
        self._factory = factory
        self.name = str(name)
        self._lock = threading.Lock()
        self.service = None
        self.client = None
        self.revive()

    def _live(self) -> InProcessClient:
        client = self.client
        if client is None:
            raise ConnectionError(f"replica {self.name} is down")
        return client

    def request(self, body: dict, timeout: float | None = None,
                traceparent: str | None = None):
        raw = body.get("_frames_raw")
        if raw is not None:
            # The router forwarded the client's frame bytes OPAQUELY;
            # the in-process boundary is where "the replica decodes"
            # happens (the one CRC walk).  The response comes back
            # framed and is split so the router can stamp its header
            # without touching the tensor bytes.
            header = {k: v for k, v in body.items() if k != "_frames_raw"}
            status, data = self._live().request_frames(
                frames_mod.join_envelope(header, raw), timeout=timeout,
                traceparent=traceparent)
            wire, out_raw = frames_mod.split_envelope(data)
            wire["_frames_raw"] = bytes(out_raw)
            return status, wire
        return self._live().request(body, timeout=timeout,
                                    traceparent=traceparent)

    def converge(self, body: dict, timeout: float | None = None,
                 traceparent: str | None = None):
        status, rows = self._live().converge(body, timeout=timeout,
                                             traceparent=traceparent)
        if status != 200:
            return status, rows

        def guarded():
            # A killed process's chunked stream BREAKS — emulate that
            # faithfully (without this, an in-process drill's kill would
            # leave the already-attached generator silently computing on
            # the closed service, and mid-stream failover would never be
            # exercised the way a real host death exercises it).
            for row in rows:
                if self.client is None:
                    raise ConnectionError(
                        f"replica {self.name} died mid-stream")
                yield row

        return status, guarded()

    def readyz(self):
        return self._live().readyz()

    def warm(self, configs) -> tuple[int, dict]:
        """Pre-compile declared configs on the live service (the
        warm-placement surface the autoscaler drives BEFORE ring join)."""
        return self._live().warm(configs)

    def fence(self, epoch: int, shard=None) -> tuple[int, dict]:
        """Ratchet the replica's router-epoch fence (takeover
        propagation — round 19; ``shard`` scopes the sweep to one
        lineage's ratchet, round 21)."""
        return self._live().fence(epoch, shard=shard)

    def snapshot(self) -> dict:
        return self._live().stats()[1]

    def kill(self) -> None:
        """Take the replica down (drains in-flight work first — admitted
        requests are idempotent and complete; NEW requests raise)."""
        with self._lock:
            svc, self.service, self.client = self.service, None, None
        if svc is not None:
            svc.close()

    def revive(self) -> None:
        from parallel_convolution_tpu.serving.frontend import (
            InProcessClient as _Client,
        )

        with self._lock:
            if self.service is None:
                self.service = self._factory()
                self.client = _Client(self.service)

    def close(self) -> None:
        self.kill()


class HTTPReplica:
    """One HTTP service replica (``scripts/serve.py``).  Transport
    failures surface as ``ConnectionError`` so the breaker classifies
    them transient; typed HTTP rejections pass through as (status, body).
    """

    def __init__(self, url: str, name: str | None = None,
                 timeout: float = 60.0, probe_timeout: float = 2.0):
        self.base = url.rstrip("/")
        self.name = name or self.base
        self.timeout = timeout
        # Health probes get their OWN short budget: the poll loop sweeps
        # replicas serially, so one black-holing host must cost it ~2 s,
        # not the request timeout.
        self.probe_timeout = min(probe_timeout, timeout)

    def _post(self, path: str, body: dict, timeout, traceparent):
        import urllib.error
        import urllib.request

        raw = body.get("_frames_raw")
        if raw is not None:
            # Opaque binary forwarding: re-wrap the router-stamped
            # header around the client's UNTOUCHED frame bytes (no
            # decode, no CRC walk — integrity is the replica's check).
            header = {k: v for k, v in body.items() if k != "_frames_raw"}
            data = frames_mod.join_envelope(header, raw)
            ctype = frames_mod.FRAMES_CONTENT_TYPE
        else:
            data = json.dumps(body).encode()
            ctype = "application/json"
        headers = {"Content-Type": ctype}
        if traceparent:
            headers["traceparent"] = traceparent
        req = urllib.request.Request(
            f"{self.base}{path}", data=data, headers=headers)
        try:
            return urllib.request.urlopen(
                req, timeout=timeout or self.timeout)
        except urllib.error.HTTPError as e:
            return e   # carries .status/.code + readable body
        except (urllib.error.URLError, OSError, TimeoutError) as e:
            raise ConnectionError(
                f"replica {self.name} unreachable: {e}") from e

    def request(self, body: dict, timeout: float | None = None,
                traceparent: str | None = None):
        resp = self._post("/v1/convolve", body, timeout, traceparent)
        with resp if hasattr(resp, "__enter__") else _closing(resp) as r:
            status = getattr(r, "status", None) or r.code
            ctype = (r.headers.get("Content-Type") or "").split(
                ";")[0].strip().lower()
            payload = r.read()
            if ctype == frames_mod.FRAMES_CONTENT_TYPE:
                try:
                    wire, out_raw = frames_mod.split_envelope(payload)
                except frames_mod.BadFrame as e:
                    raise CorruptReplicaBody(
                        f"replica {self.name} sent unparseable envelope "
                        f"(http {status}): {e}") from e
                wire["_frames_raw"] = bytes(out_raw)
                return status, wire
            try:
                return status, json.loads(payload)
            except ValueError as e:
                raise CorruptReplicaBody(
                    f"replica {self.name} sent unparseable body "
                    f"(http {status}): {e}") from e

    def converge(self, body: dict, timeout: float | None = None,
                 traceparent: str | None = None):
        resp = self._post("/v1/converge", body, timeout, traceparent)
        status = getattr(resp, "status", None) or resp.code
        if status != 200:
            with resp if hasattr(resp, "__enter__") else _closing(resp) as r:
                try:
                    return status, iter([json.loads(r.read())])
                except ValueError:
                    return status, iter([{"ok": False, "kind": "rejected",
                                          "rejected": "error",
                                          "detail": f"http {status}"}])

        def rows():
            try:
                with resp:
                    for line in resp:   # http.client de-chunks for us
                        line = line.strip()
                        if line:
                            try:
                                yield json.loads(line)
                            except ValueError as e:
                                # Corrupt NDJSON line: typed transport
                                # failure, flagged so the router's
                                # corrupt_responses counter sees it.
                                yield {"ok": False, "kind": "rejected",
                                       "rejected": "replica_unavailable",
                                       "retryable": True, "corrupt": True,
                                       "detail": "stream corrupt: "
                                                 f"{e}"[:300]}
                                return
            except OSError as e:
                # TRANSPORT death, not a typed execution failure: the
                # job itself may be fine elsewhere, so the row is
                # retryable — unlike a replica-typed `error` row, which
                # passes through retryable:false (RETRYABLE_REJECTS).
                yield {"ok": False, "kind": "rejected",
                       "rejected": "replica_unavailable",
                       "retryable": True,
                       "detail": f"stream broke: {e}"[:300]}

        return 200, rows()

    def _get(self, path: str, timeout: float | None = None):
        import urllib.error
        import urllib.request

        try:
            with urllib.request.urlopen(f"{self.base}{path}",
                                        timeout=timeout or self.timeout) as r:
                try:
                    return r.status, json.loads(r.read())
                except ValueError as ve:
                    raise CorruptReplicaBody(
                        f"replica {self.name} sent unparseable body "
                        f"({path}): {ve}") from ve
        except urllib.error.HTTPError as e:
            try:
                return e.code, json.loads(e.read())
            except Exception:  # noqa: BLE001
                return e.code, {"ok": False}
        except CorruptReplicaBody:
            # Already typed — it must not be re-wrapped by the generic
            # OSError handler below (CorruptReplicaBody IS an OSError).
            raise
        except (urllib.error.URLError, OSError, TimeoutError) as e:
            raise ConnectionError(
                f"replica {self.name} unreachable: {e}") from e

    def readyz(self):
        return self._get("/readyz", timeout=self.probe_timeout)

    def _post_json(self, path: str, body: dict, timeout):
        """POST + parse-or-typed-fallback (shared by the non-routing
        control surfaces: warm, fence)."""
        resp = self._post(path, body, timeout, None)
        with resp if hasattr(resp, "__enter__") else _closing(resp) as r:
            status = getattr(r, "status", None) or r.code
            try:
                return status, json.loads(r.read())
            except ValueError:
                return status, {"ok": False, "detail": f"http {status}"}

    def warm(self, configs) -> tuple[int, dict]:
        """POST /v1/warm — pre-compile declared configs (warm placement
        over the wire; compiles can take a while, so no probe budget)."""
        return self._post_json("/v1/warm",
                               {"configs": list(configs or ())}, None)

    def fence(self, epoch: int, shard=None) -> tuple[int, dict]:
        """POST /v1/fence — ratchet the replica's router-epoch fence
        (short probe budget: fencing is a takeover-path sweep and one
        black-holing host must not stall it).  ``shard`` scopes the
        sweep to one lineage's ratchet (round 21)."""
        body: dict = {"epoch": int(epoch)}
        if shard is not None:
            body["shard"] = str(shard)
        return self._post_json("/v1/fence", body, self.probe_timeout)

    def snapshot(self) -> dict:
        return self._get("/stats")[1]

    def close(self) -> None:
        pass


class _closing:
    """Context manager over urllib HTTPError responses (no __enter__)."""

    def __init__(self, obj):
        self.obj = obj

    def __enter__(self):
        return self.obj

    def __exit__(self, *exc):
        close = getattr(self.obj, "close", None)
        if close is not None:
            close()
        return False


# -- the router ---------------------------------------------------------------

class _ReplicaState:
    """Router-side record of one replica: transport + health + load."""

    __slots__ = ("name", "transport", "breaker", "ready", "ready_payload",
                 "in_flight", "stats")

    def __init__(self, transport, breaker: CircuitBreaker):
        self.name = transport.name
        self.transport = transport
        self.breaker = breaker
        self.ready = True          # optimistic until the first poll
        self.ready_payload: dict = {}
        self.in_flight = 0
        # resumes counts durable converge jobs that resumed ONTO this
        # replica; mid_stream_failovers counts streams that died ON it
        # after rows flowed; corrupt_responses counts unparseable bodies
        # it sent (CorruptReplicaBody / corrupt stream rows) — the
        # operator-debuggable chaos-drill surface, exposed in /stats
        # next to the autoscaler inputs.
        self.stats = {"routed": 0, "completed": 0, "sheds": 0,
                      "failures": 0, "resumes": 0,
                      "mid_stream_failovers": 0, "corrupt_responses": 0}


# Rejections that mean "no device work happened anywhere" — the tenant's
# token is refunded for these (quota meters work, not misfortune).
_REFUND_REJECTS = frozenset(
    {"queue_full", "resharding", "replica_unavailable"})
# Replica sheds the router SPILLS past (the replica is healthy but
# transiently unable) vs failures it FAILS OVER from (breaker food).
_SPILL_REJECTS = frozenset({"queue_full", "resharding"})


class ReplicaRouter:
    """The replica-set front tier (see module docstring).

    ``replicas`` are transports (:class:`InProcessReplica` /
    :class:`HTTPReplica`) with unique ``.name``s.  ``quotas`` is an
    optional :class:`TenantQuotas`.  ``hedge_s`` (off by default) fires
    ONE extra attempt at the next ring candidate when the home replica
    hasn't answered within the budget — first result wins, the loser's
    work is absorbed by the replica-side request_id dedup when both
    landed on the same replica (cross-replica hedges genuinely duplicate
    work; that is the standard tail-latency trade).
    """

    def __init__(self, replicas, *, quotas: TenantQuotas | None = None,
                 pricer=None,
                 vnodes: int = 64, breaker_threshold: int = 3,
                 breaker_cooldown_s: float = 1.0,
                 poll_interval_s: float = 0.25, load_factor: float = 2.0,
                 hedge_s: float | None = None, start_health: bool = True,
                 durable: bool = True, job_capacity: int = 64,
                 wal=None, clock=time.monotonic,
                 shard: str | None = None, on_debt=None):
        if not replicas:
            raise ValueError("at least one replica required")
        names = [r.name for r in replicas]
        if len(set(names)) != len(names):
            raise ValueError(f"replica names must be unique, got {names}")
        self._clock = clock
        self.breaker_threshold = int(breaker_threshold)
        self._replicas = {
            r.name: _ReplicaState(
                r, CircuitBreaker(breaker_threshold, breaker_cooldown_s,
                                  clock=clock))
            for r in replicas}
        self.ring = HashRing(names, vnodes=vnodes)
        self.quotas = quotas
        # Cost-priced admission (serving.pricing.WorkPricer): when armed,
        # tenant buckets are charged the request's predicted
        # device-seconds instead of 1 — an 8192² multigrid job pays its
        # real price and a thumbnail blur stays almost free.
        self.pricer = pricer
        self.load_factor = float(load_factor)
        self.hedge_s = hedge_s
        self.poll_interval_s = float(poll_interval_s)
        self.breaker_cooldown_s = float(breaker_cooldown_s)
        self._ids = itertools.count(1)
        self._lock = threading.Lock()
        # The key-config observatory: route_key -> the wire CONFIG fields
        # last seen for it (never image content).  This is the warm-
        # placement input — a JOINING replica pre-warms exactly the
        # configs whose consistent-hash home it is about to become
        # (shard_configs), before its vnodes enter the ring.  Bounded
        # FIFO; batch-path configs only (a converge job's warm state is
        # its chunk/level programs, which the first job re-warms).
        from collections import OrderedDict

        self._key_configs: "OrderedDict[str, dict]" = OrderedDict()
        self._key_configs_cap = 512
        # Durable convergence jobs (round 18): the resume-token ledger.
        # With durable=True (the default) every converge body is asked
        # to carry per-row token state, mid-stream deaths fail over to
        # the surviving ring candidates seeded from the newest token,
        # and the final row is exactly-once per request_id.
        self.durable = bool(durable)
        self.jobs = JobLedger(capacity=job_capacity,
                              shard=None if shard is None
                              else str(shard))
        self.stats = obs_metrics.MirroredStats(obs_metrics.gauge(
            "pctpu_router_stats", "replica-router admission/outcome counters",
            ("key",)), initial={
            "routed": 0, "completed": 0, "failovers": 0, "spills": 0,
            "hedges": 0, "rejected_tenant_quota": 0,
            "rejected_unavailable": 0, "progressive": 0, "resumes": 0,
            "mid_stream_failovers": 0, "wal_records": 0,
            "wal_write_errors": 0, "wal_degraded_windows": 0,
            "wal_rearms": 0,
        })
        # Crash-safe control plane (round 19): a write-ahead journal of
        # admissions / newest resume tokens / finals / ring membership /
        # tenant debt, replayed at construction — constructing a router
        # over an existing WAL IS the takeover.  ``self.epoch`` is the
        # fencing epoch: monotonic per WAL lineage, stamped on every
        # router→replica request, ratcheted replica-side, so a zombie
        # predecessor is rejected (``stale_epoch``) everywhere.  With
        # no WAL the epoch stays 0 and nothing is stamped (fencing is a
        # property of the durable deployment).
        self.wal = None
        self.epoch = 0
        # Durability degrade ladder (round 24): ``wal_degrade_threshold``
        # CONSECUTIVE append failures flip the router into a
        # ``durability: degraded`` window — it keeps serving (the r19
        # rule: durability failure is never an outage), stamps the
        # window on every ``router:`` block, and the first append that
        # succeeds again triggers a RE-ARM: a compaction snapshot built
        # from the LIVE structures (job ledger, ring, quotas), because
        # the WAL's own folded state missed everything that happened
        # during the window — replaying it would resurrect stale bytes.
        self.wal_degrade_threshold = 3
        self._wal_fail_streak = 0
        self._durability_degraded = False
        self._rearming = False
        self._wal_need_rearm = False
        # Sharded control plane (round 21): when this router owns one
        # shard of a partitioned ring, ``shard`` is its label — stamped
        # on every outbound body (``router_shard``) so replica-side
        # fencing is per-shard, and on every ``router:`` block so
        # traces attribute a request to the shard that served it.
        # ``map_version`` is the owning ShardRouter's shard-map version
        # (bumped on ownership change; 0 when unsharded).  ``on_debt``
        # is the peer-replication hook: called (tenant, delta) after
        # every quota charge/refund so a peer layer can replicate
        # tenant debt fleet-wide.
        self.shard = None if shard is None else str(shard)
        self.map_version = 0
        self.on_debt = on_debt
        if wal is not None:
            from parallel_convolution_tpu.serving.wal import RouterWAL

            self.wal = (wal if isinstance(wal, RouterWAL)
                        else RouterWAL(wal, shard=self.shard))
            self._recover()
        self._closed = threading.Event()
        self._poll_thread: threading.Thread | None = None
        if start_health:
            self.start_health()

    # -- crash recovery (round 19) --------------------------------------------
    def _wal_append(self, kind: str, **fields) -> None:
        """One WAL record, never fatal: a durability failure (disk
        full, injected ``wal_write``/``wal_fsync`` fault) is a LOUD
        counter + event, not a serving outage — the stream keeps
        flowing and recovery falls back to the newest record that DID
        land (an older boundary: more recompute, same bytes).

        Round 24 adds the degrade LADDER on top: a failure streak of
        ``wal_degrade_threshold`` flips the ``durability: degraded``
        window (stamped, evented, gauged); the first success after a
        window re-arms with a live-state compaction snapshot."""
        if self.wal is None:
            return
        try:
            self.wal.append(kind, **fields)
        except Exception as e:  # noqa: BLE001 — durability degrades loudly
            self._bump("wal_write_errors")
            with self._lock:
                self._wal_fail_streak += 1
                degraded_now = (
                    not self._durability_degraded
                    and self._wal_fail_streak
                    >= self.wal_degrade_threshold)
                if degraded_now:
                    self._durability_degraded = True
                    self.stats["wal_degraded_windows"] += 1
            if obs_metrics.enabled():
                obs_metrics.counter(
                    "pctpu_wal_append_errors_total",
                    "router WAL appends that failed (durability "
                    "degraded; serving unaffected)", ("kind",)).inc(
                    kind=kind)
                obs_events.emit("wal", event="append_failed",
                                record_kind=kind, error=repr(e)[:200])
                if degraded_now:
                    obs_metrics.gauge(
                        "pctpu_wal_durability_degraded",
                        "1 while the router serves inside a degraded-"
                        "durability window (sustained WAL append "
                        "failure), 0 when armed").set(1)
                    obs_events.emit(
                        "wal", event="durability_degraded",
                        streak=self._wal_fail_streak,
                        record_kind=kind)
        else:
            self._bump("wal_records")
            with self._lock:
                self._wal_fail_streak = 0
                # The heal signal only SETS a flag: this append may be
                # running under a quota-bucket or ledger lock (the debt
                # journal hook), and the re-arm's compaction snapshot
                # re-reads those very structures — re-arming inline
                # here deadlocks.  The serving paths drain the flag at
                # their next lock-free point (_maybe_rearm).
                if self._durability_degraded and not self._rearming:
                    self._wal_need_rearm = True

    def _maybe_rearm(self) -> None:
        """Drain a pending re-arm at a point where the caller holds no
        quota/ledger locks (request admission, the converge row loop).
        A failed re-arm keeps the window open; the next healthy append
        re-raises the flag."""
        if not self._wal_need_rearm:
            return
        with self._lock:
            if not self._wal_need_rearm:
                return
            self._wal_need_rearm = False
        self._rearm_wal()

    def _live_state_image(self):
        """A :class:`~.wal.WALState` built from the structures that
        KEPT SERVING through a degraded window — the job ledger, the
        live ring, the quota buckets, the current epoch — merged with
        the folded state's charge identities and cache tombstones.
        This is what the re-arm compaction snapshot carries: the
        journal's own folded image is the pre-window world and
        replaying it would resurrect stale tokens and un-finalized
        jobs whose finals already went out."""
        from parallel_convolution_tpu.serving.wal import WALState

        state = WALState()
        state.epoch = self.epoch
        jobs, finalized = self.jobs.export()
        old = self.wal.state
        for lid, job in jobs.items():
            prior = old.jobs.get(lid)
            # Charge identity (cost/budget/wu_start) rides only the
            # WAL admit record, so the folded copy is its one source;
            # a job admitted DURING the window never journaled one and
            # stays refund-less across a later crash (documented
            # trade-off — the window was loud).
            if prior is not None and prior.get("key") == job["key"]:
                for k in ("cost", "budget", "wu_start"):
                    job[k] = prior.get(k)
        state.jobs = jobs
        state.finalized = {lid: True for lid in finalized}
        state.ring = set(self.ring.members())
        state.ring_ever = set(old.ring_ever) | state.ring
        # Cache tombstones: keep the folded set — deaths journaled
        # during the window were lost, but the cache's own CRC + the
        # journaled-transition rule mean a stale ENTRY can still never
        # serve stale BYTES (DESIGN.md "Storage fault domains").
        state.cache_dead = dict(old.cache_dead)
        if self.quotas is not None:
            state.debts = {t: float(lvl)
                           for t, lvl in self.quotas.snapshot().items()}
        else:
            state.debts = dict(old.debts)
        return state

    def _rearm_wal(self) -> None:
        """Leave the degraded window: rotate the WAL behind a
        compaction snapshot of the LIVE state.  Failure keeps the
        window open (the heal was premature); success flips the stamp
        back to ``ok`` and counts a re-arm."""
        with self._lock:
            if not self._durability_degraded or self._rearming:
                return
            self._rearming = True
        try:
            image = self._live_state_image()
            self.wal.compact(image)
        except Exception as e:  # noqa: BLE001 — still degraded
            if obs_metrics.enabled():
                obs_events.emit("wal", event="rearm_failed",
                                error=repr(e)[:200])
            return
        finally:
            with self._lock:
                self._rearming = False
        with self._lock:
            self._durability_degraded = False
            self.stats["wal_rearms"] += 1
        if obs_metrics.enabled():
            obs_metrics.gauge(
                "pctpu_wal_durability_degraded",
                "1 while the router serves inside a degraded-"
                "durability window (sustained WAL append failure), "
                "0 when armed").set(0)
            obs_events.emit("wal", event="durability_rearmed",
                            jobs=len(self.jobs), epoch=self.epoch)

    def _refund(self, tenant: str, amount: float) -> None:
        """Quota refund + its WAL debt record (one path; the journal
        hook runs UNDER the bucket's lock so the recorded level is
        atomic with the balance change and same-tenant record order
        equals operation order — recovery's last-level-wins replay
        depends on both)."""
        if self.quotas is None or amount <= 0:
            return
        self.quotas.refund(
            tenant, amount,
            journal=(None if self.wal is None else (
                lambda lvl: self._wal_append(
                    "debt", tenant=tenant, delta=round(-amount, 9),
                    level=round(lvl, 9)))))
        self._debt_hook(tenant, -amount)

    def _recover(self) -> None:
        """Startup recovery: fold the WAL into live state, reconcile
        against the replicas (``/readyz`` + ``/stats``), bump the
        fencing epoch past everything ever seen, and propagate it.

        Invariants (DESIGN.md "Durable control plane"):

        1. the new epoch is strictly greater than the WAL's AND every
           reachable replica's fence — so even when the WAL was
           quarantined (or lost) a zombie predecessor cannot win;
        2. jobs resume from their newest DURABLE token (the ledger is
           seeded; the client's retry of the typed mid-stream row picks
           the token up via ``begin`` exactly like an in-process
           failover) and the exactly-once final gate survives the
           restart;
        3. ring membership replays: a member the WAL saw removed stays
           out; a provided transport the WAL never met joins normally;
           a recovered member with NO transport in this pool is dropped
           loudly (it cannot be dispatched to);
        4. tenant buckets restore to their journaled post-charge levels
           (refill resumes from now — recovery under-grants, never
           re-mints a drained tenant).
        """
        state = self.wal.state
        wal_epoch = state.epoch   # pre-bump (the epoch append below
        #                           folds into the same state object)
        report = dict(self.wal.recovery_report)
        # (2) durable jobs + the exactly-once gate.
        restored = self.jobs.restore(state.jobs, state.finalized)
        # (3) ring reconciliation.
        provided = set(self._replicas)
        dropped_members = sorted(state.ring - provided)
        removed = []
        for name in sorted(provided):
            if name in state.ring_ever and name not in state.ring:
                self.ring.remove(name)
                removed.append(name)
        if not self.ring.members():
            # Replay would leave an EMPTY ring (e.g. the only provided
            # transports are ones the WAL saw scale-removed): a router
            # that can route nothing is a silent total outage wearing
            # a clean boot line.  Re-seat every provided replica,
            # loudly — the operator pointed this pool at this WAL on
            # purpose.
            import warnings

            warnings.warn(
                "WAL recovery: ring replay removed every provided "
                f"replica ({removed}); re-seating all of "
                f"{sorted(provided)} rather than booting an "
                "unroutable router", RuntimeWarning, stacklevel=3)
            removed = []
            for name in sorted(provided):
                self.ring.add(name)
                self._wal_append("ring_add", name=name)
        # (4) tenant debt: restore journaled levels, then refund the
        # UNEXECUTED fraction of every crash-interrupted priced job
        # (its charge identity rides the admit record) — the
        # incremental-charge rule across a restart: the client's retry
        # re-charges only the remaining work, so die-takeover-resume-
        # complete still costs one uninterrupted job.
        refunded = {}
        if self.quotas is not None:
            for tenant, level in state.debts.items():
                self.quotas.restore_level(tenant, level)
            for lid, job in list(state.jobs.items()):
                cost = job.get("cost")
                if not cost:
                    continue
                budget = float(job.get("budget") or 0.0)
                wu_start = float(job.get("wu_start") or 0.0)
                wu_done = max(wu_start, token_progress(job.get("token")))
                denom = max(budget - wu_start, 1e-9)
                frac = max(0.0, min(1.0, (budget - wu_done) / denom))
                amount = float(cost) * frac
                if amount <= 0:
                    continue
                tenant = lid.split("\x1f", 1)[0]
                self._refund(tenant, amount)
                self._wal_append("job_settled", lid=lid)
                refunded[lid] = round(amount, 6)
        # (1) the fencing epoch: reconcile against every replica's own
        # fence (its /stats carries fence_epoch), then go one past.
        max_fence = 0
        reachable = []
        for name, rep in self._replicas.items():
            try:
                status, _ = rep.transport.readyz()
                snap = rep.transport.snapshot()
                if self.shard is not None:
                    # Per-shard fences (round 21): read THIS shard's
                    # ratchet; the scalar fence_epoch is the unsharded
                    # lineage's and would under- or over-fence here.
                    fences = snap.get("fence_epochs") or {}
                    rep_fence = int(fences.get(self.shard, 0) or 0)
                else:
                    rep_fence = int(snap.get("fence_epoch", 0) or 0)
                max_fence = max(max_fence, rep_fence)
                reachable.append(name)
            except Exception:  # noqa: BLE001 — a dead replica
                continue
        self.epoch = max(wal_epoch, max_fence) + 1
        self._wal_append("epoch", epoch=self.epoch)
        if not state.ring_ever:
            # A fresh WAL: journal the boot membership so the first
            # restart replays it instead of inferring it.
            for name in self.ring.members():
                self._wal_append("ring_add", name=name)
        fenced = []
        for name in reachable:
            fence = getattr(self._replicas[name].transport, "fence",
                            None)
            if fence is None:
                continue
            try:
                if self.shard is not None:
                    fence(self.epoch, shard=self.shard)
                else:
                    fence(self.epoch)
                fenced.append(name)
            except Exception:  # noqa: BLE001 — ratchets on first request
                continue
        self.recovery = {
            **({"shard": self.shard} if self.shard is not None else {}),
            "epoch": self.epoch, "wal_epoch": wal_epoch,
            "max_replica_fence": max_fence, "jobs_restored": restored,
            "finalized_restored": len(state.finalized),
            "ring_removed": removed, "dropped_members": dropped_members,
            "tenants_restored": sorted(state.debts),
            "refunded_jobs": refunded,
            "fenced": fenced, **report,
        }
        if dropped_members:
            import warnings

            warnings.warn(
                f"WAL recovery: ring members {dropped_members} have no "
                "transport in this pool — dropped from the recovered "
                "ring (their keys remap to the surviving members)",
                RuntimeWarning, stacklevel=3)
        if obs_metrics.enabled():
            obs_metrics.counter(
                "pctpu_wal_recoveries_total",
                "router WAL recoveries performed at startup").inc()
            obs_events.emit("wal", event="recovered", **{
                k: v for k, v in self.recovery.items()
                if k != "detail"})

    # -- health ---------------------------------------------------------------
    def start_health(self) -> None:
        if self._poll_thread is None or not self._poll_thread.is_alive():
            self._poll_thread = threading.Thread(
                target=self._poll_loop, name="pctpu-router-health",
                daemon=True)
            self._poll_thread.start()

    def _poll_loop(self) -> None:
        while not self._closed.wait(self.poll_interval_s):
            self.poll_once()

    def poll_once(self) -> None:
        """One active-health sweep: every replica's ``readyz``."""
        for rep in self._replicas.values():
            try:
                status, payload = rep.transport.readyz()
                ready, payload = status == 200, payload
            except Exception as e:  # noqa: BLE001 — a dead replica
                ready, payload = False, {"error": repr(e)[:200]}
            if ready != rep.ready and obs_metrics.enabled():
                obs_events.emit("router", event="replica_ready",
                                replica=rep.name, ready=ready)
                obs_metrics.counter(
                    "pctpu_router_ready_flips_total",
                    "replica ready-state transitions observed by the "
                    "health poll", ("replica",)).inc(replica=rep.name)
            rep.ready, rep.ready_payload = ready, payload

    # -- admission ------------------------------------------------------------
    def _bump(self, key: str, n: int = 1) -> None:
        with self._lock:
            self.stats[key] += n

    def _stamp(self, **fields) -> dict:
        """One ``router:`` response block: the given fields plus the
        fencing epoch and (when sharded) the shard label + shard-map
        version — the trace/attribution identity of the router life
        that served the request."""
        fields["epoch"] = self.epoch
        if self.wal is not None:
            # Degraded-durability honesty (round 24): every response
            # and NDJSON row served inside a degraded window says so —
            # a client that cares about crash-safety can tell these
            # results were produced while the journal was dark.
            fields["durability"] = ("degraded" if self._durability_degraded
                                    else "ok")
        if self.shard is not None:
            fields["shard"] = self.shard
            fields["map_version"] = self.map_version
        return fields

    def _debt_hook(self, tenant: str, delta: float) -> None:
        """Peer-replication fan-out for one quota charge/refund (the
        ``on_debt`` callback; errors are the peer layer's problem and
        must never fail admission)."""
        if self.on_debt is None:
            return
        try:
            self.on_debt(tenant, float(delta))
        except Exception:  # noqa: BLE001 — replication is best-effort
            pass

    def _tenant_admit(self, tenant: str, rid: str, trace_id: str,
                      cost: float = 1.0):
        """None when admitted; the (status, wire) shed otherwise.
        ``cost`` is the work-unit charge (predicted device-seconds with
        a pricer armed; 1.0 in the legacy request-count scheme)."""
        if self.quotas is None:
            return None
        # The journal hook records the post-charge level UNDER the
        # bucket's lock (a restarted router must not re-mint a drained
        # tenant a full bucket, and a level read outside the lock
        # could journal a stale balance under concurrency).
        ok, retry_after = self.quotas.take(
            tenant, cost,
            journal=(None if self.wal is None else (
                lambda lvl: self._wal_append(
                    "debt", tenant=tenant, delta=round(cost, 9),
                    level=round(lvl, 9)))))
        if ok:
            self._debt_hook(tenant, cost)
            if self.pricer is not None and obs_metrics.enabled():
                obs_metrics.counter(
                    "pctpu_router_work_units_total",
                    "work units (predicted device-seconds) charged at "
                    "admission", ("tenant",)).inc(cost, tenant=tenant)
            return None
        self._bump("rejected_tenant_quota")
        if obs_metrics.enabled():
            obs_metrics.counter(
                "pctpu_router_tenant_quota_total",
                "tenant-bucket admission sheds", ("tenant",)).inc(
                tenant=tenant)
            obs_events.emit("router", event="tenant_quota", tenant=tenant,
                            request_id=rid, cost_units=round(cost, 6),
                            retry_after_s=round(retry_after, 4))
        return 429, {
            "ok": False, "rejected": "tenant_quota", "retryable": True,
            "retry_after_s": round(retry_after, 4), "tenant": tenant,
            "cost_units": round(cost, 6),
            "request_id": rid, "trace_id": trace_id,
            "detail": f"tenant {tenant!r} bucket empty; refills at "
                      f"{self.quotas.bucket(tenant).rate}/s "
                      f"(this request costs {cost:.4g} units)",
        }

    def _observe_config(self, key: str, body: dict) -> None:
        """Record a route_key's wire CONFIG (warm-placement input)."""
        cfg = {k: body[k] for k in ROUTE_KEY_FIELDS if k in body}
        with self._lock:
            self._key_configs[key] = cfg
            self._key_configs.move_to_end(key)
            while len(self._key_configs) > self._key_configs_cap:
                self._key_configs.popitem(last=False)

    # -- dispatch -------------------------------------------------------------
    def _load_bound(self) -> int:
        """Bounded-load cap: ``load_factor ×`` the fair in-flight share,
        floored at ``load_factor`` — at near-zero total in-flight the
        fair share rounds to 1, and spilling the SECOND concurrent
        request for a key off its home would trade a duplicate compile
        on another replica for no protection at all (the cap exists for
        sustained overload, not a cold-start burst)."""
        live = [r for r in self._replicas.values()
                if r.ready and r.breaker.state() != OPEN]
        n_live = max(1, len(live))
        total = sum(r.in_flight for r in self._replicas.values())
        fair = self.load_factor * (total + 1) / n_live
        return max(1, int(self.load_factor + 0.999), int(fair + 0.999))

    def _record_counter(self, replica: str, outcome: str) -> None:
        if obs_metrics.enabled():
            obs_metrics.counter(
                "pctpu_router_requests_total",
                "routed dispatch outcomes per replica",
                ("replica", "outcome")).inc(replica=replica, outcome=outcome)

    def _try_one(self, rep: _ReplicaState, body: dict, timeout,
                 traceparent):
        """One dispatch to one replica.

        Returns ``("ok", status, wire)``, ``("shed", status, wire)``
        (typed retryable — spill past it), or ``("fail", status, wire)``
        / ``("fail", None, None)`` (breaker food — fail over).
        """
        with self._lock:
            rep.in_flight += 1
            rep.stats["routed"] += 1
        try:
            status, wire = rep.transport.request(
                body, timeout=timeout, traceparent=traceparent)
        except Exception as e:  # noqa: BLE001 — transport death
            rep.breaker.record_failure(e)
            with self._lock:
                rep.stats["failures"] += 1
                if isinstance(e, CorruptReplicaBody):
                    rep.stats["corrupt_responses"] += 1
            self._record_counter(rep.name, "transport_error")
            if obs_metrics.enabled():
                obs_events.emit("router", event="failover",
                                replica=rep.name, error=repr(e)[:200],
                                request_id=body.get("request_id", ""))
            return "fail", None, {"detail": repr(e)[:200]}
        finally:
            with self._lock:
                rep.in_flight -= 1
        reason = wire.get("rejected")
        if status == 200 and wire.get("ok"):
            rep.breaker.record_success()
            with self._lock:
                rep.stats["completed"] += 1
            self._record_counter(rep.name, "completed")
            return "ok", status, wire
        if reason in _SPILL_REJECTS:
            # The replica is healthy, just transiently unable: not
            # breaker food, but do try the next ring candidate.
            rep.breaker.record_success()
            with self._lock:
                rep.stats["sheds"] += 1
            self._record_counter(rep.name, f"shed_{reason}")
            return "shed", status, wire
        if reason == "error" or status >= 500:
            # A typed terminal execution failure (or an untyped 5xx) IS
            # a replica-health signal — and the work is idempotent, so
            # failing over is always safe.
            rep.breaker.record_failure()
            with self._lock:
                rep.stats["failures"] += 1
            self._record_counter(rep.name, "error")
            return "fail", status, wire
        # invalid / deadline / tenant-level outcomes: the request's own
        # story; pass through verbatim.
        rep.breaker.record_success()
        self._record_counter(rep.name, reason or "other")
        return "ok", status, wire

    def _dispatch(self, key: str, body: dict, timeout, sp,
                  offset: int = 0):
        """The candidate walk: home replica, then ring order.

        Pass 1 honors readiness + bounded load; pass 2 (only if pass 1
        dispatched nothing) ignores them — when EVERY replica looks
        unready, trying one beats returning unavailable unexamined.
        ``offset`` rotates the walk's starting point (a hedge starts at
        the NEXT ring candidate — re-walking from the home would just
        dedup into the slow attempt it is meant to race).
        """
        order = self.ring.candidates(key)
        home = order[0] if order else ""
        if offset and order:
            off = offset % len(order)
            order = order[off:] + order[:off]
        meta = self._stamp(home=home, replica="", attempts=0,
                           failovers=0, spills=0)
        last_shed = last_fail = None
        tp = (obs_trace.format_traceparent(sp.context)
              if sp.context is not None else None)
        dispatched_any = False
        for relaxed in (False, True):
            if relaxed and dispatched_any:
                break
            bound = self._load_bound()
            for name in order:
                rep = self._replicas.get(name)
                if rep is None:   # removed while this walk was underway
                    continue
                if not relaxed:
                    if not rep.ready or rep.in_flight >= bound:
                        meta["spills"] += 1
                        self._bump("spills")
                        continue
                if not rep.breaker.allow():
                    meta["spills"] += 1
                    self._bump("spills")
                    continue
                dispatched_any = True
                meta["attempts"] += 1
                verdict, status, wire = self._try_one(rep, body, timeout, tp)
                if verdict == "ok":
                    meta["replica"] = name
                    if name != home:
                        sp.set(spilled=True)
                    return status, wire, meta
                if verdict == "shed":
                    last_shed = (status, wire, name)
                    meta["spills"] += 1
                    self._bump("spills")
                else:
                    last_fail = (status, wire, name)
                    meta["failovers"] += 1
                    self._bump("failovers")
        if last_shed is not None:
            status, wire, name = last_shed
            meta["replica"] = name
            return status, wire, meta
        if last_fail is not None and last_fail[0] is not None:
            status, wire, name = last_fail
            meta["replica"] = name
            return status, wire, meta
        self._bump("rejected_unavailable")
        return 503, {
            "ok": False, "rejected": "replica_unavailable",
            "retryable": True,
            "retry_after_s": round(self.breaker_cooldown_s, 4),
            "request_id": body.get("request_id", ""),
            "detail": f"no live replica among {len(order)} "
                      f"({meta['failovers']} failed, {meta['spills']} "
                      "skipped)",
        }, meta

    # -- the public request path ---------------------------------------------
    def request(self, body: dict, timeout: float | None = None,
                tenant: str | None = None):
        """Route one wire-format request; returns ``(status, wire)``.

        The response carries a ``router`` stamp: the serving replica,
        the home replica, and the attempt/failover/spill counts — which
        is how ``loadgen`` observes failovers without server logs.
        """
        body = dict(body)
        rid = body.get("request_id") or f"rt{next(self._ids)}"
        body["request_id"] = rid
        tenant = str(tenant or body.get("tenant") or "default")
        body["tenant"] = tenant
        if self.epoch:
            # The fencing stamp (round 19): replicas ratchet on it and
            # reject anything older — a zombie router cannot write.
            body["router_epoch"] = self.epoch
        if self.shard is not None:
            # Round 21: scope the replica-side fence to THIS shard's
            # ratchet — fencing shard A's zombie must not reject the
            # same process's live ownership of shard B.
            body["router_shard"] = self.shard
        self._bump("routed")
        cost = (self.pricer.price(body)
                if self.pricer is not None else 1.0)
        # Which wire arm this request rides — stamped on the route span
        # and the response, so a trace/loadgen run can segment its
        # latency curves by codec.
        wire_arm = "frames" if "_frames_raw" in body else "json"
        with obs_trace.span("route", request_id=rid, tenant=tenant,
                            wire=wire_arm,
                            **({"shard": self.shard,
                                "map_version": self.map_version}
                               if self.shard is not None else {})) as sp:
            tid = sp.context.trace_id if sp.context is not None else ""
            shed = self._tenant_admit(tenant, rid, tid, cost)
            if shed is not None:
                sp.set(outcome="tenant_quota")
                status, wire = shed
                wire["wire"] = wire_arm
                wire["router"] = self._stamp(
                    home="", replica="", attempts=0, failovers=0,
                    spills=0)
                return status, wire
            # Admission's debt record may just have healed a degraded
            # window; the bucket lock is released now, so the re-arm
            # can run — this very response then stamps ``ok``.
            self._maybe_rearm()
            key = route_key(body)
            self._observe_config(key, body)
            sp.set(key=key)
            if self.hedge_s is not None:
                status, wire, meta = self._dispatch_hedged(
                    key, body, timeout, sp)
            else:
                status, wire, meta = self._dispatch(key, body, timeout, sp)
            sp.set(outcome=wire.get("rejected", "completed"),
                   replica=meta.get("replica", ""),
                   failovers=meta.get("failovers", 0))
            if status == 200 and wire.get("ok"):
                self._bump("completed")
                if (self.quotas is not None and self.pricer is not None
                        and wire.get("cache") == "hit"):
                    # The replica served this from its content-addressed
                    # result cache: no device ran.  Settle the admission
                    # charge down to the hit floor (pricing.hit_units) —
                    # the router cannot know at admission time, so it
                    # refunds the difference once the response says so.
                    over = cost - self.pricer.hit_units()
                    if over > 0:
                        self._refund(tenant, over)
            elif (self.quotas is not None
                  and wire.get("rejected") in _REFUND_REJECTS):
                # Refund the SAME charge admission took: with a pricer
                # armed that is the request's work units, not 1.
                self._refund(tenant, cost)
            wire.setdefault("wire", wire_arm)
            wire.setdefault("router", meta)
            if self.pricer is not None:
                wire["router"].setdefault("cost_units", round(cost, 6))
            return status, wire

    def _dispatch_hedged(self, key: str, body: dict, timeout, sp):
        """Tail-latency hedging: fire the normal dispatch, and if it has
        not resolved within ``hedge_s``, fire ONE more full dispatch
        concurrently (same request_id → the replica-side idempotency
        ledger absorbs a same-replica duplicate).  First result wins."""
        results: list = []
        done = threading.Condition()

        def attempt(offset: int = 0):
            r = self._dispatch(key, body, timeout, sp, offset=offset)
            with done:
                results.append(r)
                done.notify_all()

        t1 = threading.Thread(target=attempt, daemon=True)
        t1.start()
        with done:
            done.wait(self.hedge_s)
            if not results:
                self._bump("hedges")
                if obs_metrics.enabled():
                    obs_events.emit(
                        "router", event="hedge",
                        request_id=body.get("request_id", ""))
                # The hedge starts one ring position past the home: the
                # whole point is a DIFFERENT replica than the slow
                # attempt (same-replica hedges just dedup into it).
                threading.Thread(target=attempt, args=(1,),
                                 daemon=True).start()
            while not results:
                done.wait(1.0)
            # Prefer a 200 if both landed; else the first verdict.
            for r in results:
                if r[0] == 200 and r[1].get("ok"):
                    return r
            return results[0]

    # -- progressive ----------------------------------------------------------
    def _converge_cost(self, body: dict) -> float:
        """The admission charge for one converge job: with a pricer
        armed, the predicted device-seconds of the REMAINING work — a
        resumed job (body carries a token) is charged only for the
        budget the token hasn't spent (the r17 refund rule, extended:
        work already done was charged in the job's previous life)."""
        if self.pricer is None:
            return 1.0
        done = token_progress(body.get("resume"))
        if done > 0:
            total = float(body.get("max_iters", 500) or 500)
            remaining = max(1, int(total - done))
            return self.pricer.price(dict(body, max_iters=remaining),
                                     converge=True)
        return self.pricer.price(body, converge=True)

    def _converge_walk(self, key: str, body: dict, timeout, tp,
                       tried: set):
        """Walk ring candidates not yet ``tried`` with this job.

        Returns ``("stream", rep, rows)`` on an attached 200 stream
        (the replica's in-flight count already bumped), ``("pass",
        status, wire)`` for a request's-own-fault typed outcome
        (invalid/deadline/tenant — pass through verbatim), or
        ``("reject", status, wire)`` when the walk exhausted (typed
        retryable).  Pass 1 honors readiness + bounded load; pass 2
        relaxes them only if pass 1 dispatched nothing — replicas
        already in ``tried`` (they failed or shed THIS job) are never
        re-submitted.
        """
        rid = body.get("request_id", "")
        order = [n for n in self.ring.candidates(key) if n not in tried]
        last = None
        dispatched_any = False
        for relaxed in (False, True):
            if relaxed and dispatched_any:
                break
            bound = self._load_bound()
            for name in order:
                if name in tried:
                    continue
                rep = self._replicas.get(name)
                if rep is None:   # removed mid-walk
                    continue
                if not relaxed and (not rep.ready
                                    or rep.in_flight >= bound):
                    self._bump("spills")
                    continue
                if not rep.breaker.allow():
                    self._bump("spills")
                    continue
                dispatched_any = True
                try:
                    status, rows = rep.transport.converge(
                        body, timeout=timeout, traceparent=tp)
                except Exception as e:  # noqa: BLE001
                    rep.breaker.record_failure(e)
                    tried.add(name)
                    self._bump("failovers")
                    self._record_counter(rep.name, "transport_error")
                    with self._lock:
                        rep.stats["failures"] += 1
                        if isinstance(e, CorruptReplicaBody):
                            rep.stats["corrupt_responses"] += 1
                    last = (503, {
                        "kind": "rejected", "ok": False,
                        "rejected": "replica_unavailable",
                        "retryable": True, "request_id": rid,
                        "retry_after_s": round(self.breaker_cooldown_s, 4),
                        "detail": repr(e)[:200]})
                    continue
                if status != 200:
                    first = list(rows)[:1]
                    wire = first[0] if first else {"ok": False}
                    reason = wire.get("rejected")
                    if reason in _SPILL_REJECTS:
                        rep.breaker.record_success()
                        self._bump("spills")
                        last = (status, wire)
                        continue
                    if reason == "error" or status >= 500:
                        rep.breaker.record_failure()
                        tried.add(name)
                        self._bump("failovers")
                        with self._lock:
                            rep.stats["failures"] += 1
                        last = (status, wire)
                        continue
                    # invalid / deadline / tenant-level outcomes: the
                    # request's own fault — no ring walk helps, and it
                    # is NOT replica-health evidence (same taxonomy as
                    # `_try_one`).
                    rep.breaker.record_success()
                    return "pass", status, wire
                rep.breaker.record_success()
                self._record_counter(rep.name, "progressive")
                # The stream counts against the replica's in-flight
                # load for its WHOLE lifetime (progressive jobs are the
                # longest-running work in the system — invisible to
                # bounded-load spill, they'd pile onto one replica).
                with self._lock:
                    rep.in_flight += 1
                    rep.stats["routed"] += 1
                return "stream", rep, rows
        if last is not None:
            return "reject", last[0], last[1]
        self._bump("rejected_unavailable")
        return "reject", 503, {
            "kind": "rejected", "ok": False,
            "rejected": "replica_unavailable", "retryable": True,
            "retry_after_s": round(self.breaker_cooldown_s, 4),
            "request_id": rid,
            "detail": f"no live replica among "
                      f"{len(order)} candidates"}

    def converge(self, body: dict, timeout: float | None = None,
                 tenant: str | None = None):
        """Route one progressive convergence job; ``(status, rows)``.

        Round 18 (durable jobs): with ``durable=True`` every snapshot
        row the replica streams carries a bounded resume token (state
        recorded in the router's :class:`~..serving.jobs.JobLedger`,
        STRIPPED from the rows the client sees), and a mid-stream death
        — transport break, typed ``error`` row, untyped 5xx — after
        rows have flowed FAILS OVER to the remaining ring candidates
        seeded from the newest token: the job continues on a surviving
        replica from its last ``check_every``/V-cycle boundary instead
        of ending the stream.  Rows after a resume stamp ``router:
        {resumed_from, resume_count}``; the final row is exactly-once
        per ``request_id`` (ledger-gated).  Only when NO candidate
        remains does the stream end with the typed retryable row, and
        the tenant is refunded the UNEXECUTED fraction of the admission
        charge (quota meters work).  A client retry of that typed row
        (same ``request_id``) resumes from the ledger's token — and is
        admission-charged only for the remaining work.
        """
        body = dict(body)
        rid = body.get("request_id") or f"rt{next(self._ids)}"
        body["request_id"] = rid
        tenant = str(tenant or body.get("tenant") or "default")
        body["tenant"] = tenant
        if self.epoch:
            body["router_epoch"] = self.epoch
        if self.shard is not None:
            body["router_shard"] = self.shard
        self._bump("routed")
        self._bump("progressive")
        key = route_key(body)
        # The ledger identity is TENANT-SCOPED: request_id is
        # client-stamped, and route_key carries neither tenant nor image
        # content — without the scope, tenant B reusing tenant A's id on
        # a same-config job would be seeded from A's private field state.
        lid = f"{tenant}\x1f{rid}"
        ledger_seeded = False
        if self.durable:
            # Ask replicas for per-row token state; seed a client retry
            # from the ledger's newest token (explicit body tokens win).
            body["resume_state"] = True
            if "resume" not in body:
                token = self.jobs.begin(lid, key)
                if token is not None and not self._token_fits(token,
                                                              body):
                    # The retry changed the budget/cadence such that the
                    # token's boundary is no longer legal (e.g. raising
                    # max_iters past the old budget's short final
                    # chunk): start fresh rather than fail the job
                    # terminally 'invalid' on a token the CLIENT never
                    # supplied.
                    token = None
                if token is not None:
                    body["resume"] = token
                    ledger_seeded = True
        cost = self._converge_cost(body)
        with obs_trace.span("route", request_id=rid, tenant=tenant,
                            progressive=True,
                            **({"shard": self.shard,
                                "map_version": self.map_version}
                               if self.shard is not None else {})) as sp:
            tid = sp.context.trace_id if sp.context is not None else ""
            shed = self._tenant_admit(tenant, rid, tid, cost)
            if shed is not None:
                sp.set(outcome="tenant_quota")
                status, wire = shed
                wire["kind"] = "rejected"
                wire.setdefault("router", self._stamp(replica=""))
                return status, iter([wire])
            if self.durable:
                # Write-ahead admission — AFTER the quota gate (a shed
                # job took no charge, so it must leave no charge
                # identity for a recovery to "refund"): the job and,
                # with a pricer armed, its charge identity are durable
                # before any replica sees it; recovery refunds the
                # UNEXECUTED fraction of crash-interrupted jobs from
                # exactly these fields.
                self._wal_append(
                    "admit", lid=lid, key=key,
                    **({"cost": round(cost, 9),
                        "budget": float(body.get("max_iters", 500)
                                        or 500),
                        "wu_start": token_progress(body.get("resume"))}
                       if (self.pricer is not None
                           and self.quotas is not None) else {}))
            # NOT observed into the warm-placement observatory: a
            # converge job's warm state is its chunk/level programs,
            # which warmup() cannot reproduce from these fields (the
            # observatory is batch-path configs only, by design).
            tp = (obs_trace.format_traceparent(sp.context)
                  if sp.context is not None else None)
            tried: set[str] = set()
            verdict, a, b = self._converge_walk(key, body, timeout, tp,
                                                tried)
            if verdict == "pass":
                sp.set(outcome=b.get("rejected") or "rejected")
                # The request's own terminal fault: the charge stays,
                # but the WAL must record it SETTLED — a recovery has
                # nothing to reconcile for this job.
                self._wal_append("job_settled", lid=lid)
                b.setdefault("router", self._stamp(replica=""))
                return a, iter([b])
            if verdict == "reject":
                sp.set(outcome=b.get("rejected") or "rejected")
                # Same refund rule as `request`: the token comes back
                # only when NO replica did work — a terminal `error`
                # outcome executed on a device and stays charged.
                # Either way the charge identity settles NOW, so a
                # later recovery can't refund it (or refund it twice).
                if (self.quotas is not None
                        and b.get("rejected") in _REFUND_REJECTS):
                    self._refund(tenant, cost)
                self._wal_append("job_settled", lid=lid)
                b.setdefault("router", self._stamp(replica=""))
                return a, iter([b])
            rep, rows = a, b
            sp.set(outcome="streaming", replica=rep.name)
            if self.durable:
                # Pin the job while its stream is live: capacity
                # eviction must never take a MID-STREAM job's token
                # (the ledger_evicted fix — unpinned in release()).
                self.jobs.pin(lid)
            if ledger_seeded:
                # A client retry resuming from the ledger is a resume
                # too — counted and stamped like a mid-stream one ("the
                # job left a dead stream"; the ledger doesn't know which
                # replica died, the retry gap hides it).
                self._record_resume(lid, key, rid, "client-retry", rep,
                                    body["resume"])
            # `hold` shares the live attempt between the durable driver
            # and the release closure: released exactly once, for
            # whichever replica currently carries the stream — even
            # when the caller drops the stream un-started.
            hold = {"rep": rep, "released": False}

            def release():
                with self._lock:
                    if not hold["released"]:
                        hold["released"] = True
                        hold["rep"].in_flight -= 1
                if self.durable:
                    self.jobs.unpin(lid)

            return 200, ReleasingStream(
                self._stream_durable(key, body, timeout, tp, rid, lid,
                                     tenant, cost, tried, hold, rows),
                release)

    @staticmethod
    def _token_fits(token: dict, body: dict) -> bool:
        """Is this ledger token a legal seed for THIS body's budget?
        Jacobi tokens sit on check_every boundaries — or the minting
        budget's own final short chunk, which a changed max_iters may
        invalidate.  Multigrid tokens count V-CYCLES (every cycle is a
        legal boundary; max_iters is a fine-grid WORK-UNIT budget), so
        only the banked work must still fit the budget.
        """
        try:
            solver = str(token.get("solver")
                         or body.get("solver") or "jacobi")
            mi = float(body.get("max_iters", 500) or 500)
            if solver == "multigrid":
                return token_progress(token) <= mi
            it = int(token.get("iters", 0))
            ce = max(1, int(body.get("check_every", 10) or 10))
        except (TypeError, ValueError):
            return False
        return it <= mi and (it % ce == 0 or it == int(mi))

    def _record_resume(self, lid: str, key: str, rid: str,
                       from_name: str, to_rep, token: dict) -> None:
        """One resume's bookkeeping — ledger note, counters, obs event —
        shared by the mid-stream failover and client-retry paths so the
        stamp/metric vocabulary cannot drift between them."""
        n_res, _ = self.jobs.note_resume(lid, key, from_name)
        self._wal_append("resume", lid=lid, key=key,
                         from_replica=from_name)
        self._bump("resumes")
        with self._lock:
            to_rep.stats["resumes"] += 1
        if obs_metrics.enabled():
            obs_metrics.counter(
                "pctpu_converge_resumes_total",
                "durable converge jobs resumed mid-stream on a "
                "surviving replica", ("replica",)).inc(
                replica=to_rep.name)
            obs_events.emit(
                "resume", request_id=rid, from_replica=from_name,
                to_replica=to_rep.name,
                at_iters=int(token.get("iters", 0)),
                work_units=float(token.get("work_units", 0.0)),
                resume_count=n_res)

    def _switch_stream(self, hold, rep) -> None:
        """Move the in-flight accounting from the dying replica to the
        resumed one (the walk already bumped the newcomer)."""
        with self._lock:
            if not hold["released"]:
                hold["rep"].in_flight -= 1
            hold["rep"], hold["released"] = rep, False

    def _note_mid_stream_death(self, rep: _ReplicaState, kind: str,
                               detail: str, corrupt: bool) -> None:
        """Breaker + counter bookkeeping for one mid-stream death."""
        if kind == "resharding":
            # Healthy-but-unable (a reshape window): spill semantics,
            # not breaker food — but still a mid-stream failover.
            rep.breaker.record_success()
            self._bump("spills")
        else:
            rep.breaker.record_failure()
            self._bump("failovers")
        self._bump("mid_stream_failovers")
        with self._lock:
            rep.stats["mid_stream_failovers"] += 1
            if kind != "resharding":
                rep.stats["failures"] += 1
            if corrupt:
                rep.stats["corrupt_responses"] += 1
        self._record_counter(rep.name, "mid_stream_death")
        if obs_metrics.enabled():
            obs_events.emit("router", event="mid_stream_death",
                            replica=rep.name, reason=kind,
                            detail=detail[:200])

    def _stream_durable(self, key: str, body: dict, timeout, tp,
                        rid: str, lid: str, tenant: str, cost: float,
                        tried: set, hold: dict, rows):
        """The durable stream driver: pass rows through (token recorded,
        state stripped, router stamped), and on a mid-stream death walk
        the remaining ring candidates with the newest resume token until
        the job finishes or no candidate remains."""
        wu_start = token_progress(body.get("resume"))
        budget = float(body.get("max_iters", 500) or 500)
        wu_last = wu_start
        rows_flowed = 0
        try:
            while True:
                rep = hold["rep"]
                death = None   # (reason, detail, corrupt, row|None)
                try:
                    for row in rows:
                        row = dict(row)
                        if row.get("kind") == "rejected":
                            reason = row.get("rejected")
                            if reason in ("error", "replica_unavailable",
                                          "resharding"):
                                death = (reason,
                                         str(row.get("detail", ""))[:300],
                                         bool(row.get("corrupt")), row)
                                break
                            # invalid / tenant-level mid-stream rows: the
                            # request's own story — pass through and
                            # stop (charge stays; settle it so recovery
                            # has nothing to reconcile).
                            self._wal_append("job_settled", lid=lid)
                            row.setdefault(
                                "router", self._stamp(replica=rep.name))
                            yield row
                            return
                        if self.durable:
                            tok = self.jobs.observe(lid, key, row)
                            if tok is not None:
                                # Write-ahead: the token is durable
                                # BEFORE the row reaches the client, so
                                # a router crash right after this yield
                                # still resumes from this boundary.
                                self._wal_append("token", lid=lid,
                                                 key=key, token=tok)
                            row.pop("state_b64", None)
                            row.pop("state_shape", None)
                        wu_last = max(wu_last, float(
                            row.get("work_units", 0.0) or 0.0))
                        rows_flowed += 1
                        self._maybe_rearm()
                        stamp = self._stamp(replica=rep.name)
                        n_res, res_from = self.jobs.resume_info(lid)
                        if n_res:
                            stamp["resume_count"] = n_res
                            stamp["resumed_from"] = res_from
                        row["router"] = stamp
                        if row.get("kind") == "final":
                            if (self.durable
                                    and not self.jobs.finalize(lid)):
                                # Exactly-once: a concurrent stream for
                                # the same id already delivered the
                                # final.  End THIS stream with a typed
                                # terminal row — every stream must end
                                # in a final or a typed rejection (a
                                # silent EOF would let a client take
                                # its last snapshot for the result) —
                                # and never via the death classifier,
                                # which would charge a healthy replica
                                # a breaker failure for a completed job.
                                yield {
                                    "kind": "rejected", "ok": False,
                                    "rejected": "error",
                                    "retryable": False,
                                    "request_id": rid,
                                    "detail": "request_id collision: "
                                              "the final row was "
                                              "already delivered to a "
                                              "concurrent stream for "
                                              "this id",
                                    "router": self._stamp(
                                        replica=rep.name)}
                                return
                            self._wal_append("final", lid=lid)
                            self._bump("completed")
                            with self._lock:
                                rep.stats["completed"] += 1
                            yield row
                            return
                        yield row
                    else:
                        # Loop EXHAUSTED (no typed-death break): the
                        # stream ended without a final row — treat as a
                        # transport death (a half-closed HTTP stream can
                        # end cleanly mid-job).  Must be the for's else:
                        # after a typed-death break this line would
                        # clobber the captured death row.
                        death = ("replica_unavailable",
                                 "stream ended early", False, None)
                except Exception as e:  # noqa: BLE001 — mid-stream death
                    death = ("replica_unavailable", repr(e)[:300],
                             isinstance(e, CorruptReplicaBody), None)
                reason, detail, corrupt, death_row = death
                self._note_mid_stream_death(rep, reason, detail, corrupt)
                # This replica failed THIS job mid-stream: the resume
                # walk must not hand the job straight back to it.
                tried.add(rep.name)
                token = (self.jobs.token(lid, key)
                         if self.durable else None)
                if self.durable and (token is not None
                                     or rows_flowed == 0):
                    resume_body = dict(body)
                    if token is not None:
                        resume_body["resume"] = token
                    verdict, a, b = self._converge_walk(
                        key, resume_body, timeout, tp, tried)
                    if verdict == "stream":
                        self._switch_stream(hold, a)
                        rows = b
                        if token is not None:
                            # resumed_from names the DYING replica (the
                            # one the job left), per stamp contract.
                            self._record_resume(lid, key, rid, rep.name,
                                                a, token)
                        continue
                    if verdict == "pass":
                        self._wal_append("job_settled", lid=lid)
                        b.setdefault("router",
                                     self._stamp(replica=""))
                        yield b
                        return
                    # Walk exhausted.  A NON-retryable typed death (a
                    # replica-typed `error` row — possibly reproduced on
                    # every candidate the walk just tried) must pass
                    # through verbatim, retryable:false: reporting it as
                    # a retryable `replica_unavailable` would send the
                    # client into an infinite retry loop re-executing a
                    # deterministic failure (the r14 taxonomy split).
                    if (death_row is not None
                            and not death_row.get("retryable", False)):
                        end_row = death_row
                    else:
                        end_row = b
                elif death_row is not None:
                    # Non-resumable typed death: the replica's own row
                    # passes through (trace_id and detail intact), with
                    # a Retry-After hint where the reason is retryable.
                    end_row = death_row
                    if end_row.get("retryable"):
                        end_row.setdefault(
                            "retry_after_s",
                            round(self.breaker_cooldown_s, 4))
                else:
                    end_row = {
                        "kind": "rejected", "ok": False,
                        "rejected": reason,
                        "retryable": reason != "error",
                        "retry_after_s": round(
                            self.breaker_cooldown_s, 4),
                        "request_id": rid, "detail": detail}
                # No candidate left: refund the UNEXECUTED fraction of
                # the admission charge (with a pricer armed, cost covers
                # [wu_start, budget]; without one, keep the r14 rule —
                # refund only when NO replica did work).
                if self.quotas is not None:
                    if self.pricer is not None:
                        denom = max(budget - wu_start, 1e-9)
                        frac = max(0.0, min(1.0,
                                            (budget - wu_last) / denom))
                        if frac > 0:
                            self._refund(tenant, cost * frac)
                    elif (rows_flowed == 0
                          and end_row.get("rejected") in _REFUND_REJECTS):
                        self._refund(tenant, cost)
                # This stream END settles the charge identity — the
                # refund (if any) just happened, so a later recovery
                # must not reconcile this job again.  The token itself
                # survives: a client retry still resumes.
                self._wal_append("job_settled", lid=lid)
                n_res, res_from = self.jobs.resume_info(lid)
                stamp = self._stamp(replica="")
                if n_res:
                    stamp["resume_count"] = n_res
                    stamp["resumed_from"] = res_from
                end_row["router"] = {**stamp,
                                     **end_row.get("router", {})}
                yield end_row
                return
        finally:
            # Generator-exhaustion release twin of the wrapper closure:
            # whichever runs first wins (hold["released"] gates both).
            with self._lock:
                if not hold["released"]:
                    hold["released"] = True
                    hold["rep"].in_flight -= 1
            if self.durable:
                self.jobs.unpin(lid)

    # -- pool mutation (autoscaling) ------------------------------------------
    def add_replica(self, transport, join_ring: bool = True) -> None:
        """Register a NEW replica (unique ``transport.name``).

        With ``join_ring=False`` the replica is registered (health-
        polled, dispatchable as a relaxed-pass fallback via nothing —
        it owns no ring span) but receives no routed traffic until
        :meth:`join_ring`: the autoscaler's warm-placement window sits
        between the two calls — pre-warm the joining replica's key
        shard FIRST, then add its vnodes, so the remapped keys land on
        warm executables instead of a compile storm.
        """
        name = str(transport.name)
        with self._lock:
            if name in self._replicas:
                raise ValueError(f"replica {name!r} already registered")
            rep = _ReplicaState(transport, CircuitBreaker(
                self.breaker_threshold, self.breaker_cooldown_s,
                clock=self._clock))
            # Copy-on-write: concurrent dispatch threads iterate the OLD
            # dict object; in-place insertion could blow their iterators.
            self._replicas = {**self._replicas, name: rep}
        # One immediate active probe: the first routed request must not
        # ride the optimistic default into a replica that isn't up yet.
        try:
            status, payload = rep.transport.readyz()
            rep.ready, rep.ready_payload = status == 200, payload
        except Exception as e:  # noqa: BLE001 — a dead newborn
            rep.ready, rep.ready_payload = False, {"error": repr(e)[:200]}
        if obs_metrics.enabled():
            obs_events.emit("router", event="replica_added", replica=name,
                            in_ring=bool(join_ring))
        if join_ring:
            self.join_ring(name)

    def join_ring(self, name: str) -> None:
        """Add a registered replica's vnodes to the ring (it starts
        receiving its key shard NOW — pre-warm first)."""
        if name not in self._replicas:
            raise KeyError(f"unknown replica {name!r}")
        self.ring.add(name)
        self._wal_append("ring_add", name=name)
        if obs_metrics.enabled():
            obs_events.emit("router", event="ring_join", replica=name)

    def remove_replica(self, name: str, drain_s: float = 10.0,
                       close: bool = True) -> dict:
        """Drain one replica out of the pool (the scale-down path).

        Ring removal happens FIRST — new requests route to the
        remaining members (the same remap-only-the-touched-member
        property as a kill, but voluntary) — then in-flight work gets
        ``drain_s`` wall seconds to land (progressive streams count:
        they hold ``in_flight`` for their whole life).  A request that
        races the final close surfaces as the usual transport-death
        failover, i.e. a typed retryable outcome, never a dropped
        request.  Returns ``{"replica", "drained", "in_flight"}``.
        """
        with self._lock:
            rep = self._replicas.get(name)
            if rep is None:
                raise KeyError(f"unknown replica {name!r}")
            if len(self._replicas) <= 1:
                raise ValueError("cannot remove the last replica")
        self.ring.remove(name)
        self._wal_append("ring_remove", name=name)
        deadline = time.monotonic() + max(0.0, float(drain_s))
        while rep.in_flight > 0 and time.monotonic() < deadline:
            time.sleep(0.01)
        with self._lock:
            remaining = dict(self._replicas)
            remaining.pop(name, None)
            self._replicas = remaining
        if close:
            try:
                rep.transport.close()
            except Exception:  # noqa: BLE001 — best-effort teardown
                pass
        info = {"replica": name, "drained": rep.in_flight == 0,
                "in_flight": rep.in_flight}
        if obs_metrics.enabled():
            obs_events.emit("router", event="replica_removed", **info)
        return info

    def shard_configs(self, name: str) -> list[dict]:
        """The wire configs a replica named ``name`` would become HOME
        for if it joined the ring now — the pre-warm worklist (from the
        key-config observatory; config fields only, no image content).
        """
        with self._lock:
            items = list(self._key_configs.items())
        members = self.ring.members()
        if name not in members:
            members = [*members, name]
        probe = HashRing(members, vnodes=self.ring.vnodes)
        out = []
        for key, cfg in items:
            cands = probe.candidates(key)
            if cands and cands[0] == name:
                out.append(dict(cfg))
        return out

    # -- lifecycle / introspection -------------------------------------------
    def readyz(self):
        """(status, payload): 200 iff at least one replica is ready."""
        reps = {
            name: {"ready": rep.ready,
                   "breaker": rep.breaker.state(),
                   "in_flight": rep.in_flight}
            for name, rep in self._replicas.items()}
        ready = any(v["ready"] and v["breaker"] != OPEN
                    for v in reps.values())
        return (200 if ready else 503), {
            "ok": ready, "ready": ready, "replicas": reps}

    def snapshot(self) -> dict:
        members = set(self.ring.members())
        with self._lock:
            stats = dict(self.stats)
            per = {}
            for name, rep in self._replicas.items():
                payload = rep.ready_payload or {}
                per[name] = {
                    "ready": rep.ready,
                    "breaker": rep.breaker.snapshot(),
                    "in_flight": rep.in_flight,
                    # The autoscaler's own inputs, exposed for operators
                    # and tests alike (from the last /readyz poll):
                    "queue_depth": payload.get("queue_depth"),
                    "queue_bound": payload.get("queue_bound"),
                    "warm_keys": payload.get("warm_keys"),
                    "degraded": payload.get("degraded") or [],
                    "in_ring": name in members,
                    **rep.stats,
                }
        return {
            "router": stats,
            "replicas": per,
            "ring": sorted(members),
            "observed_keys": len(self._key_configs),
            # Durable-job ledger (round 18): live tokens + total resumes
            # — the chaos-drill operator surface.  Round 19 adds the
            # ledger_evicted counter inside.
            "jobs": self.jobs.snapshot(),
            # Crash-safe control plane (round 19): the fencing epoch
            # and the WAL's own health.  Round 21: the shard this
            # router owns (None when unsharded) + its map version.
            "epoch": self.epoch,
            **({"shard": self.shard, "map_version": self.map_version}
               if self.shard is not None else {}),
            **({"wal": self.wal.snapshot(),
                "durability": ("degraded" if self._durability_degraded
                               else "ok")}
               if self.wal is not None else {}),
            **({"tenants": self.quotas.snapshot()}
               if self.quotas is not None else {}),
        }

    def replica(self, name: str):
        """The named replica's TRANSPORT (drills kill/revive through it)."""
        return self._replicas[name].transport

    def close(self, close_replicas: bool = True) -> None:
        self._closed.set()
        t = self._poll_thread
        if t is not None and t.is_alive():
            t.join(5.0)
        if self.wal is not None:
            self.wal.close()
        if close_replicas:
            for rep in self._replicas.values():
                try:
                    rep.transport.close()
                except Exception:  # noqa: BLE001 — best-effort teardown
                    pass


# -- HTTP frontend ------------------------------------------------------------

def make_router_http_server(router: ReplicaRouter, host: str = "127.0.0.1",
                            port: int = 8080):
    """The router's own stdlib HTTP frontend: same wire format as the
    replica frontend (a client cannot tell a router from a replica,
    except for the extra ``router`` stamp), plus router-level
    ``/readyz``/``/stats``.  Tenant identity rides the ``x-tenant``
    header or the ``tenant`` body field."""
    from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

    class Handler(BaseHTTPRequestHandler):
        protocol_version = "HTTP/1.1"

        def log_message(self, fmt, *args):  # noqa: D102
            pass

        def _send(self, status: int, payload: dict) -> None:
            send_json(self, status, payload)

        def do_GET(self):  # noqa: N802 — http.server API
            if self.path == "/healthz":
                self._send(200, {"ok": True, **router.snapshot()})
            elif self.path == "/readyz":
                self._send(*router.readyz())
            elif self.path == "/stats":
                self._send(200, router.snapshot())
            elif self.path == "/v1/shardmap":
                # Sharded control plane (round 21): any router serves
                # the version-stamped shard map — clients fetch it from
                # whichever peer answers and route directly to owners.
                smw = getattr(router, "shardmap_wire", None)
                if smw is None:
                    self._send(404, {"ok": False,
                                     "detail": "not a sharded router"})
                else:
                    self._send(200, smw())
            elif self.path == "/metrics":
                from parallel_convolution_tpu.serving.frontend import (
                    metrics_text,
                )

                data = metrics_text().encode()
                self.send_response(200)
                self.send_header(
                    "Content-Type",
                    "text/plain; version=0.0.4; charset=utf-8")
                self.send_header("Content-Length", str(len(data)))
                self.end_headers()
                self.wfile.write(data)
            else:
                self._send(404, {"ok": False, "detail": "unknown path"})

        def _do_post_frames(self):
            """The negotiated binary wire at the ROUTER tier.

            ``/v1/convolve`` forwards the tensor bytes OPAQUELY: only
            the envelope header is parsed (everything routing, pricing,
            and QoS read lives there); the frames pass to the replica
            and back byte-untouched, CRC-verified once at the replica.
            ``/v1/converge`` is the exception — mid-stream failover
            needs the router to READ rows (resume tokens), so the job
            runs JSON router↔replica and rows re-frame at this edge;
            a converge stream amortizes that cost over its whole run.
            """
            n = int(self.headers.get("Content-Length", "0") or 0)
            raw = self.rfile.read(n)
            try:
                body, frames_raw = frames_mod.split_envelope(raw)
            except frames_mod.BadFrame as e:
                send_frames(self, 400, frames_mod.encode_envelope(
                    {"ok": False, "rejected": "bad_frame",
                     "retryable": False, "wire": "frames",
                     "detail": str(e)[:300]}, {}))
                return
            tenant = self.headers.get("x-tenant")
            if self.path == "/v1/convolve":
                body["_frames_raw"] = bytes(frames_raw)
                status, wire = router.request(body, tenant=tenant)
                out_raw = wire.pop("_frames_raw", b"")
                send_frames(self, status,
                            frames_mod.join_envelope(wire, out_raw))
                return
            # converge: decode fully, run the JSON machinery, re-frame.
            try:
                _, arrays = frames_mod.decode_envelope(raw)
            except frames_mod.BadFrame as e:
                send_frames(self, 400, frames_mod.encode_envelope(
                    {"ok": False, "rejected": "bad_frame", "kind":
                     "rejected", "retryable": False, "wire": "frames",
                     "detail": str(e)[:300]}, {}))
                return
            body.pop("_frame_fields", None)
            jbody = _frames_converge_to_json(body, arrays)
            status, rows = router.converge(jbody, tenant=tenant)
            if status != 200:
                row = next(iter(rows))
                send_frames(self, status, _reframe_row(row))
                return
            send_frames_stream(self, (_reframe_row(r) for r in rows))

        def do_POST(self):  # noqa: N802 — http.server API
            if self.path not in ("/v1/convolve", "/v1/converge",
                                 "/v1/peersync"):
                # Drain the body first: under HTTP/1.1 keep-alive an
                # unread body would be parsed as the NEXT request line.
                drain_body(self)
                self._send(404, {"ok": False, "detail": "unknown path"})
                return
            if self.path == "/v1/peersync":
                # Peer anti-entropy pull (round 21): the caller posts
                # its sync cursor, the reply carries map + membership +
                # debt deltas since then.
                sync = getattr(router, "handle_peersync", None)
                try:
                    n = int(self.headers.get("Content-Length", "0"))
                    body = json.loads(self.rfile.read(n) or b"{}")
                    if not isinstance(body, dict):
                        raise ValueError("body must be a JSON object")
                except (ValueError, json.JSONDecodeError) as e:
                    self._send(400, {"ok": False, "rejected": "invalid",
                                     "detail": f"bad JSON body: {e}"})
                    return
                if sync is None:
                    self._send(404, {"ok": False,
                                     "detail": "not a sharded router"})
                else:
                    self._send(200, sync(body))
                return
            ctype = (self.headers.get("Content-Type") or "").split(
                ";")[0].strip().lower()
            if ctype == frames_mod.FRAMES_CONTENT_TYPE:
                self._do_post_frames()
                return
            try:
                n = int(self.headers.get("Content-Length", "0"))
                body = json.loads(self.rfile.read(n) or b"{}")
                if not isinstance(body, dict):
                    raise ValueError("body must be a JSON object")
            except (ValueError, json.JSONDecodeError) as e:
                self._send(400, {"ok": False, "rejected": "invalid",
                                 "detail": f"bad JSON body: {e}"})
                return
            tenant = self.headers.get("x-tenant")
            if self.path == "/v1/converge":
                status, rows = router.converge(body, tenant=tenant)
                if status != 200:
                    self._send(status, next(iter(rows)))
                    return
                send_ndjson_stream(self, rows)
                return
            self._send(*router.request(body, tenant=tenant))

    return ThreadingHTTPServer((host, port), Handler)


def _frames_converge_to_json(header: dict, arrays: dict) -> dict:
    """A framed converge request → its JSON-wire twin (the router's
    converge machinery — failover walk, resume tokens — reads row and
    body DICTS, so framed converge transcodes at the router edge)."""
    import base64

    import numpy as np

    body = dict(header)
    img = arrays.get("image")
    if img is not None:
        body["image_b64"] = base64.b64encode(
            np.ascontiguousarray(img).tobytes()).decode("ascii")
    state = arrays.get("resume_state")
    if state is not None:
        token = dict(body.get("resume") or {})
        token["state_b64"] = base64.b64encode(
            np.ascontiguousarray(state).tobytes()).decode("ascii")
        token["state_shape"] = list(state.shape)
        body["resume"] = token
    return body


def _reframe_row(row: dict) -> bytes:
    """One JSON stream row → its framed twin (``image_b64`` and the
    resume-token ``state_b64`` become tensor frames; geometry comes
    from the row's own wire fields)."""
    import base64

    import numpy as np

    out = dict(row)
    out["wire"] = "frames"
    arrays = {}
    b64 = out.pop("image_b64", None)
    shape = out.pop("image_shape", None)
    if b64 is not None:
        flat = np.frombuffer(base64.b64decode(b64), np.uint8)
        arrays["image"] = (flat.reshape([int(v) for v in shape])
                           if shape else flat)
    s64 = out.pop("state_b64", None)
    sshape = out.pop("state_shape", None)
    if s64 is not None:
        sflat = np.frombuffer(base64.b64decode(s64), np.float32)
        arrays["state"] = (sflat.reshape([int(v) for v in sshape])
                           if sshape else sflat)
    return frames_mod.encode_envelope(out, arrays)
