"""Warm-executable engine: compile once per key, serve many times.

A one-shot entry point (CLI ``run``, ``bench.py``) pays trace + compile +
mesh setup on every invocation; a service must pay them once per
*configuration* and amortize across the request stream — the serving
analogue of persistent MPI channels (PAPERS.md "Persistent and
Partitioned MPI for Stencil Communication": set the communication/compute
schedule up once, reuse it for every iteration).

Design points:

* **Key = full compile identity.**  :class:`EngineKey` carries everything
  that changes the compiled program: per-request image shape, filter,
  storage dtype, iteration count, fuse, boundary, quantize, requested
  backend — plus the engine's mesh grid.  Two requests with equal keys
  are guaranteed to share an executable (and therefore to be safely
  micro-batchable).
* **LRU eviction.**  The cache holds at most ``capacity`` keys; touching
  a key refreshes it.  Eviction drops the engine's reference (the
  underlying ``parallel.step._build_iterate`` lru_cache may briefly keep
  the jitted runner alive; that cache is bounded too).
* **Per-key single-flight.**  A cold key compiles exactly once no matter
  how many threads ask for it concurrently: one leader compiles, the
  rest wait on the in-flight event — a thundering herd of identical cold
  requests can never stampede the compiler.
* **Degradation per key.**  With ``fallback=True`` (the serving default)
  the requested backend is resolved through the resilience ladder
  (``resilience.degrade``: probe once, walk pallas_rdma → pallas →
  shifted on classified-transient compile faults); the entry records the
  ``effective_backend`` that every response is stamped with.

Batched execution stacks B same-key images on the leading dim and folds
them into the plane axis — ``(B, C, H, W) → (B*C, H, W)`` — which is the
framework's established data-parallel tier (``ConvolutionModel.run_images``
concatenates planes the same way; SURVEY.md §2: DP "falls out free"
because every plane is independent in the stencil).  The fold is exactly
a vmap of the prepared per-image step over the stacked dim, realized on
the axis the compiled runner already treats as batch — so batched bytes
are identical to sequential single-request bytes by construction, which
``tests/test_serving.py`` asserts per backend.
"""

from __future__ import annotations

import dataclasses
import threading
from collections import OrderedDict

import numpy as np

from parallel_convolution_tpu.ops.filters import get_filter
from parallel_convolution_tpu.utils.config import (
    BACKENDS, BOUNDARIES, STORAGES,
)
from parallel_convolution_tpu.utils.tracing import PhaseTimer

__all__ = ["EngineKey", "WarmEngine"]


@dataclasses.dataclass(frozen=True)
class EngineKey:
    """The compile identity of one servable configuration.

    ``shape`` is ONE request's planar image shape (C, H, W); the batch
    dim is not part of the key — executables per batch size live inside
    the key's cache entry.  ``grid`` pins the mesh the executable was
    built for, so an engine restarted on different hardware can never
    alias a stale key.
    """

    shape: tuple[int, int, int]      # (C, H, W) of one request
    filter_name: str = "blur3"
    storage: str = "f32"
    iters: int = 1
    fuse: int = 1
    boundary: str = "zero"
    quantize: bool = True
    backend: str = "shifted"         # requested; the entry records effective
    grid: tuple[int, int] = (1, 1)   # mesh grid (rows, cols)

    def validate(self) -> None:
        """Terminal (ValueError) on any out-of-registry field — the typed
        ``Rejected("invalid")`` the service returns comes from here."""
        get_filter(self.filter_name)  # raises on unknown names
        if self.backend not in BACKENDS:
            raise ValueError(f"unknown backend {self.backend!r}")
        if self.storage not in STORAGES:
            raise ValueError(f"unknown storage {self.storage!r}")
        if self.boundary not in BOUNDARIES:
            raise ValueError(f"unknown boundary {self.boundary!r}")
        if self.storage == "u8" and not self.quantize:
            raise ValueError("storage='u8' requires quantize=True")
        if len(self.shape) != 3 or min(self.shape) < 1:
            raise ValueError(f"bad planar shape {self.shape}")
        if self.iters < 1 or self.fuse < 1:
            raise ValueError("iters and fuse must be >= 1")


class _Entry:
    """One warm key: resolved backend + compiled runners per batch size."""

    __slots__ = ("key", "effective_backend", "fns", "lock")

    def __init__(self, key: EngineKey, effective_backend: str):
        self.key = key
        self.effective_backend = effective_backend
        self.fns: dict[int, object] = {}   # batch size -> jitted runner
        self.lock = threading.Lock()       # per-batch-size build flight


class _InFlight:
    """A cold key's compilation in progress: leader fills, waiters wait."""

    __slots__ = ("event", "entry", "error")

    def __init__(self):
        self.event = threading.Event()
        self.entry: _Entry | None = None
        self.error: BaseException | None = None


class WarmEngine:
    """Warm-executable cache over ``parallel.step`` for a fixed mesh."""

    def __init__(self, mesh=None, capacity: int = 16, fallback: bool = True):
        from parallel_convolution_tpu.parallel.mesh import make_grid_mesh

        self.mesh = mesh if mesh is not None else make_grid_mesh()
        self.capacity = max(1, int(capacity))
        self.fallback = fallback
        self._lock = threading.Lock()
        self._entries: OrderedDict[EngineKey, _Entry] = OrderedDict()
        self._inflight: dict[EngineKey, _InFlight] = {}
        self.stats = {
            "hits": 0, "misses": 0, "compiles": 0, "evictions": 0,
            "single_flight_waits": 0, "batches": 0, "images": 0,
        }

    # -- key construction ---------------------------------------------------
    def key_for(self, shape, **kw) -> EngineKey:
        """An :class:`EngineKey` for this engine's mesh; clamps fuse the
        way ``_build_iterate`` will, so equal executables get equal keys."""
        from parallel_convolution_tpu.parallel.mesh import grid_shape

        key = EngineKey(shape=tuple(int(s) for s in shape),
                        grid=grid_shape(self.mesh), **kw)
        if key.fuse > max(1, key.iters):
            key = dataclasses.replace(key, fuse=max(1, key.iters))
        return key

    # -- entry acquisition (LRU + single-flight) ----------------------------
    def entry(self, key: EngineKey) -> _Entry:
        """The warm entry for ``key``; compiles (single-flight) when cold."""
        while True:
            with self._lock:
                e = self._entries.get(key)
                if e is not None:
                    self._entries.move_to_end(key)
                    self.stats["hits"] += 1
                    return e
                fl = self._inflight.get(key)
                if fl is None:
                    fl = _InFlight()
                    self._inflight[key] = fl
                    self.stats["misses"] += 1
                    leader = True
                else:
                    self.stats["single_flight_waits"] += 1
                    leader = False
            if not leader:
                fl.event.wait()
                if fl.error is not None:
                    raise fl.error
                # The leader landed the entry; loop to take the hit path
                # (or recompile if an eviction already dropped it).
                with self._lock:
                    e = self._entries.get(key)
                    if e is not None:
                        self._entries.move_to_end(key)
                        return e
                continue
            try:
                entry = self._build_entry(key)
            except BaseException as err:
                fl.error = err
                with self._lock:
                    self._inflight.pop(key, None)
                fl.event.set()
                raise
            with self._lock:
                self._entries[key] = entry
                self._entries.move_to_end(key)
                while len(self._entries) > self.capacity:
                    self._entries.popitem(last=False)
                    self.stats["evictions"] += 1
                self._inflight.pop(key, None)
            fl.event.set()
            return entry

    def _build_entry(self, key: EngineKey) -> _Entry:
        """Resolve the backend (degradation ladder) and compile batch=1.

        Runs OUTSIDE the engine lock (compiles take seconds on real
        chips); the single-flight record keeps concurrent cold callers
        from duplicating the work.
        """
        key.validate()
        effective = key.backend
        if self.fallback:
            from parallel_convolution_tpu.resilience import degrade

            effective = degrade.resolve_backend(
                self.mesh, get_filter(key.filter_name), key.backend,
                quantize=key.quantize, fuse=key.fuse, boundary=key.boundary,
                storage=key.storage, block_hw=self._block_hw(key))
        entry = _Entry(key, effective)
        self._compile_batch(entry, 1)
        return entry

    def _block_hw(self, key: EngineKey) -> tuple[int, int]:
        from parallel_convolution_tpu.parallel.mesh import padded_extent

        (_, H, W), (R, C) = key.shape, key.grid
        return (padded_extent(H, R) // R, padded_extent(W, C) // C)

    def _compile_batch(self, entry: _Entry, batch: int):
        """The jitted runner for ``batch`` stacked requests of this key."""
        with entry.lock:
            fn = entry.fns.get(batch)
            if fn is not None:
                return fn
            from parallel_convolution_tpu.parallel import step as step_lib

            key = entry.key
            C, H, W = key.shape
            filt = get_filter(key.filter_name)
            # Folded leading dim: batch × channels independent planes.
            probe = np.zeros((batch * C, H, W), np.float32)
            xs, valid_hw, block_hw = step_lib._prepare(
                probe, self.mesh, filt.radius, key.storage)
            fn = step_lib._build_iterate(
                self.mesh, filt, key.iters, key.quantize, valid_hw,
                block_hw, entry.effective_backend, key.fuse, key.boundary,
                None, False)
            # Trace + XLA-compile NOW (jit compiles on first call): warm
            # means the request path never sees compilation.
            import jax

            jax.block_until_ready(fn(xs))
            entry.fns[batch] = fn
            with self._lock:
                self.stats["compiles"] += 1
            return fn

    # -- warmup -------------------------------------------------------------
    def warmup(self, keys) -> list[str]:
        """Pre-compile declared configs (batch size 1); returns the
        effective backend per key, in order."""
        return [self.entry(k).effective_backend for k in keys]

    # -- execution ----------------------------------------------------------
    def run_batch(self, key: EngineKey, images: np.ndarray,
                  timer: PhaseTimer | None = None):
        """Run ``images`` (B, C, H, W) f32 through the warm executable.

        Returns ``(out, info)``: ``out`` is (B, C, H, W) float32 with the
        valid extent restored, ``info`` carries ``effective_backend`` and
        the compile/copy_in/device/copy_out phase walls (seconds) from
        ``timer`` (a fresh :class:`PhaseTimer` when not supplied — the
        serving latency breakdown reuses its ``to_row`` export).
        """
        import jax
        import jax.numpy as jnp

        from parallel_convolution_tpu.parallel import step as step_lib

        t = timer or PhaseTimer()
        B, C, H, W = images.shape
        if (C, H, W) != key.shape:
            raise ValueError(
                f"batch shape {(C, H, W)} does not match key {key.shape}")
        with t.phase("compile"):
            entry = self.entry(key)
            fn = entry.fns.get(B) or self._compile_batch(entry, B)
        filt = get_filter(key.filter_name)
        with t.phase("copy_in"):
            folded = np.ascontiguousarray(
                images.reshape(B * C, H, W).astype(np.float32))
            xs, valid_hw, _ = step_lib._prepare(
                folded, self.mesh, filt.radius, key.storage)
            jax.block_until_ready(xs)
        with t.phase("device"):
            out = fn(xs)
            jax.block_until_ready(out)
        with t.phase("copy_out"):
            out = np.asarray(
                out[:, : valid_hw[0], : valid_hw[1]].astype(jnp.float32))
            out = out.reshape(B, C, H, W)
        with self._lock:
            self.stats["batches"] += 1
            self.stats["images"] += B
        info = {
            "effective_backend": entry.effective_backend,
            "batch_size": B,
            "phases": {name: t.wall(name)
                       for name in ("compile", "copy_in", "device",
                                    "copy_out")},
        }
        return out, info

    # -- introspection ------------------------------------------------------
    def snapshot(self) -> dict:
        """Stats + resident keys, for /stats and the loadgen row."""
        with self._lock:
            return {
                "stats": dict(self.stats),
                "capacity": self.capacity,
                "resident": [
                    {"filter": k.filter_name, "shape": list(k.shape),
                     "backend": k.backend,
                     "effective_backend": e.effective_backend,
                     "batch_sizes": sorted(e.fns)}
                    for k, e in self._entries.items()
                ],
            }
