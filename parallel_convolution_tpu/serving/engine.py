"""Warm-executable engine: compile once per key, serve many times.

A one-shot entry point (CLI ``run``, ``bench.py``) pays trace + compile +
mesh setup on every invocation; a service must pay them once per
*configuration* and amortize across the request stream — the serving
analogue of persistent MPI channels (PAPERS.md "Persistent and
Partitioned MPI for Stencil Communication": set the communication/compute
schedule up once, reuse it for every iteration).

Design points:

* **Key = full compile identity.**  :class:`EngineKey` carries everything
  that changes the compiled program: per-request image shape, filter,
  storage dtype, iteration count, fuse, boundary, quantize, requested
  backend — plus the engine's mesh grid.  Two requests with equal keys
  are guaranteed to share an executable (and therefore to be safely
  micro-batchable).
* **LRU eviction.**  The cache holds at most ``capacity`` keys; touching
  a key refreshes it.  Eviction drops the engine's reference (the
  underlying ``parallel.step._build_iterate`` lru_cache may briefly keep
  the jitted runner alive; that cache is bounded too).
* **Per-key single-flight.**  A cold key compiles exactly once no matter
  how many threads ask for it concurrently: one leader compiles, the
  rest wait on the in-flight event — a thundering herd of identical cold
  requests can never stampede the compiler.
* **Degradation per key.**  With ``fallback=True`` (the serving default)
  the requested backend is resolved through the resilience ladder
  (``resilience.degrade``: probe once, walk pallas_rdma → pallas →
  shifted on classified-transient compile faults); the entry records the
  ``effective_backend`` that every response is stamped with.

Batched execution stacks B same-key images on the leading dim and folds
them into the plane axis — ``(B, C, H, W) → (B*C, H, W)`` — which is the
framework's established data-parallel tier (``ConvolutionModel.run_images``
concatenates planes the same way; SURVEY.md §2: DP "falls out free"
because every plane is independent in the stencil).  The fold is exactly
a vmap of the prepared per-image step over the stacked dim, realized on
the axis the compiled runner already treats as batch — so batched bytes
are identical to sequential single-request bytes by construction, which
``tests/test_serving.py`` asserts per backend.
"""

from __future__ import annotations

import dataclasses
import threading
from collections import OrderedDict

import numpy as np

from parallel_convolution_tpu.obs import (
    events as obs_events, metrics as obs_metrics, trace as obs_trace,
)
from parallel_convolution_tpu.ops.filters import get_filter
from parallel_convolution_tpu.utils.config import (
    BACKENDS, BOUNDARIES, SOLVERS, STORAGES,
)
from parallel_convolution_tpu.utils.tracing import PhaseTimer

__all__ = ["EngineKey", "WarmEngine", "bucket_extent", "bucket_key"]


@dataclasses.dataclass(frozen=True)
class EngineKey:
    """The compile identity of one servable configuration.

    ``shape`` is ONE request's planar image shape (C, H, W); the batch
    dim is not part of the key — executables per batch size live inside
    the key's cache entry.  ``grid`` pins the mesh the executable was
    built for, so an engine restarted on different hardware can never
    alias a stale key.
    """

    shape: tuple[int, int, int]      # (C, H, W) of one request
    filter_name: str = "blur3"
    storage: str = "f32"
    iters: int = 1
    fuse: int = 1
    boundary: str = "zero"
    quantize: bool = True
    backend: str = "shifted"         # requested; the entry records effective
    grid: tuple[int, int] = (1, 1)   # mesh grid (rows, cols)
    tile: tuple[int, int] | None = None  # Pallas kernel tile (None=default)
    overlap: bool = False            # RESOLVED interior-first overlapped
    #                                  halo pipeline knob (resolve_key
    #                                  settles None/auto before keying, so
    #                                  equal executables share one key)
    col_mode: str = "packed"         # RESOLVED column-slab transport
    #                                  (packed | strided) — same pre-keying
    #                                  rule as overlap/backend: auto and
    #                                  explicit requests that compile the
    #                                  same program share one warm
    #                                  executable
    solver: str = "jacobi"           # convergence strategy (SOLVERS):
    #                                  "multigrid" keys the V-cycle's
    #                                  compiled level programs (converge
    #                                  jobs only — the batch path is
    #                                  solver-less and rejects it)
    mg_levels: int | None = None     # multigrid level-count cap (part of
    #                                  the compile identity: it changes
    #                                  the level schedule)
    rank: int = 2                    # stencil rank (utils.config.RANKS):
    #                                  rank=3 keys a VOLUME config —
    #                                  ``shape`` is then (D, H, W) of one
    #                                  two-field volume, ``filter_name``
    #                                  a registered rank-3 form, and the
    #                                  executables come from
    #                                  volumes.driver instead of
    #                                  parallel.step

    def validate(self) -> None:
        """Terminal (ValueError) on any out-of-registry field — the typed
        ``Rejected("invalid")`` the service returns comes from here.

        ``backend="auto"`` never reaches here: :meth:`WarmEngine.key_for`
        resolves it to a concrete tier first, so two requests that tune
        to the same program share one key (and one executable)."""
        from parallel_convolution_tpu.utils.config import RANKS

        if self.rank not in RANKS:
            raise ValueError(f"rank must be one of {RANKS}, "
                             f"got {self.rank}")
        if self.rank == 3:
            self._validate_volume()
            return
        get_filter(self.filter_name)  # raises on unknown names
        if self.backend not in BACKENDS:
            raise ValueError(f"unknown backend {self.backend!r} (auto is "
                             "resolved in key_for, never stored in a key)")
        if self.storage not in STORAGES:
            raise ValueError(f"unknown storage {self.storage!r}")
        if self.boundary not in BOUNDARIES:
            raise ValueError(f"unknown boundary {self.boundary!r}")
        if self.storage == "u8" and not self.quantize:
            raise ValueError("storage='u8' requires quantize=True")
        if len(self.shape) != 3 or min(self.shape) < 1:
            raise ValueError(f"bad planar shape {self.shape}")
        if self.iters < 1 or self.fuse < 1:
            raise ValueError("iters and fuse must be >= 1")
        if self.tile is not None and (
                len(self.tile) != 2 or min(self.tile) < 1):
            raise ValueError(f"tile must be two positive ints, "
                             f"got {self.tile}")
        from parallel_convolution_tpu.parallel import channels

        if self.col_mode not in channels.COL_MODES:
            raise ValueError(
                f"unknown col_mode {self.col_mode!r} (auto is resolved "
                "in key_for, never stored in a key)")
        if self.solver not in SOLVERS:
            raise ValueError(f"unknown solver {self.solver!r}")
        if self.mg_levels is not None and int(self.mg_levels) < 1:
            raise ValueError(f"mg_levels must be >= 1, got {self.mg_levels}")
        if self.solver == "multigrid":
            # V-cycle residual/correction fields are signed floats — a
            # u8 store-back would clamp the error equation to garbage.
            if self.quantize:
                raise ValueError("solver='multigrid' requires "
                                 "quantize=False")
            if self.storage != "f32":
                raise ValueError("solver='multigrid' requires "
                                 "storage='f32'")

    def _validate_volume(self) -> None:
        """Rank-3 key constraints.  ``shape`` is (D, H, W) of one
        two-field volume; ``filter_name`` must resolve in the rank-3
        registry (raises with the registered names on a miss).  Volumes
        are float fields end to end, serve on the registry path (no
        backend ladder, no Pallas tier, no overlap pipeline), and
        converge through the chunked-jacobi driver."""
        from parallel_convolution_tpu.parallel import (
            kernels as kernel_forms,
        )

        if self.boundary not in BOUNDARIES:
            raise ValueError(f"unknown boundary {self.boundary!r}")
        kernel_forms.resolve(3, self.filter_name, self.boundary)
        if self.backend not in BACKENDS:
            raise ValueError(f"unknown backend {self.backend!r}")
        if len(self.shape) != 3 or min(self.shape) < 1:
            raise ValueError(f"bad volume shape {self.shape} "
                             "(want (D, H, W))")
        if self.iters < 1 or self.fuse < 1:
            raise ValueError("iters and fuse must be >= 1")
        if self.quantize or self.storage != "f32":
            raise ValueError("rank-3 volumes are float fields: "
                             "storage='f32' and quantize=False required")
        if self.solver != "jacobi":
            raise ValueError("rank-3 convergence is the chunked-jacobi "
                             f"driver; solver={self.solver!r} is rank-2 "
                             "only")
        if self.tile is not None or self.overlap:
            raise ValueError("rank-3 keys have no kernel tile or "
                             "overlapped-halo form")
        if self.col_mode != "packed":
            raise ValueError("rank-3 keys use the canonical 'packed' "
                             "column transport label")


# Shape-bucket extent ladder for lane co-batching: dense at thumbnail
# sizes (where request mixes cluster), sparse above, capped pad waste
# (~1.33x worst-case per dim between rungs).
_BUCKET_LADDER = (8, 16, 32, 48, 64, 96, 128, 192, 256, 384, 512, 768,
                  1024, 1280, 1536, 1920, 2048, 2560, 3072, 4096)


def bucket_extent(v: int) -> int:
    """Round one spatial extent UP to its lane bucket rung.

    Rounding up (never down) keeps every geometry-derived validity
    check (block >= radius*fuse, halo fits) at least as satisfied for
    the bucket as for the original extent.  Above the ladder, round up
    to the next multiple of the top rung spacing."""
    v = int(v)
    for rung in _BUCKET_LADDER:
        if v <= rung:
            return rung
    step = 1024
    return ((v + step - 1) // step) * step


def bucket_key(key):
    """The LANE a key batches under — ``key`` itself when pad-to-bucket
    co-batching cannot be proven byte-identical.

    Zero-padding the (H, W) margin is results-invariant ONLY for one
    Jacobi iteration under zero boundaries: the padded region is zero,
    one pointwise stencil application over a zero-margin image writes
    the same interior bytes as the unpadded program (per-pixel
    shifted-add summation order does not depend on extent), and the
    crop discards the rest.  Reflect/edge boundaries read the margin,
    and iters > 1 propagates it inward — those keys get a degenerate
    exact-key lane (same behavior as before this round).
    """
    if not isinstance(key, EngineKey):
        return key
    if key.rank != 2:
        # A rank-3 volume's zero-pad margin changes the stencil's D-face
        # geometry reading, and co-batching across (D, H, W) shapes was
        # never proven byte-identical — volumes get exact-key lanes.
        return key
    if key.iters != 1 or key.boundary != "zero" or key.solver != "jacobi":
        return key
    c, h, w = key.shape
    bh, bw = bucket_extent(h), bucket_extent(w)
    if (bh, bw) == (h, w):
        return key
    return dataclasses.replace(key, shape=(c, bh, bw))


class _Entry:
    """One warm key: resolved backend + compiled runners per batch size."""

    __slots__ = ("key", "effective_backend", "fns", "lock", "plan_source",
                 "predicted_gpx", "plan_key", "effective_overlap",
                 "effective_col_mode", "splits", "compile_ref",
                 "converge_fns", "mg_levels", "compiles")

    def __init__(self, key: EngineKey, effective_backend: str,
                 plan_source: str = "explicit",
                 predicted_gpx: float | None = None,
                 plan_key: str = ""):
        self.key = key
        self.effective_backend = effective_backend
        # The overlap knob the executables are ACTUALLY compiled with:
        # the key's resolved value, re-clamped to False when the degrade
        # walk left the RDMA tier (only that tier has an overlapped form).
        self.effective_overlap = bool(
            key.overlap) and effective_backend == "pallas_rdma"
        # Same rule for the column transport: re-clamped to the
        # canonical 'packed' when the degrade walk left the persistent
        # tier (no column RDMA transport exists elsewhere).
        from parallel_convolution_tpu.parallel import step as _step_lib

        self.effective_col_mode = _step_lib.clamp_col_mode(
            key.col_mode, effective_backend)
        self.plan_source = plan_source       # explicit|measured|
        #                                      interpolated|predicted
        self.predicted_gpx = predicted_gpx   # cost-model Gpx/s/chip
        self.plan_key = plan_key             # tuning canonical key: the
        #                                      drift series' label
        self.compile_ref: dict | None = None  # the single-flight leader's
        #                                      compile_build span ref —
        #                                      waiters (and reports) link
        #                                      to WHO paid for the compile
        self.mg_levels: int | None = None  # multigrid keys: the level
        #                                    count the planner ACTUALLY
        #                                    scheduled (resolved at the
        #                                    first converge stream; the
        #                                    post-resolution stamp rows
        #                                    carry — never the cap)
        self.compiles = 0   # executables built FOR THIS KEY (batch sizes
        #                     + converge chunks) — the per-shard compile
        #                     ledger the warm-placement gate reads: a
        #                     pre-warmed joining replica's shard keys
        #                     must hold this flat through the remapped
        #                     traffic that follows ring join.
        self.fns: dict[int, object] = {}   # batch size -> jitted runner
        self.converge_fns: dict[int, object] = {}  # chunk length n ->
        #                                    jitted convergence chunk
        #                                    (run_converge's progressive
        #                                    executables; n varies only on
        #                                    the final short chunk)
        self.splits: dict[int, dict] = {}  # batch size -> exchange split
        #                                    (pure model math, cached off
        #                                    the per-request hot path;
        #                                    batch-dependent only via the
        #                                    RDMA tiled-kernel switch)
        self.lock = threading.Lock()       # per-batch-size build flight


class _InFlight:
    """A cold key's compilation in progress: leader fills, waiters wait."""

    __slots__ = ("event", "entry", "error", "span_ref")

    def __init__(self):
        self.event = threading.Event()
        self.entry: _Entry | None = None
        self.error: BaseException | None = None
        self.span_ref: dict | None = None  # leader's compile_build span


class WarmEngine:
    """Warm-executable cache over ``parallel.step`` for a fixed mesh."""

    def __init__(self, mesh=None, capacity: int = 16, fallback: bool = True,
                 plans=None):
        from parallel_convolution_tpu.parallel.mesh import make_grid_mesh

        self.mesh = mesh if mesh is not None else make_grid_mesh()
        self.capacity = max(1, int(capacity))
        self.fallback = fallback
        # The plan cache backend="auto" keys resolve through: a
        # tuning.PlanCache, a path to a plan file, or None (ambient
        # PCTPU_PLAN_FILE, else pure cost model).
        if isinstance(plans, str):
            from parallel_convolution_tpu.tuning import PlanCache

            plans = PlanCache.load(plans)
        self.plans = plans
        self._lock = threading.Lock()
        self._entries: OrderedDict[EngineKey, _Entry] = OrderedDict()
        self._inflight: dict[EngineKey, _InFlight] = {}
        # Resolution provenance per auto-resolved key (stamped into the
        # entry at build time; explicit keys default to 'explicit').
        self._plan_sources: dict[EngineKey, str] = {}
        # The legacy stats dict, now a view over the obs registry: every
        # write mirrors into pctpu_engine_stats{key=...} (obs.metrics).
        self.stats = obs_metrics.MirroredStats(obs_metrics.gauge(
            "pctpu_engine_stats", "warm-engine cache/execution counters",
            ("key",)), initial={
            "hits": 0, "misses": 0, "compiles": 0, "evictions": 0,
            "single_flight_waits": 0, "batches": 0, "images": 0,
            "reshapes": 0,
        })

    def grid(self) -> tuple[int, int]:
        from parallel_convolution_tpu.parallel.mesh import grid_shape

        return grid_shape(self.mesh)

    # -- elastic recovery ---------------------------------------------------
    def reshape(self, mesh) -> dict:
        """Re-bind the engine onto a different mesh MID-PROCESS.

        The serve-through-shrink leg: drop every warm entry (they were
        compiled for the old grid), swap the mesh, and re-warm the
        previously-resident keys on the new grid — so the first request
        after a shrink hits a warm executable, not a cold compile.  A key
        whose image cannot fit the new grid (block < radius*fuse) is
        SKIPPED with a warning, never fatal: serve-through-shrink must
        not die because one tiny config has no home on the smaller mesh.

        The caller must quiesce execution first — the service drains its
        batcher before calling this (``ConvolutionService.reshape``), so
        no in-flight ``run_batch`` can straddle the swap; a stale-grid
        key reaching :meth:`run_batch` afterwards raises (terminal), it
        can never silently run on the wrong decomposition.
        """
        import warnings

        from parallel_convolution_tpu.parallel.mesh import grid_shape

        new_grid = grid_shape(mesh)
        with self._lock:
            old_grid = self.grid()
            old_keys = list(self._entries)
            self._entries.clear()
            self._plan_sources.clear()
            self._inflight.clear()
            self.mesh = mesh
            self.stats["reshapes"] += 1
        rewarmed, skipped = [], []
        for k in old_keys:
            nk = dataclasses.replace(k, grid=new_grid)
            try:
                self.entry(nk)
                rewarmed.append(nk)
            except Exception as e:  # noqa: BLE001 — per-key, never fatal
                skipped.append((nk, repr(e)[:200]))
                warnings.warn(
                    f"reshape: key {k.filter_name}/{k.shape} has no home "
                    f"on grid {new_grid}: {e}", stacklevel=2)
        info = {
            "old_grid": old_grid, "grid": new_grid,
            "rewarmed": len(rewarmed), "skipped": len(skipped),
        }
        if obs_metrics.enabled():
            obs_events.emit(
                "reshape", old_grid=f"{old_grid[0]}x{old_grid[1]}",
                grid=f"{new_grid[0]}x{new_grid[1]}",
                rewarmed=info["rewarmed"], skipped=info["skipped"])
        return info

    # -- key construction ---------------------------------------------------
    def resolve_key(self, shape, **kw) -> tuple[EngineKey, str]:
        """``(EngineKey, plan_source)`` for this engine's mesh; clamps
        fuse the way ``_build_iterate`` will, so equal executables get
        equal keys.

        ``backend="auto"`` (with ``fuse=None``/``tile=None`` meaning
        'tune it') resolves through the tuning subsystem HERE — against
        this engine's plan cache — so an auto request and an explicit
        request for the tuned config produce the SAME key and share one
        warm executable.  ``plan_source`` is THIS call's provenance
        ('explicit' for named configs): responses must stamp per-request
        provenance, because an auto and an explicit request can share a
        key (and an entry) while having different origins.
        """
        from parallel_convolution_tpu.parallel.mesh import grid_shape

        from parallel_convolution_tpu.parallel import step as step_lib

        kw = dict(kw)
        plan_source = "explicit"
        if int(kw.get("rank", 2)) == 3:
            # Volumes have no tuning space (one registry path, no tile,
            # no overlap, no column A/B): "auto" normalizes to the
            # canonical shifted label and the knobs to their clamped
            # values, so every spelling of a volume config shares one
            # key.  Everything else is validated by the key itself.
            if kw.get("backend") in (None, "auto"):
                kw["backend"] = "shifted"
            kw["overlap"] = bool(kw.get("overlap") or False)
            kw["col_mode"] = ("packed" if kw.get("col_mode") in
                              (None, "auto") else kw["col_mode"])
            kw["fuse"] = max(1, int(kw.get("fuse") or 1))
            key = EngineKey(shape=tuple(int(s) for s in shape),
                            grid=grid_shape(self.mesh), **kw)
            if key.fuse > max(1, key.iters):
                key = dataclasses.replace(key, fuse=max(1, key.iters))
            return key, "explicit"
        if kw.get("backend") == "auto":
            from parallel_convolution_tpu import tuning

            res = tuning.resolve(
                self.mesh, get_filter(kw.get("filter_name", "blur3")),
                tuple(int(s) for s in shape),
                storage=kw.get("storage", "f32"),
                quantize=bool(kw.get("quantize", True)),
                boundary=kw.get("boundary", "zero"),
                fuse=kw.get("fuse"), tile=kw.get("tile"),
                overlap=kw.get("overlap"),
                col_mode=kw.get("col_mode"),
                plans=self.plans)
            kw["backend"] = res.backend
            kw["fuse"], kw["tile"] = res.fuse, res.tile
            kw["overlap"] = res.overlap
            kw["col_mode"] = res.col_mode
            plan_source = res.source
        # Settle the overlap knob BEFORE keying (None -> False for
        # explicit backends; requests clamped to the RDMA tier and the
        # interpret guard) — two requests that compile the same program
        # must share one key, and the key must state the compiled form.
        kw["overlap"] = step_lib.resolve_overlap(
            kw.get("overlap"), kw.get("backend", "shifted"), self.mesh)
        # Same pre-keying rule for the column transport: None/'auto'
        # resolve through the cost model for the persistent tier and
        # normalize to the canonical 'packed' everywhere else, so an
        # auto and an explicit request that compile the same program
        # share one warm executable.
        from parallel_convolution_tpu.parallel.mesh import (
            grid_shape as _grid_shape, padded_extent as _padded_extent,
        )

        (_, _H, _W) = tuple(int(s) for s in shape)
        _R, _C = _grid_shape(self.mesh)
        _filt = get_filter(kw.get("filter_name", "blur3"))
        kw["col_mode"] = step_lib.resolve_col_mode(
            kw.get("col_mode"), kw.get("backend", "shifted"), self.mesh,
            (_padded_extent(_H, _R) // _R, _padded_extent(_W, _C) // _C),
            _filt.radius, max(1, int(kw.get("fuse") or 1)),
            kw.get("storage", "f32"))
        if kw.get("fuse") is None and "fuse" in kw:
            # Same contract as RunConfig/ConvolutionModel: fuse=None
            # means 'tune it', which needs backend='auto' — silently
            # running an explicit backend at fuse=1 would accept here
            # what every other entry point rejects as invalid.
            raise ValueError(
                "fuse=None means 'tune it' and needs backend='auto'")
        if kw.get("tile") is not None:
            kw["tile"] = tuple(int(v) for v in kw["tile"])
        key = EngineKey(shape=tuple(int(s) for s in shape),
                        grid=grid_shape(self.mesh), **kw)
        if key.fuse > max(1, key.iters):
            key = dataclasses.replace(key, fuse=max(1, key.iters))
        if plan_source != "explicit":
            with self._lock:
                self._plan_sources[key] = plan_source
                # Bounded independently of _entries: keys stamped here can
                # be rejected before any entry exists (queue_full, block
                # validation), so LRU eviction alone would never trim
                # them — adversarially varied auto traffic must not grow
                # this side table forever.  FIFO is fine: a dropped note
                # is re-stamped by the next resolve_key for that key.
                limit = max(64, 4 * self.capacity)
                while len(self._plan_sources) > limit:
                    self._plan_sources.pop(next(iter(self._plan_sources)))
        return key, plan_source

    def key_for(self, shape, **kw) -> EngineKey:
        """:meth:`resolve_key` without the provenance (compat surface)."""
        return self.resolve_key(shape, **kw)[0]

    # -- entry acquisition (LRU + single-flight) ----------------------------
    def entry(self, key: EngineKey) -> _Entry:
        """The warm entry for ``key``; compiles (single-flight) when cold."""
        while True:
            with self._lock:
                e = self._entries.get(key)
                if e is not None:
                    self._entries.move_to_end(key)
                    self.stats["hits"] += 1
                    return e
                fl = self._inflight.get(key)
                if fl is None:
                    fl = _InFlight()
                    self._inflight[key] = fl
                    self.stats["misses"] += 1
                    leader = True
                else:
                    self.stats["single_flight_waits"] += 1
                    leader = False
            if not leader:
                fl.event.wait()
                if fl.error is not None:
                    raise fl.error
                # Single-flight attribution (obs.trace): this thread did
                # not compile — link the LEADER's compile_build span onto
                # our enclosing span (run_batch's compile phase), so the
                # trace report can tell who paid and who drafted.
                if fl.span_ref is not None:
                    obs_trace.add_link(fl.span_ref, kind="single_flight")
                # The leader landed the entry; loop to take the hit path
                # (or recompile if an eviction already dropped it).
                with self._lock:
                    e = self._entries.get(key)
                    if e is not None:
                        self._entries.move_to_end(key)
                        return e
                continue
            try:
                with obs_trace.span(
                        "compile_build", backend=key.backend,
                        filter=key.filter_name, fuse=key.fuse,
                        shape=list(key.shape)) as bsp:
                    entry = self._build_entry(key)
                    entry.compile_ref = fl.span_ref = bsp.ref
            except BaseException as err:
                fl.error = err
                with self._lock:
                    self._inflight.pop(key, None)
                fl.event.set()
                raise
            with self._lock:
                self._entries[key] = entry
                self._entries.move_to_end(key)
                while len(self._entries) > self.capacity:
                    old_key, _ = self._entries.popitem(last=False)
                    # Drop the provenance note too (re-resolved on the
                    # next auto key_for); keeps the side table bounded.
                    self._plan_sources.pop(old_key, None)
                    self.stats["evictions"] += 1
                self._inflight.pop(key, None)
            fl.event.set()
            return entry

    def _build_entry(self, key: EngineKey) -> _Entry:
        """Resolve the backend (degradation ladder) and compile batch=1.

        Runs OUTSIDE the engine lock (compiles take seconds on real
        chips); the single-flight record keeps concurrent cold callers
        from duplicating the work.
        """
        key.validate()
        if key.rank == 3:
            # No degrade walk and no tuning Workload: the volume path is
            # one registry program per (form, boundary) — there is no
            # lower tier to fall to, and a fault in it is terminal by
            # design.  The cost-model stamp comes from the rank-3
            # roofline so predicted-vs-measured visibility survives.
            from parallel_convolution_tpu.tuning import costmodel
            from parallel_convolution_tpu.utils.config import (
                VOLUME_FIELDS, VOLUME_RADII,
            )

            dev0 = self.mesh.devices.flat[0]
            hw = costmodel.hardware_for(
                dev0.platform, getattr(dev0, "device_kind", "") or "")
            D = key.shape[0]
            predicted = costmodel.predict_gpx_per_chip(
                costmodel.predict_volume_seconds_per_cell_iter(
                    key.grid, self._block_hw(key), D,
                    VOLUME_RADII[key.filter_name], key.fuse,
                    key.filter_name, hw, fields=VOLUME_FIELDS))
            plan_key = (f"vol|{key.filter_name}|{key.shape[0]}x"
                        f"{key.shape[1]}x{key.shape[2]}|{key.boundary}"
                        f"|grid={key.grid[0]}x{key.grid[1]}")
            entry = _Entry(key, key.backend, plan_source="explicit",
                           predicted_gpx=round(predicted, 3),
                           plan_key=plan_key)
            self._compile_batch(entry, 1)
            return entry
        effective = key.backend
        if self.fallback:
            from parallel_convolution_tpu.resilience import degrade

            effective = degrade.resolve_backend(
                self.mesh, get_filter(key.filter_name), key.backend,
                quantize=key.quantize, fuse=key.fuse, boundary=key.boundary,
                tile=key.tile, storage=key.storage,
                block_hw=self._block_hw(key), overlap=key.overlap,
                col_mode=key.col_mode)
        # Cost-model figure for the config actually compiled: every
        # response carries predicted-vs-measured visibility, so a silent
        # mistune (or a degraded tier) shows in per-request artifacts.
        from parallel_convolution_tpu.tuning import costmodel, search
        from parallel_convolution_tpu.tuning.plans import Workload

        w = Workload.from_mesh(self.mesh, get_filter(key.filter_name),
                               key.shape, storage=key.storage,
                               quantize=key.quantize,
                               boundary=key.boundary)
        predicted = costmodel.predict_gpx_per_chip(search.predict(
            w, search.Candidate(
                effective, key.fuse, key.tile,
                bool(key.overlap) and effective == "pallas_rdma",
                key.col_mode)))
        with self._lock:
            source = self._plan_sources.get(key, "explicit")
        entry = _Entry(key, effective, plan_source=source,
                       predicted_gpx=round(predicted, 3), plan_key=w.key())
        self._compile_batch(entry, 1)
        return entry

    def _block_hw(self, key: EngineKey) -> tuple[int, int]:
        from parallel_convolution_tpu.parallel.mesh import padded_extent

        (_, H, W), (R, C) = key.shape, key.grid
        return (padded_extent(H, R) // R, padded_extent(W, C) // C)

    def _compile_batch(self, entry: _Entry, batch: int):
        """The jitted runner for ``batch`` stacked requests of this key."""
        with entry.lock:
            fn = entry.fns.get(batch)
            if fn is not None:
                return fn
            if entry.key.rank == 3:
                return self._compile_volume_batch(entry, batch)
            from parallel_convolution_tpu.parallel import step as step_lib

            key = entry.key
            C, H, W = key.shape
            filt = get_filter(key.filter_name)
            # Folded leading dim: batch × channels independent planes.
            probe = np.zeros((batch * C, H, W), np.float32)
            xs, valid_hw, block_hw = step_lib._prepare(
                probe, self.mesh, filt.radius, key.storage)
            fn = step_lib._build_iterate(
                self.mesh, filt, key.iters, key.quantize, valid_hw,
                block_hw, entry.effective_backend, key.fuse, key.boundary,
                key.tile, False, entry.effective_overlap,
                entry.effective_col_mode)
            # Trace + XLA-compile NOW (jit compiles on first call): warm
            # means the request path never sees compilation.
            import jax

            jax.block_until_ready(fn(xs))
            entry.fns[batch] = fn
            entry.compiles += 1
            with self._lock:
                self.stats["compiles"] += 1
            return fn

    def _compile_volume_batch(self, entry: _Entry, batch: int):
        """The rank-3 twin of the batch compile: ``batch`` volumes fold
        their field pairs onto the leading axis — (B, F, D, H, W) →
        (B*F, D, H, W), the volume driver's interleaved-field contract —
        and the runner comes from ``volumes.driver``.  Caller holds
        ``entry.lock``."""
        import jax

        from parallel_convolution_tpu.utils.config import VOLUME_FIELDS
        from parallel_convolution_tpu.volumes import driver

        key = entry.key
        D, H, W = key.shape
        F = batch * VOLUME_FIELDS
        probe = np.zeros((F, D, H, W), np.float32)
        xs, valid_hw = driver.prepare_volume(probe, self.mesh,
                                             key.boundary)
        _, block_hw, _ = driver._geometry((F, D, H, W), self.mesh,
                                          key.boundary)
        fn = driver._build_volume_iterate(
            self.mesh, key.filter_name, key.iters, D, valid_hw,
            block_hw, key.fuse, key.boundary)
        jax.block_until_ready(fn(xs))
        entry.fns[batch] = fn
        entry.compiles += 1
        with self._lock:
            self.stats["compiles"] += 1
        return fn

    # -- warmup -------------------------------------------------------------
    def warmup(self, keys) -> list[str]:
        """Pre-compile declared configs (batch size 1); returns the
        effective backend per key, in order.

        No plan-file parameter ON PURPOSE: ``keys`` are already-resolved
        :class:`EngineKey` values, so arming the plan cache here would be
        too late to affect them (the trap is real: an auto key built
        before the plans load resolves against the cost model).  Arm
        ``self.plans`` (constructor ``plans=``, or
        ``ConvolutionService.warmup(plan_file=...)`` which loads BEFORE
        building keys) and then call this.
        """
        return [self.entry(k).effective_backend for k in keys]

    # -- execution ----------------------------------------------------------
    def run_batch(self, key: EngineKey, images: np.ndarray,
                  timer: PhaseTimer | None = None):
        """Run ``images`` (B, C, H, W) f32 through the warm executable.

        Returns ``(out, info)``: ``out`` is (B, C, H, W) float32 with the
        valid extent restored, ``info`` carries ``effective_backend`` and
        the compile/copy_in/device/copy_out phase walls (seconds) from
        ``timer`` (a fresh :class:`PhaseTimer` when not supplied — the
        serving latency breakdown reuses its ``to_row`` export).
        """
        import jax
        import jax.numpy as jnp

        from parallel_convolution_tpu.parallel import step as step_lib

        t = timer or PhaseTimer()
        if key.rank == 3:
            return self._run_volume_batch(key, images, t)
        B, C, H, W = images.shape
        if (C, H, W) != key.shape:
            raise ValueError(
                f"batch shape {(C, H, W)} does not match key {key.shape}")
        if key.grid != self.grid():
            # A key compiled for a pre-reshape grid must never execute on
            # the new decomposition (the service re-keys requests after
            # its drain, so this only fires on a caller bug).
            raise ValueError(
                f"stale key grid {key.grid}: engine mesh is now "
                f"{self.grid()} (resharded mid-process)")
        with t.phase("compile"):
            # The trace's compile span covers acquisition (warm hit or
            # cold build): the leader's compile_build nests inside it, a
            # single-flight waiter LINKS the leader's build span instead
            # (obs.trace — who paid vs who drafted).
            with obs_trace.span("compile", backend=key.backend,
                                batch=B) as csp:
                entry = self.entry(key)
                fn = entry.fns.get(B) or self._compile_batch(entry, B)
                csp.set(effective_backend=entry.effective_backend)
        filt = get_filter(key.filter_name)
        with t.phase("copy_in"):
            with obs_trace.span("copy_in", batch=B):
                folded = np.ascontiguousarray(
                    images.reshape(B * C, H, W).astype(np.float32))
                xs, valid_hw, _ = step_lib._prepare(
                    folded, self.mesh, filt.radius, key.storage)
                jax.block_until_ready(xs)
        # The timer is shared across retry ATTEMPTS (the service re-invokes
        # run_batch with it), so telemetry must charge only THIS call's
        # device delta — a retried batch's drift/exchange series would
        # otherwise include the failed attempt's wall.
        dev_before = t.wall("device")
        with t.phase("device"):
            with obs_trace.span("device", batch=B,
                                backend=entry.effective_backend) as dsp:
                out = fn(xs)
                jax.block_until_ready(out)
        dev_s = t.wall("device") - dev_before
        with t.phase("copy_out"):
            with obs_trace.span("copy_out", batch=B):
                out = np.asarray(
                    out[:, : valid_hw[0], : valid_hw[1]].astype(jnp.float32))
                out = out.reshape(B, C, H, W)
        with self._lock:
            self.stats["batches"] += 1
            self.stats["images"] += B
        if obs_metrics.enabled():
            # Attach the (already closed) device span's context so the
            # model-attributed exchange/compute spans record_step emits
            # land as ITS children — the span tree's leaf level.
            with obs_trace.attach(dsp.context):
                self._record_batch_obs(entry, B, filt, dev_s)
        # Overlap-adjusted exchange attribution for the response (pure
        # model arithmetic — always on, obs or not): hidden vs exposed
        # exchange is how the overlapped-halo lever is judged per
        # request.  Cached per (entry, batch) — it is a pure function of
        # them (batch-dependent only via the RDMA tiled switch), and a
        # benign last-writer-wins race writes identical dicts.
        split = entry.splits.get(B)
        if split is None:
            from parallel_convolution_tpu.obs import attribution

            dev0 = self.mesh.devices.flat[0]
            split = attribution.predicted_exchange_split(
                key.grid, self._block_hw(key), filt.radius,
                max(1, min(key.fuse, key.iters)),
                backend=entry.effective_backend, storage=key.storage,
                shape=(B * C, H, W), tile=key.tile, quantize=key.quantize,
                separable=entry.effective_backend in ("separable",
                                                      "pallas_sep"),
                platform=dev0.platform,
                device_kind=getattr(dev0, "device_kind", "") or "",
                overlap=entry.effective_overlap,
                col_mode=entry.effective_col_mode)
            entry.splits[B] = split
        info = {
            "effective_backend": entry.effective_backend,
            "effective_grid": f"{key.grid[0]}x{key.grid[1]}",
            "plan_source": entry.plan_source,
            "plan_key": entry.plan_key,
            "predicted_gpx_per_chip": entry.predicted_gpx,
            "batch_size": B,
            "overlap": entry.effective_overlap,
            "col_mode": entry.effective_col_mode,
            "exchange_fraction": round(split["exchange_fraction"], 4),
            "exchange_hidden_fraction": round(
                split["exchange_hidden_fraction"], 4),
            "phases": {name: t.wall(name)
                       for name in ("compile", "copy_in", "device",
                                    "copy_out")},
        }
        return out, info

    def _run_volume_batch(self, key: EngineKey, volumes: np.ndarray,
                          t: PhaseTimer):
        """The rank-3 arm of :meth:`run_batch`: ``volumes`` is
        (B, 2, D, H, W) float32, ``key.shape`` its (D, H, W).  Returns
        ``(out, info)`` with ``out`` the same shape float32 (no u8
        quantization — volumes are float fields end to end) and the
        same ``info`` stamps as rank 2; the exchange attribution comes
        from the rank-3 face-bytes model
        (``obs.attribution.volume_face_bytes_per_round``)."""
        import jax

        from parallel_convolution_tpu.utils.config import (
            VOLUME_FIELDS, VOLUME_RADII,
        )
        from parallel_convolution_tpu.volumes import driver

        if volumes.ndim != 5 or volumes.shape[1] != VOLUME_FIELDS:
            raise ValueError(
                f"volume batch must be (B, {VOLUME_FIELDS}, D, H, W), "
                f"got {volumes.shape}")
        B = volumes.shape[0]
        if tuple(volumes.shape[2:]) != key.shape:
            raise ValueError(
                f"batch volume shape {tuple(volumes.shape[2:])} does "
                f"not match key {key.shape}")
        if key.grid != self.grid():
            raise ValueError(
                f"stale key grid {key.grid}: engine mesh is now "
                f"{self.grid()} (resharded mid-process)")
        D, H, W = key.shape
        with t.phase("compile"):
            with obs_trace.span("compile", backend=key.backend,
                                batch=B, rank=3):
                entry = self.entry(key)
                fn = entry.fns.get(B) or self._compile_batch(entry, B)
        with t.phase("copy_in"):
            with obs_trace.span("copy_in", batch=B):
                folded = np.ascontiguousarray(
                    volumes.reshape(B * VOLUME_FIELDS, D, H, W)
                    .astype(np.float32))
                xs, valid_hw = driver.prepare_volume(
                    folded, self.mesh, key.boundary)
                jax.block_until_ready(xs)
        with t.phase("device"):
            with obs_trace.span("device", batch=B,
                                backend=entry.effective_backend):
                out = fn(xs)
                jax.block_until_ready(out)
        with t.phase("copy_out"):
            with obs_trace.span("copy_out", batch=B):
                out = np.asarray(out)[:, :, : valid_hw[0], : valid_hw[1]]
                out = out.reshape(B, VOLUME_FIELDS, D, H, W)
        with self._lock:
            self.stats["batches"] += 1
            self.stats["images"] += B
        split = entry.splits.get(B)
        if split is None:
            # Model-attributed exchange share: the rank-3 roofline with
            # and without its collective term (a 1x1 grid has none).
            from parallel_convolution_tpu.tuning import costmodel

            dev0 = self.mesh.devices.flat[0]
            hw = costmodel.hardware_for(
                dev0.platform, getattr(dev0, "device_kind", "") or "")
            r = VOLUME_RADII[key.filter_name]
            args = (self._block_hw(key), D, r, key.fuse, key.filter_name,
                    hw)
            total = costmodel.predict_volume_seconds_per_cell_iter(
                key.grid, *args, fields=B * VOLUME_FIELDS)
            local = costmodel.predict_volume_seconds_per_cell_iter(
                (1, 1), *args, fields=B * VOLUME_FIELDS)
            from parallel_convolution_tpu.obs import attribution

            face = attribution.volume_face_bytes_per_round(
                key.grid, self._block_hw(key), D, r, key.fuse,
                fields=B * VOLUME_FIELDS, storage=key.storage,
                boundary=key.boundary)
            split = {
                "exchange_fraction": max(0.0, 1.0 - local / total),
                "exchange_hidden_fraction": 0.0,  # no overlapped form
                "face_bytes": face["total"],
            }
            entry.splits[B] = split
        info = {
            "effective_backend": entry.effective_backend,
            "effective_grid": f"{key.grid[0]}x{key.grid[1]}",
            "plan_source": entry.plan_source,
            "plan_key": entry.plan_key,
            "predicted_gpx_per_chip": entry.predicted_gpx,
            "batch_size": B,
            "overlap": False,
            "col_mode": "packed",
            "exchange_fraction": round(split["exchange_fraction"], 4),
            "exchange_hidden_fraction": 0.0,
            "phases": {name: t.wall(name)
                       for name in ("compile", "copy_in", "device",
                                    "copy_out")},
        }
        return out, info

    def _record_batch_obs(self, entry: _Entry, B: int, filt,
                          dev_s: float) -> None:
        """Per-batch telemetry: halo/exchange attribution for THIS call's
        device wall plus the predicted-vs-measured drift series per plan
        key — the recalibration input ROADMAP item 5a consumes."""
        from parallel_convolution_tpu.obs import attribution

        key = entry.key
        C, H, W = key.shape
        dev0 = self.mesh.devices.flat[0]
        attribution.record_step(
            backend=entry.effective_backend, grid=key.grid,
            block_hw=self._block_hw(key), radius=filt.radius,
            fuse=max(1, min(key.fuse, key.iters)), iters=key.iters,
            channels=B * C, storage=key.storage, boundary=key.boundary,
            wall_s=dev_s, shape=(B * C, H, W), quantize=key.quantize,
            tile=key.tile, platform=dev0.platform,
            device_kind=getattr(dev0, "device_kind", "") or "",
            source="serving", overlap=entry.effective_overlap,
            col_mode=entry.effective_col_mode)
        if dev_s > 0:
            attribution.record_drift(
                entry.plan_key, entry.effective_backend,
                entry.predicted_gpx,
                B * C * H * W * key.iters / dev_s / self.mesh.size / 1e9)

    # -- progressive convergence --------------------------------------------
    def _converge_fn(self, entry: _Entry, n: int):
        """The warm convergence-chunk executable for ``n`` iterations of
        this entry's config (compiled under the entry lock, cached)."""
        fn = entry.converge_fns.get(n)
        if fn is not None:
            return fn
        with entry.lock:
            fn = entry.converge_fns.get(n)
            if fn is not None:
                return fn
            if entry.key.rank == 3:
                import jax

                from parallel_convolution_tpu.utils.config import (
                    VOLUME_FIELDS,
                )
                from parallel_convolution_tpu.volumes import driver

                key = entry.key
                D, H, W = key.shape
                probe = np.zeros((VOLUME_FIELDS, D, H, W), np.float32)
                xs, valid_hw = driver.prepare_volume(
                    probe, self.mesh, key.boundary)
                _, block_hw, _ = driver._geometry(
                    (VOLUME_FIELDS, D, H, W), self.mesh, key.boundary)
                fn = driver.converge_chunk_fn(
                    self.mesh, key.filter_name, n, D, valid_hw,
                    block_hw, key.fuse, key.boundary)
                jax.block_until_ready(fn(xs)[1])
                entry.converge_fns[n] = fn
                entry.compiles += 1
                with self._lock:
                    self.stats["compiles"] += 1
                return fn
            import jax

            from parallel_convolution_tpu.parallel import step as step_lib

            key = entry.key
            filt = get_filter(key.filter_name)
            probe = np.zeros(key.shape, np.float32)
            xs, valid_hw, block_hw = step_lib._prepare(
                probe, self.mesh, filt.radius, key.storage)
            fn = step_lib._build_converge_chunk(
                self.mesh, filt, n, key.quantize, valid_hw, block_hw,
                entry.effective_backend, key.boundary, key.fuse, key.tile,
                False, entry.effective_overlap, entry.effective_col_mode)
            jax.block_until_ready(fn(xs)[1])  # compile NOW: the stream's
            #                                   first chunk must not pay it
            entry.converge_fns[n] = fn
            entry.compiles += 1
            with self._lock:
                self.stats["compiles"] += 1
            return fn

    def run_converge(self, key: EngineKey, image: np.ndarray, *,
                     tol: float, max_iters: int, check_every: int,
                     start_done: int = 0, start_wu: float = 0.0,
                     start_diff: float = float("inf")):
        """Progressive run-to-convergence through the warm cache.

        ``image`` is ONE (C, H, W) f32 field; ``key.iters`` should equal
        ``check_every`` (the chunk program's compile identity — the
        service's converge keying does this).  A generator yielding
        ``(image_f32, done, diff, work_units)`` per chunk exactly like
        ``step.sharded_converge_stream``, but with the chunk executables
        cached on the warm entry (same LRU / single-flight / degrade
        machinery as the batch path) so a stream of convergence jobs for
        one config compiles once.  ``work_units`` is the fine-grid work
        spent so far — for jacobi the iteration count itself; for
        ``key.solver == "multigrid"`` (one yield per V-CYCLE, ``done``
        counting cycles, ``diff`` the fine-grid residual norm) the
        pixel-weighted per-level accounting that makes the two solvers
        comparable under one budget.

        ``start_done``/``start_wu`` seed a RESUMED job (round 18):
        ``image`` is then a mid-stream field from a resume token, the
        iteration/cycle count continues from ``start_done``, and
        ``max_iters`` keeps meaning the job's TOTAL budget — the
        resumed stream only spends what the token hasn't.  Tokens are
        minted on ``check_every`` (resp. V-cycle) boundaries, so the
        remaining chunk sizes are exactly the uninterrupted run's —
        which is why the resumed final row is byte-identical (asserted
        in tests/test_chaos.py; crop + zero-re-pad is bit-exact on any
        grid, so it holds even resuming onto a different mesh).

        A mid-stream mesh reshape raises the same stale-grid ValueError
        as :meth:`run_batch` — the service turns it into a typed,
        retryable ``resharding`` row after the best-so-far snapshots
        already streamed out.
        """
        import jax.numpy as jnp

        from parallel_convolution_tpu.parallel import step as step_lib

        entry = self.entry(key)
        if key.rank == 3:
            # ``image`` is one (2, D, H, W) float32 volume; the chunk
            # executables come from volumes.driver through the same
            # warm-entry cache, and the chunk math is identical — so
            # resume tokens minted on check_every boundaries replay
            # byte-stably exactly like rank 2.
            yield from self._run_volume_converge(
                entry, key, image, tol=tol, max_iters=max_iters,
                check_every=check_every, start_done=start_done,
                start_diff=start_diff)
            return
        filt = get_filter(key.filter_name)
        if tuple(image.shape) != key.shape:
            raise ValueError(
                f"image shape {tuple(image.shape)} does not match key "
                f"{key.shape}")
        start_done, start_wu = int(start_done), float(start_wu)
        if float(start_diff) < tol:
            # The token already met the tolerance (the dead stream died
            # between its last chunk and the final row): nothing left to
            # run — the caller emits the final row from the token.
            return
        if key.solver == "multigrid":
            # The V-cycle's level programs are module-level lru-cached
            # (solvers.multigrid) on (mesh, filter, geometry, backend) —
            # a stream of jobs for one config compiles once, exactly the
            # warm-cache property the chunk path has.  The stale-grid
            # guard runs per cycle: the generator reads self.grid() each
            # readback, so a mid-stream reshape surfaces as the same
            # typed ValueError, never an execution on the wrong mesh.
            from parallel_convolution_tpu.solvers import multigrid

            entry.mg_levels = len(multigrid.plan_levels(
                self.mesh, image.shape[1:], filt.radius, key.boundary,
                key.mg_levels))
            budget = float(max_iters) - start_wu
            if budget <= 0:
                return
            stream = multigrid.mg_converge_stream(
                np.ascontiguousarray(image, dtype=np.float32), filt,
                tol=tol, max_iters=budget, mesh=self.mesh,
                quantize=key.quantize, backend=entry.effective_backend,
                storage=key.storage, boundary=key.boundary,
                tile=key.tile, overlap=entry.effective_overlap,
                mg_levels=key.mg_levels,
                col_mode=entry.effective_col_mode)
            for out, cycles, residual, wu in stream:
                if key.grid != self.grid():
                    raise ValueError(
                        f"stale key grid {key.grid}: engine mesh is now "
                        f"{self.grid()} (resharded mid-process)")
                yield (out, cycles + start_done, residual,
                       round(wu + start_wu, 3))
            return
        xs, valid_hw, _ = step_lib._prepare(
            np.ascontiguousarray(image, dtype=np.float32), self.mesh,
            filt.radius, key.storage)
        check_every, max_iters = int(check_every), int(max_iters)
        done, diff = start_done, float("inf")   # start_diff >= tol here:
        #                                         the chunk loop re-reads
        #                                         its own residual
        while done < max_iters and diff >= tol:
            if key.grid != self.grid():
                raise ValueError(
                    f"stale key grid {key.grid}: engine mesh is now "
                    f"{self.grid()} (resharded mid-process)")
            n = min(check_every, max_iters - done)
            fn = self._converge_fn(entry, n)
            xs, d = fn(xs)
            diff = float(d)   # the readback fences the chunk
            done += n
            yield (np.asarray(xs[:, : valid_hw[0], : valid_hw[1]]
                              .astype(jnp.float32)), done, diff, float(done))

    def _run_volume_converge(self, entry: _Entry, key: EngineKey,
                             volume: np.ndarray, *, tol: float,
                             max_iters: int, check_every: int,
                             start_done: int = 0,
                             start_diff: float = float("inf")):
        """The rank-3 arm of :meth:`run_converge`: yields
        ``(volume_f32, done, diff, work_units)`` per chunk, volumes at
        the valid extent."""
        from parallel_convolution_tpu.utils.config import VOLUME_FIELDS
        from parallel_convolution_tpu.volumes import driver

        expect = (VOLUME_FIELDS,) + key.shape
        if tuple(volume.shape) != expect:
            raise ValueError(
                f"volume shape {tuple(volume.shape)} does not match "
                f"key (want {expect})")
        if float(start_diff) < tol:
            return
        xs, valid_hw = driver.prepare_volume(
            np.ascontiguousarray(volume, dtype=np.float32), self.mesh,
            key.boundary)
        check_every, max_iters = int(check_every), int(max_iters)
        done, diff = int(start_done), float("inf")
        while done < max_iters and diff >= tol:
            if key.grid != self.grid():
                raise ValueError(
                    f"stale key grid {key.grid}: engine mesh is now "
                    f"{self.grid()} (resharded mid-process)")
            n = min(check_every, max_iters - done)
            fn = self._converge_fn(entry, n)
            xs, d = fn(xs)
            diff = float(d)   # the readback fences the chunk
            done += n
            out = np.asarray(xs)[:, :, : valid_hw[0], : valid_hw[1]]
            yield (out.astype(np.float32, copy=False), done, diff,
                   float(done))

    # -- introspection ------------------------------------------------------
    def warm_key_count(self) -> int:
        """Resident warm keys (the ``/readyz`` payload's ``warm_keys``
        — one of the autoscaler's placement signals)."""
        with self._lock:
            return len(self._entries)

    def degraded(self) -> list[dict]:
        """Distinct requested→effective backend downgrades among resident
        entries — the 'current degrade tier' surface ``/readyz`` reports
        (a degraded service still serves; readiness reports it rather
        than failing on it)."""
        with self._lock:
            pairs = sorted({(k.backend, e.effective_backend)
                            for k, e in self._entries.items()
                            if e.effective_backend != k.backend})
        return [{"requested": req, "effective": eff} for req, eff in pairs]

    def snapshot(self) -> dict:
        """Stats + resident keys, for /stats and the loadgen row."""
        from parallel_convolution_tpu.parallel import channels

        with self._lock:
            return {
                "stats": dict(self.stats),
                # Persistent-channel reuse evidence: descriptor-plan
                # builds vs cache hits, process-global (the
                # --channels-smoke leg asserts builds stay flat across
                # a warm key's request stream).
                "channels": channels.stats(),
                "capacity": self.capacity,
                "grid": "x".join(str(v) for v in self.grid()),
                "resident": [
                    {"filter": k.filter_name, "shape": list(k.shape),
                     "backend": k.backend,
                     "effective_backend": e.effective_backend,
                     "fuse": k.fuse,
                     "tile": list(k.tile) if k.tile else None,
                     "overlap": e.effective_overlap,
                     "col_mode": e.effective_col_mode,
                     "plan_source": e.plan_source,
                     "predicted_gpx_per_chip": e.predicted_gpx,
                     "batch_sizes": sorted(e.fns),
                     # Per-key compile ledger (r17): the warm-placement
                     # gate asserts a pre-warmed shard holds this flat.
                     "compiles": e.compiles,
                     "iters": k.iters}
                    for k, e in self._entries.items()
                ],
            }
