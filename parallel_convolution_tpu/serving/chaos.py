"""Chaos transport: seeded network-shaped failure injection.

Every fault the stack could inject before round 18 (``resilience.faults``,
six sites) lived at compute/IO — the transport layer that rounds 14–17
built (router, hedging, breakers, autoscaler probes) had never been
drilled under network-shaped failure.  :class:`ChaosTransport` closes
that gap: it wraps a replica transport (:class:`~.router.InProcessReplica`
or :class:`~.router.HTTPReplica`) and injects failures at the FOUR
transport sites the ``PCTPU_FAULTS`` grammar grew this round
(``faults.SITE_TABLE``):

* ``transport_send``   — the request never reaches the replica
  (``drop`` connection error, seeded ``latency``, or a ``blackhole``
  that burns the timeout first);
* ``transport_recv``   — the replica DID the work but the response is
  lost (``drop`` — the idempotency-ledger case) or arrives as garbage
  (``corrupt`` → :class:`~.router.CorruptReplicaBody`, breaker food);
* ``transport_stream`` — one progressive NDJSON row dies in flight
  (``disconnect``/``corrupt`` AFTER best-so-far rows landed — the
  mid-stream resume case);
* ``readyz_probe``     — the active-health poll lies (``flap``).

WHICH hits fail rides the proven, seeded ``PCTPU_FAULTS`` machinery
(hit-indexed / range / probability triggers — every injected failure is
replayable bit-for-bit); WHAT the failure looks like is this module's
per-site ``modes`` map.  Injected failures surface as the same exception
types real networks produce (``ConnectionError`` and subclasses), so the
router's breaker/failover/resume machinery is exercised exactly as it
would be by a dying host — nothing in the serving plane knows chaos
exists.

stdlib-only; jax stays inside the replicas.
"""

from __future__ import annotations

import random
import time

from parallel_convolution_tpu.obs import (
    events as obs_events, metrics as obs_metrics,
)
from parallel_convolution_tpu.resilience.faults import (
    InjectedFault, fault_point,
)

__all__ = ["ChaosTransport", "DEFAULT_MODES", "corrupt_frame_bytes",
           "modes_from_spec", "router_kill_due", "truncate_frame_bytes"]


def corrupt_frame_bytes(raw, *, seed: int = 0) -> bytes:
    """Deterministically flip one bit inside the LAST byte region of a
    framed payload — the corrupt-body mode for the binary wire.

    Flipping near the END of the buffer lands inside the final frame's
    PAYLOAD (headers and CRC fields sit ahead of it), so the decoder's
    structural checks all pass and the CRC is what must catch it — the
    exact in-transit corruption the checksum exists for.  ``seed``
    varies which bit, so a sweep can prove detection isn't positional
    luck."""
    data = bytearray(raw)
    if not data:
        return bytes(data)
    # Offset from the end, staying inside the last 64 bytes (or the
    # whole buffer when shorter); never the terminal byte alone — vary
    # by seed so repeated injections corrupt different payload bits.
    span = min(64, len(data))
    pos = len(data) - 1 - (seed % span)
    data[pos] ^= 1 << ((seed // span) % 8 or 1)
    return bytes(data)


def truncate_frame_bytes(raw, *, seed: int = 0) -> bytes:
    """Deterministically cut a framed payload SHORT — the mid-stream
    truncation sibling of :func:`corrupt_frame_bytes`.

    Drops between 1 and 64 trailing bytes (seeded), never the whole
    buffer, so the decoder sees a structurally plausible PREFIX whose
    declared lengths overrun the bytes present — the torn-socket shape
    ``frames.BadFrame``'s truncation checks exist for.  ``seed`` varies
    the cut depth so a sweep can prove detection isn't positional
    luck."""
    data = bytes(raw)
    if len(data) <= 1:
        return b""
    cut = 1 + (seed % min(64, len(data) - 1))
    return data[:-cut]


def router_kill_due() -> bool:
    """Consult the ``router_kill`` fault site: True when the seeded
    plan says the router process dies NOW.  Crash drills
    (``soak.py --router-restart``, ``scripts/wal_smoke.py``) poll this
    per streamed row and convert a True into what a real router death
    looks like — the stream abandoned un-closed, then a standby
    takeover replaying the WAL — instead of an in-band exception the
    serving plane would politely handle."""
    try:
        fault_point("router_kill")
    except InjectedFault:
        if obs_metrics.enabled():
            obs_events.emit("chaos", site="router_kill", mode="kill",
                            replica="router")
        return True
    return False

# site -> the failure shapes it can take (the first is the default).
SITE_MODES = {
    "transport_send": ("drop", "latency", "blackhole"),
    "transport_recv": ("drop", "corrupt"),
    "transport_stream": ("disconnect", "corrupt", "truncate"),
    "readyz_probe": ("flap",),
}

# Literal consults per site — the fault-site drift guard
# (tests/test_chaos.py) greps the tree for literal site-name consults,
# so the grammar's documented table can never silently lose a consult
# hidden behind a variable.
_CONSULTS = {
    "transport_send": lambda: fault_point("transport_send"),
    "transport_recv": lambda: fault_point("transport_recv"),
    "transport_stream": lambda: fault_point("transport_stream"),
    "readyz_probe": lambda: fault_point("readyz_probe"),
}
DEFAULT_MODES = {site: modes[0] for site, modes in SITE_MODES.items()}


def modes_from_spec(spec: str) -> dict[str, str]:
    """Parse ``site=mode,site=mode`` (e.g. from a CLI flag); raises
    ValueError on unknown sites/modes so a typo can't silently noop."""
    out: dict[str, str] = {}
    for part in filter(None, (p.strip() for p in spec.split(","))):
        if "=" not in part:
            raise ValueError(
                f"bad chaos mode {part!r}: expected site=mode")
        site, mode = (s.strip() for s in part.split("=", 1))
        if site not in SITE_MODES:
            raise ValueError(
                f"unknown chaos site {site!r}; known: "
                f"{sorted(SITE_MODES)}")
        if mode not in SITE_MODES[site]:
            raise ValueError(
                f"unknown mode {mode!r} for {site}; known: "
                f"{SITE_MODES[site]}")
        out[site] = mode
    return out


class ChaosTransport:
    """A replica transport wrapper injecting seeded transport failure.

    ``modes`` overrides :data:`DEFAULT_MODES` per site.  ``latency_s``
    is the mean injected latency (the actual sleep draws uniformly from
    [0.5, 1.5]× it, seeded); ``blackhole_s`` bounds a black-hole stall
    (clamped to the caller's timeout when one is given).  All other
    attributes (``kill``/``revive``/``service``...) delegate to the
    wrapped transport, so drills drive the replica through the wrapper.
    """

    def __init__(self, inner, modes: dict | str | None = None, *,
                 seed: int = 0, latency_s: float = 0.05,
                 blackhole_s: float = 2.0):
        if isinstance(modes, str):
            modes = modes_from_spec(modes)
        bad = set(modes or {}) - set(SITE_MODES)
        if bad:
            raise ValueError(f"unknown chaos site(s) {sorted(bad)}")
        self.inner = inner
        self.modes = {**DEFAULT_MODES, **(modes or {})}
        for site, mode in self.modes.items():
            if mode not in SITE_MODES[site]:
                raise ValueError(
                    f"unknown mode {mode!r} for {site}; known: "
                    f"{SITE_MODES[site]}")
        self._rng = random.Random(seed)
        self.latency_s = float(latency_s)
        self.blackhole_s = float(blackhole_s)
        self.injected: dict[str, int] = {}   # site -> count (asserts)

    @property
    def name(self) -> str:
        return self.inner.name

    def __getattr__(self, attr):
        # kill/revive/service/... delegate to the wrapped transport
        # (only called when normal lookup missed).  "inner" itself must
        # fail plainly — delegating it would recurse forever on a
        # half-constructed wrapper.
        if attr == "inner":
            raise AttributeError(attr)
        return getattr(self.inner, attr)

    # -- injection ------------------------------------------------------------
    def _consult(self, site: str) -> str | None:
        """The site's mode when the installed fault plan fires, else
        None.  The plan's hit counters/seed decide WHEN; the mode map
        decides WHAT."""
        try:
            _CONSULTS[site]()
            return None
        except InjectedFault:
            mode = self.modes[site]
            self.injected[site] = self.injected.get(site, 0) + 1
            if obs_metrics.enabled():
                obs_metrics.counter(
                    "pctpu_chaos_injections_total",
                    "network-shaped failures injected by the chaos "
                    "transport", ("site", "mode", "replica")).inc(
                    site=site, mode=mode, replica=self.name)
                obs_events.emit("chaos", site=site, mode=mode,
                                replica=self.name)
            return mode

    def _send(self, timeout) -> None:
        mode = self._consult("transport_send")
        if mode is None:
            return
        if mode == "latency":
            time.sleep(self.latency_s * (0.5 + self._rng.random()))
            return
        if mode == "blackhole":
            # A black hole costs the caller its timeout budget FIRST —
            # the failure shape breakers/hedges exist for.
            time.sleep(min(self.blackhole_s,
                           timeout if timeout else self.blackhole_s))
            raise ConnectionError(
                f"chaos: black-holed send to {self.name} timed out")
        raise ConnectionError(f"chaos: dropped send to {self.name}")

    def _recv(self) -> None:
        mode = self._consult("transport_recv")
        if mode is None:
            return
        if mode == "corrupt":
            from parallel_convolution_tpu.serving.router import (
                CorruptReplicaBody,
            )

            raise CorruptReplicaBody(
                f"chaos: corrupt body from {self.name}")
        raise ConnectionError(
            f"chaos: dropped response from {self.name} "
            "(the work executed)")

    # -- the transport protocol ------------------------------------------------
    def request(self, body: dict, timeout: float | None = None,
                traceparent: str | None = None):
        self._send(timeout)
        status, wire = self.inner.request(body, timeout=timeout,
                                          traceparent=traceparent)
        self._recv()
        return status, wire

    def converge(self, body: dict, timeout: float | None = None,
                 traceparent: str | None = None):
        self._send(timeout)
        status, rows = self.inner.converge(body, timeout=timeout,
                                           traceparent=traceparent)
        self._recv()
        if status != 200:
            return status, rows
        return 200, self._chaos_rows(rows)

    def _chaos_rows(self, rows):
        """Per-row stream injection: consult ``transport_stream`` before
        each row crosses — a triggered hit breaks the stream AFTER the
        earlier rows already landed (the resume case)."""
        from parallel_convolution_tpu.serving.router import (
            CorruptReplicaBody,
        )

        for row in rows:
            mode = self._consult("transport_stream")
            if mode == "corrupt":
                raise CorruptReplicaBody(
                    f"chaos: corrupt stream row from {self.name}")
            if mode == "truncate":
                # Round 24: run the REAL codec path — encode this row
                # as a PCTE envelope, tear its tail, and let the
                # decoder's own truncation check produce the typed
                # error the router resumes from.  If the torn prefix
                # somehow decoded, that would be a codec hole — still
                # surfaced typed, never silently served.
                from parallel_convolution_tpu.serving import frames

                raw = truncate_frame_bytes(
                    frames.encode_envelope(dict(row)),
                    seed=self._rng.randrange(1 << 16))
                try:
                    frames.decode_envelope(raw)
                except frames.BadFrame as e:
                    raise CorruptReplicaBody(
                        f"chaos: truncated stream envelope from "
                        f"{self.name}: {e}") from None
                raise CorruptReplicaBody(
                    f"chaos: truncated stream envelope from "
                    f"{self.name} decoded clean (codec hole)")
            if mode is not None:
                raise ConnectionError(
                    f"chaos: mid-stream disconnect from {self.name}")
            yield row

    def readyz(self):
        if self._consult("readyz_probe") is not None:
            raise ConnectionError(
                f"chaos: readyz probe to {self.name} flapped")
        return self.inner.readyz()

    def warm(self, configs):
        return self.inner.warm(configs)

    def snapshot(self) -> dict:
        return self.inner.snapshot()

    def close(self) -> None:
        self.inner.close()
