"""Durable convergence jobs: the router's resume-token ledger.

Convergence jobs are the longest-running work this stack serves (the
paper's 100-iteration Jacobi runs, scaled up by multigrid V-cycles), yet
before round 18 they were the LEAST fault-tolerant: ``router.converge``
failed over only before the first NDJSON row, and a replica dying
mid-stream ended the stream with a typed retryable row — the client
restarted from iteration 0 and every device-second already spent (and
charged by the round-17 pricer) was lost.

This module is the durability half of the fix.  A :class:`JobLedger`,
keyed on the SAME ``request_id`` identity the replica-side idempotency
dedup uses, records per streamed snapshot row a bounded **resume
token** — the wire-shaped triple the converge stream can be re-seeded
from on any surviving replica:

* ``iters`` / ``work_units`` — how far the job got (chunk/cycle index,
  always a ``check_every`` boundary for jacobi and a V-cycle boundary
  for multigrid, so a resumed run's remaining chunk math is EXACTLY the
  uninterrupted run's — the byte-identity contract);
* ``diff`` — the residual at that point (the stopping rule re-reads it);
* ``state_b64``/``state_shape`` — the float32 field at the valid extent
  (the r5 checkpoint rule applied in memory: crop + zero-re-pad is
  bit-exact on ANY grid, so resume works even onto a replica holding a
  different mesh — ``step.reshard_prepared``'s masking invariant).

Tokens stay WIRE-SHAPED in the ledger (the b64 string a replica row
carried), so the router never decodes image bytes; decoding happens once,
replica-side, in ``frontend.decode_converge``.  The ledger also owns the
**exactly-once final row** rule: :meth:`finalize` returns True for the
first final row of a ``request_id`` and False for every later one (a
resumed stream racing a half-delivered original can never hand the
client two finals), and drops the entry so the token's field bytes are
freed the moment the job completes.

stdlib + numpy only; jax stays inside the replicas.
"""

from __future__ import annotations

import base64
import threading
from collections import OrderedDict

import numpy as np

__all__ = ["JobLedger", "state_from_wire", "state_to_wire",
           "token_from_row", "token_progress"]

# The wire fields one resume token carries (a dict, not a dataclass: it
# rides request bodies and NDJSON rows verbatim).
TOKEN_FIELDS = ("iters", "diff", "work_units", "solver", "state_b64",
                "state_shape")


def state_to_wire(state: np.ndarray) -> tuple[str, list[int]]:
    """(state_b64, state_shape) for a (C, H, W) float32 field — or a
    (F, D, H, W) rank-3 volume; the shape list's length carries rank."""
    arr = np.ascontiguousarray(state, dtype=np.float32)
    return (base64.b64encode(arr.tobytes()).decode("ascii"),
            [int(s) for s in arr.shape])


def state_from_wire(state_b64: str, state_shape) -> np.ndarray:
    """Decode a token's field state; raises ValueError on a malformed
    token (the caller maps it to the typed ``invalid`` rejection)."""
    try:
        shape = tuple(int(s) for s in state_shape)
    except (TypeError, ValueError) as e:
        raise ValueError(f"bad resume state_shape {state_shape!r}") from e
    if len(shape) not in (3, 4) or min(shape) < 1:
        raise ValueError(
            f"resume state must be (C, H, W) or rank-3 (F, D, H, W), "
            f"got {shape}")
    try:
        raw = base64.b64decode(state_b64)
    except (TypeError, ValueError) as e:
        raise ValueError(f"bad resume state_b64: {e}") from e
    want = int(np.prod(shape)) * 4
    if len(raw) != want:
        raise ValueError(
            f"resume state carries {len(raw)} bytes, expected {want} "
            f"for f32 {shape}")
    return np.frombuffer(raw, np.float32).reshape(shape).copy()


def token_from_row(row: dict) -> dict | None:
    """Extract the resume token a wire snapshot row carries (None when
    the row has no state — the replica wasn't asked to carry it, or the
    row is a rejection)."""
    if not row.get("ok") or not row.get("state_b64"):
        return None
    return {
        "iters": int(row.get("iters", 0)),
        "diff": float(row.get("diff", 0.0)),
        "work_units": float(row.get("work_units", 0.0)),
        "solver": str(row.get("solver") or "jacobi"),
        "state_b64": row["state_b64"],
        "state_shape": row.get("state_shape"),
    }


def token_progress(token: dict | None) -> float:
    """Work units a token has already banked (0.0 for no token) — the
    incremental-charge rule's input."""
    if not token:
        return 0.0
    try:
        return max(0.0, float(token.get("work_units", 0.0)))
    except (TypeError, ValueError):
        return 0.0


class _Job:
    __slots__ = ("route_key", "token", "resume_count", "resumed_from")

    def __init__(self, route_key: str):
        self.route_key = route_key
        self.token: dict | None = None
        self.resume_count = 0
        self.resumed_from: list[str] = []


class JobLedger:
    """FIFO-bounded ledger of in-flight convergence jobs, keyed
    ``request_id`` (the same identity the replica dedup uses).

    NOTE the bound is by COUNT: each live token pins one f32 field
    (C×H×W×4 bytes) until the job finalizes or is evicted — size
    ``capacity`` down for large-frame deployments, exactly the
    ``dedup_capacity`` rule on the service side.
    """

    def __init__(self, capacity: int = 64, shard: str | None = None):
        self.capacity = max(1, int(capacity))
        # Round 21: the shard lineage this ledger serves (None when the
        # router is unsharded) — snapshot attribution only; the ledger
        # itself is per-sub-router and therefore per-shard already.
        self.shard = None if shard is None else str(shard)
        self._jobs: "OrderedDict[str, _Job]" = OrderedDict()
        # rids whose final row already went out (FIFO-bounded, cheap
        # strings): the exactly-once gate outlives the job entry, which
        # finalize drops to free the token's field bytes.
        self._finalized: "OrderedDict[str, bool]" = OrderedDict()
        # rids with a LIVE attached stream (the router pins a job while
        # its rows are flowing): capacity eviction must never take one
        # of these — evicting a mid-stream job silently loses its
        # resume token, turning the next mid-stream death into a
        # restart-from-iteration-0 the client can't explain.  Idle
        # entries (dead stream, awaiting a client retry) stay FIFO
        # evictable; `evicted` counts them (exposed in /stats).
        self._pinned: set[str] = set()
        self.evicted = 0
        self._lock = threading.Lock()

    def _get(self, rid: str, route_key: str | None = None) -> _Job:
        job = self._jobs.get(rid)
        if job is None or (route_key is not None
                           and job.route_key != route_key):
            # A reused request_id naming a DIFFERENT config must start
            # fresh: resuming another job's field into this one would be
            # silent corruption, not durability.
            job = _Job(route_key or "")
            self._jobs[rid] = job
        self._jobs.move_to_end(rid)
        self._evict_locked(keep=rid)
        return job

    def _evict_locked(self, keep: str | None = None) -> None:
        while len(self._jobs) > self.capacity:
            victim = next(
                (k for k in self._jobs
                 if k != keep and k not in self._pinned), None)
            if victim is None:
                # Every entry is mid-stream: the bound goes SOFT rather
                # than a live job going quietly un-resumable (live
                # streams are already bounded by max_progressive).
                break
            self._jobs.pop(victim)
            self.evicted += 1

    def pin(self, rid: str) -> None:
        """Mark ``rid`` as having a live attached stream (eviction-
        immune until :meth:`unpin`)."""
        with self._lock:
            self._pinned.add(rid)

    def unpin(self, rid: str) -> None:
        with self._lock:
            self._pinned.discard(rid)
            self._evict_locked()

    def observe(self, rid: str, route_key: str, row: dict) -> dict | None:
        """Record the newest resume token a snapshot row carries;
        returns it (the router's WAL appends exactly what was kept)."""
        token = token_from_row(row)
        if token is None:
            return None
        with self._lock:
            self._get(rid, route_key).token = token
        return token

    def token(self, rid: str, route_key: str) -> dict | None:
        """The newest token for ``rid`` — None when unknown, or when the
        id was last seen naming a different config."""
        with self._lock:
            job = self._jobs.get(rid)
            if job is None or job.route_key != route_key:
                return None
            return job.token

    def begin(self, rid: str, route_key: str) -> dict | None:
        """Open one converge call for ``rid``: clears any stale
        exactly-once mark (a FRESH submission's final row is legitimate
        even if a previous life of this id finalized — the client only
        retries when it never saw that final) and returns the newest
        token so a client retry after a mid-stream typed retryable row
        RESUMES from where the dead stream got to instead of iteration
        0.  Returns None when the id is unknown or names a different
        config (then the job starts fresh)."""
        with self._lock:
            self._finalized.pop(rid, None)
            job = self._jobs.get(rid)
            if job is None or job.route_key != route_key:
                return None
            return job.token

    def note_resume(self, rid: str, route_key: str,
                    from_replica: str) -> tuple[int, list[str]]:
        """Count one mid-stream resume; returns (resume_count,
        resumed_from) for the router stamp."""
        with self._lock:
            job = self._get(rid, route_key)
            job.resume_count += 1
            job.resumed_from.append(str(from_replica))
            return job.resume_count, list(job.resumed_from)

    def resume_info(self, rid: str) -> tuple[int, list[str]]:
        with self._lock:
            job = self._jobs.get(rid)
            if job is None:
                return 0, []
            return job.resume_count, list(job.resumed_from)

    def finalize(self, rid: str) -> bool:
        """Exactly-once final-row gate: True for the FIRST final row of
        this ``request_id``, False for every later one (a resumed stream
        racing a half-delivered original can never hand the client two
        finals).  The job entry — and its token's field bytes — is
        dropped on the first final; the finalized mark is kept in a
        bounded side set so the gate survives the drop."""
        with self._lock:
            if rid in self._finalized:
                self._finalized.move_to_end(rid)
                return False
            self._finalized[rid] = True
            while len(self._finalized) > 4 * self.capacity:
                self._finalized.popitem(last=False)
            self._jobs.pop(rid, None)
            return True

    def drop(self, rid: str) -> None:
        with self._lock:
            self._jobs.pop(rid, None)
            self._finalized.pop(rid, None)

    def restore(self, jobs: dict, finalized=()) -> int:
        """Seed the ledger from a recovered WAL image (round 19):
        ``jobs`` maps lid → ``{key, token, resume_count, resumed_from}``
        (the :class:`~.wal.WALState` shape), ``finalized`` re-arms the
        exactly-once gate across the restart.  Entries beyond capacity
        evict FIFO (counted).  Returns how many jobs were restored."""
        with self._lock:
            for lid, j in jobs.items():
                job = _Job(str(j.get("key", "")))
                job.token = j.get("token")
                job.resume_count = int(j.get("resume_count", 0))
                job.resumed_from = [str(x)
                                    for x in j.get("resumed_from", [])]
                self._jobs[str(lid)] = job
            self._evict_locked()
            for rid in finalized:
                self._finalized[str(rid)] = True
            while len(self._finalized) > 4 * self.capacity:
                self._finalized.popitem(last=False)
            return len(self._jobs)

    def export(self) -> tuple[dict, list[str]]:
        """The ledger's LIVE image in :meth:`restore`'s shape:
        (``jobs`` mapping lid → {key, token, resume_count,
        resumed_from}, ``finalized`` lid list).  The degraded-
        durability re-arm reads this (round 24): WAL appends that
        failed during a degraded window never reached the folded
        state, so the re-arm compaction snapshot must be built from
        the structures that kept serving — this ledger — not from the
        journal's stale image."""
        with self._lock:
            jobs = {lid: {"key": j.route_key, "token": j.token,
                          "resume_count": j.resume_count,
                          "resumed_from": list(j.resumed_from)}
                    for lid, j in self._jobs.items()}
            return jobs, list(self._finalized)

    def __len__(self) -> int:
        with self._lock:
            return len(self._jobs)

    def snapshot(self) -> dict:
        with self._lock:
            return {
                "jobs": len(self._jobs),
                "capacity": self.capacity,
                **({"shard": self.shard}
                   if self.shard is not None else {}),
                "pinned": len(self._pinned),
                # Live (un-finalized) jobs evicted at capacity — should
                # stay 0 under healthy load; a rising count means the
                # ledger is sized below the idle-retry window.
                "ledger_evicted": self.evicted,
                "resumes": sum(j.resume_count
                               for j in self._jobs.values()),
            }
