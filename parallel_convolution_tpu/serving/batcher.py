"""Bounded request queue with same-key micro-batching.

The throughput regime of an iterated stencil is bandwidth-bound and its
executables are batch-shaped, so the way to serve many small requests
fast is to coalesce them: requests with the SAME :class:`EngineKey`
stack on a leading dim and ride one device program.  The batcher is the
queueing half of that bargain; the engine is the compute half.

Invariants (asserted by ``tests/test_serving.py``):

* **Bounded queue.**  ``try_submit`` refuses (returns False) once
  ``max_queue`` items are pending — admission control happens at the
  door, atomically with the queue, so overflow can never wedge the
  worker or grow memory.
* **Same-key only.**  A flush drains only items whose key equals the
  head item's key (up to ``max_batch``); mixed-key arrivals are never
  co-batched, because different keys mean different compiled programs.
  Other keys keep their arrival order for subsequent flushes.
* **Deadline flush.**  The head item waits at most ``max_delay_s`` for
  batch-mates (or less, if its own deadline is sooner); a single request
  on an idle service therefore completes in ~``max_delay_s``, it does
  not wait for a full batch.
* **One worker.**  All device execution happens on the single worker
  thread, serializing access to the mesh; HTTP handler threads only
  enqueue and wait on their slot.

Tracing (round 13): the batcher itself opens no spans — it is the
thread hop.  A request's :class:`obs.trace.SpanContext` rides its
payload (``payload["trace"]``), and the executor derives the per-request
``queue`` span from this queue's own clocks (``_Item.enqueued_at`` →
flush collect) plus the per-flush ``batch`` span that links every
co-batched request (``service._execute_batch``).
"""

from __future__ import annotations

import threading
import time
from collections import deque

from parallel_convolution_tpu.obs import metrics as obs_metrics

__all__ = ["MicroBatcher", "Slot"]


class Slot:
    """One request's result rendezvous (a minimal, stdlib-only future)."""

    __slots__ = ("_event", "_result")

    def __init__(self):
        self._event = threading.Event()
        self._result = None

    def set(self, result) -> None:
        self._result = result
        self._event.set()

    def result(self, timeout: float | None = None):
        """The Response/Rejected once available; None on wait timeout."""
        if not self._event.wait(timeout):
            return None
        return self._result

    def done(self) -> bool:
        return self._event.is_set()


class _Item:
    __slots__ = ("key", "payload", "slot", "enqueued_at", "deadline_at")

    def __init__(self, key, payload, deadline_at, slot=None):
        self.key = key
        self.payload = payload
        # An externally-supplied slot lets the service's request_id dedup
        # ledger hand hedged submissions the SAME rendezvous object.
        self.slot = slot if slot is not None else Slot()
        self.enqueued_at = time.monotonic()
        self.deadline_at = deadline_at  # absolute monotonic, or None


class MicroBatcher:
    """Coalesce same-key requests; flush on size or deadline.

    ``execute(key, items)`` (the service's batch runner) is called on the
    worker thread with 1..max_batch same-key items and MUST set every
    item's slot — the batcher guarantees delivery attempts, the executor
    guarantees typed results.
    """

    def __init__(self, execute, *, max_batch: int = 8,
                 max_delay_s: float = 0.005, max_queue: int = 64,
                 start: bool = True):
        if max_batch < 1 or max_queue < 1 or max_delay_s < 0:
            raise ValueError("max_batch/max_queue >= 1, max_delay_s >= 0")
        self._execute = execute
        self.max_batch = max_batch
        self.max_delay_s = max_delay_s
        self.max_queue = max_queue
        self._cv = threading.Condition()
        self._pending: deque[_Item] = deque()
        self._closed = False
        self._worker: threading.Thread | None = None
        # Legacy stats dict as a view over the obs registry
        # (pctpu_batcher_stats{key=...}); dict semantics unchanged.
        self.stats = obs_metrics.MirroredStats(obs_metrics.gauge(
            "pctpu_batcher_stats", "micro-batcher queue/flush counters",
            ("key",)), initial={
            "enqueued": 0, "refused": 0, "flushes": 0,
            "flushed_items": 0, "max_observed_depth": 0})
        self._depth_gauge = obs_metrics.gauge(
            "pctpu_queue_depth", "pending requests in the batcher queue")
        if start:
            self.start()

    # -- producer side -------------------------------------------------------
    def try_submit(self, key, payload, deadline_at=None,
                   slot: Slot | None = None) -> Slot | None:
        """Enqueue; returns the item's :class:`Slot`, or None when the
        queue is full or the batcher closed (the caller sheds load).
        ``slot`` substitutes a caller-owned rendezvous (dedup ledger)."""
        item = _Item(key, payload, deadline_at, slot=slot)
        with self._cv:
            if self._closed or len(self._pending) >= self.max_queue:
                self.stats["refused"] += 1
                return None
            self._pending.append(item)
            self.stats["enqueued"] += 1
            self.stats["max_observed_depth"] = max(
                self.stats["max_observed_depth"], len(self._pending))
            self._depth_gauge.set(len(self._pending))
            self._cv.notify_all()
        return item.slot

    def depth(self) -> int:
        with self._cv:
            return len(self._pending)

    # -- worker side ---------------------------------------------------------
    def start(self) -> None:
        if self._worker is None or not self._worker.is_alive():
            self._worker = threading.Thread(
                target=self._loop, name="pctpu-batcher", daemon=True)
            self._worker.start()

    def close(self, drain: bool = True, timeout: float = 30.0) -> None:
        """Stop accepting; optionally wait for the queue to drain."""
        with self._cv:
            self._closed = True
            self._cv.notify_all()
        w = self._worker
        if drain and w is not None and w.is_alive():
            w.join(timeout)

    def _collect(self) -> tuple[object, list[_Item]] | None:
        """Block until a flush is due; returns (key, same-key items)."""
        with self._cv:
            while not self._pending:
                if self._closed:
                    return None
                self._cv.wait(timeout=0.1)
            head = self._pending[0]
            flush_at = head.enqueued_at + self.max_delay_s
            if head.deadline_at is not None and head.deadline_at < flush_at:
                # The head cannot afford the full batching window: flush
                # NOW rather than gamble its remaining budget on
                # hypothetical batch-mates.  (Waiting until exactly
                # deadline_at would guarantee the executor's expiry check
                # sheds it — a tight deadline on an idle service must be
                # served, not starved.)
                flush_at = 0.0
            while True:
                n_same = sum(1 for it in self._pending if it.key == head.key)
                now = time.monotonic()
                if (n_same >= self.max_batch or now >= flush_at
                        or self._closed):
                    break
                self._cv.wait(timeout=flush_at - now)
            batch: list[_Item] = []
            rest: deque[_Item] = deque()
            for it in self._pending:
                if it.key == head.key and len(batch) < self.max_batch:
                    batch.append(it)
                else:
                    rest.append(it)   # order among other keys preserved
            self._pending = rest
            self.stats["flushes"] += 1
            self.stats["flushed_items"] += len(batch)
            self._depth_gauge.set(len(self._pending))
            self._cv.notify_all()
            return head.key, batch

    def _loop(self) -> None:
        while True:
            got = self._collect()
            if got is None:
                return
            key, batch = got
            try:
                self._execute(key, batch)
            except BaseException as e:  # noqa: BLE001 — never kill the worker
                # The executor's contract is typed results; if it leaked an
                # exception anyway, fail its items rather than hanging their
                # waiters (and keep serving subsequent batches).
                for it in batch:
                    if not it.slot.done():
                        it.slot.set(e)
