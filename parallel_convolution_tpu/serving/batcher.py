"""Bounded request queue with shape-bucketed lanes + continuous batching.

The throughput regime of an iterated stencil is bandwidth-bound and its
executables are batch-shaped, so the way to serve many small requests
fast is to coalesce them: requests whose :class:`EngineKey` maps to the
same LANE stack on a leading dim and ride one device program.  The
batcher is the queueing half of that bargain; the engine is the compute
half.

Two structural changes over the original drain-between-flushes design:

* **Shape-bucketed lanes.**  ``lane_of(key)`` (the service passes
  ``engine.bucket_key``) maps near-miss keys — same compile identity,
  H×W within one bucket — onto a shared lane, so a 96×120 and a
  100×128 thumbnail co-batch (padded to the bucket, cropped on the way
  out) instead of serializing as two one-item flushes.  Without
  ``lane_of`` every key is its own lane: exact-key batching, the old
  behavior, and what non-EngineKey tests exercise.
* **Mid-flight refill (continuous batching).**  Collection and
  execution are a two-stage pipeline on separate threads: the COLLECTOR
  assembles the next flush (including the host-side ``prepare`` work —
  deadline shedding, pad-to-bucket stacking) while the EXECUTOR still
  runs the previous one on the device.  The old design drained between
  flushes — host stacking and device execution strictly alternated on
  one worker; now the device refills without a flush barrier
  (``pipeline_depth=0`` restores the drain behavior, kept as the A/B
  control arm for ``scripts/wire_ab.py``).

Invariants (asserted by ``tests/test_serving.py`` / ``tests/test_wire.py``):

* **Bounded queue.**  ``try_submit`` refuses (returns None) once
  ``max_queue`` items are pending — admission control happens at the
  door, atomically with the queue, so overflow can never wedge the
  workers or grow memory.  ``depth()`` counts QUEUED items (the
  admission bound); ``max_observed_depth`` additionally counts items
  held in staged/executing flushes, so the high-water mark reflects
  everything the batcher owns, not just the queue.
* **Same-lane only.**  A flush drains only items from one lane (up to
  ``max_batch``); different lanes mean different compiled programs.
  Arrival order within a lane is preserved.
* **Deadline flush.**  A lane's head waits at most ``max_delay_s`` for
  batch-mates (or less, if its own deadline is sooner); a single
  request on an idle service completes in ~``max_delay_s``.
* **Cost-priced lane priority.**  When several lanes are due at once,
  the cheapest head (``payload["cost_units"]``, stamped by the
  service's admission pricer) flushes first, so a large job never
  head-of-line-blocks a thumbnail — with an age backstop: a lane
  overdue by more than ``STARVATION_MULT`` delay windows preempts the
  price order outright.
* **One executor.**  All device execution happens on the single
  executor thread, serializing access to the mesh; handler threads only
  enqueue and wait on their slot, the collector only does host work.

Tracing (round 13): the batcher itself opens no spans — it is the
thread hop.  A request's :class:`obs.trace.SpanContext` rides its
payload (``payload["trace"]``), and the executor derives the
per-request ``queue`` span from this queue's own clocks
(``_Item.enqueued_at`` → flush collect) plus the per-flush ``batch``
span that links every co-batched request (``service._execute_batch``).
"""

from __future__ import annotations

import threading
import time
from collections import OrderedDict, deque

from parallel_convolution_tpu.obs import metrics as obs_metrics

__all__ = ["MicroBatcher", "Slot"]

# A lane overdue by this many delay windows outranks any price: the
# cost-priced order must never become starvation of expensive work.
STARVATION_MULT = 8.0

# Bound on distinct per-lane gauge labels: adversarially varied shapes
# must not grow /metrics cardinality forever; the overflow bucket
# aggregates the tail.
_LANE_LABEL_CAP = 32


def _lane_label(lane) -> str:
    """A compact, stable exposition label for one lane key."""
    shape = getattr(lane, "shape", None)
    if shape is not None:
        label = "x".join(str(v) for v in shape)
        filt = getattr(lane, "filter_name", "")
        return f"{label}:{filt}" if filt else label
    return str(lane)[:48]


def _area(key) -> int:
    """Pixels one item of ``key`` contributes to a flush (0 = unknown)."""
    shape = getattr(key, "shape", None)
    if not shape:
        return 0
    n = 1
    for v in shape:
        n *= int(v)
    return n


class Slot:
    """One request's result rendezvous (a minimal, stdlib-only future)."""

    __slots__ = ("_event", "_result")

    def __init__(self):
        self._event = threading.Event()
        self._result = None

    def set(self, result) -> None:
        self._result = result
        self._event.set()

    def result(self, timeout: float | None = None):
        """The Response/Rejected once available; None on wait timeout."""
        if not self._event.wait(timeout):
            return None
        return self._result

    def done(self) -> bool:
        return self._event.is_set()


class _Item:
    __slots__ = ("key", "payload", "slot", "enqueued_at", "deadline_at",
                 "units")

    def __init__(self, key, payload, deadline_at, slot=None):
        self.key = key
        self.payload = payload
        # An externally-supplied slot lets the service's request_id dedup
        # ledger hand hedged submissions the SAME rendezvous object.
        self.slot = slot if slot is not None else Slot()
        self.enqueued_at = time.monotonic()
        self.deadline_at = deadline_at  # absolute monotonic, or None
        # Cost-priced priority input (service admission stamps it);
        # non-dict payloads (unit tests) price flat.
        units = 1.0
        if isinstance(payload, dict):
            try:
                units = max(0.0, float(payload.get("cost_units", 1.0)))
            except (TypeError, ValueError):
                units = 1.0
        self.units = units


class MicroBatcher:
    """Coalesce same-lane requests; flush on size or deadline; refill
    the device mid-flight.

    ``execute(lane, items)`` — or ``execute(lane, items, prepared)``
    when ``prepare`` is armed — runs on the executor thread with
    1..max_batch same-lane items and MUST set every item's slot: the
    batcher guarantees delivery attempts, the executor guarantees typed
    results.  ``prepare(lane, items)`` runs on the COLLECTOR thread
    (the host half of the pipeline: deadline shedding, pad-to-bucket
    stacking) and its return value is handed to ``execute`` — that
    overlap of host assembly with device execution IS the continuous
    batching win.
    """

    def __init__(self, execute, *, max_batch: int = 8,
                 max_delay_s: float = 0.005, max_queue: int = 64,
                 start: bool = True, lane_of=None, prepare=None,
                 pipeline_depth: int = 1):
        if max_batch < 1 or max_queue < 1 or max_delay_s < 0:
            raise ValueError("max_batch/max_queue >= 1, max_delay_s >= 0")
        self._execute = execute
        self._prepare = prepare
        self.lane_of = lane_of
        self.max_batch = max_batch
        self.max_delay_s = max_delay_s
        self.max_queue = max_queue
        # 0 = drain-between-flushes (the pre-continuous behavior, kept
        # as the A/B control arm); N >= 1 = up to N assembled flushes
        # may wait behind the executing one.
        self.pipeline_depth = max(0, int(pipeline_depth))
        self._cv = threading.Condition()
        self._lanes: OrderedDict[object, deque[_Item]] = OrderedDict()
        self._queued = 0
        self._staged: deque = deque()     # (lane, batch, prepared)
        self._exec_busy = False
        self._executing = 0               # items inside execute right now
        self._closed = False
        self._collector_done = False
        self._collector: threading.Thread | None = None
        self._executor: threading.Thread | None = None
        self._pad_px = 0                  # padded-but-unused pixels
        self._total_px = 0                # pixels across all flushes
        self._lane_labels: set[str] = set()
        # Legacy stats dict as a view over the obs registry
        # (pctpu_batcher_stats{key=...}); dict semantics unchanged.
        self.stats = obs_metrics.MirroredStats(obs_metrics.gauge(
            "pctpu_batcher_stats", "micro-batcher queue/flush counters",
            ("key",)), initial={
            "enqueued": 0, "refused": 0, "flushes": 0,
            "flushed_items": 0, "max_observed_depth": 0,
            "refills": 0, "lanes": 0, "pad_waste_ratio": 0.0})
        self._depth_gauge = obs_metrics.gauge(
            "pctpu_queue_depth", "pending requests in the batcher queue")
        self._lane_gauge = obs_metrics.gauge(
            "pctpu_lane_depth",
            "queued requests per shape-bucketed batcher lane", ("lane",))
        if start:
            self.start()

    # -- producer side -------------------------------------------------------
    def try_submit(self, key, payload, deadline_at=None,
                   slot: Slot | None = None) -> Slot | None:
        """Enqueue; returns the item's :class:`Slot`, or None when the
        queue is full or the batcher closed (the caller sheds load).
        ``slot`` substitutes a caller-owned rendezvous (dedup ledger)."""
        item = _Item(key, payload, deadline_at, slot=slot)
        lane = self.lane_of(key) if self.lane_of is not None else key
        with self._cv:
            if self._closed or self._queued >= self.max_queue:
                self.stats["refused"] += 1
                return None
            q = self._lanes.get(lane)
            if q is None:
                q = self._lanes[lane] = deque()
            q.append(item)
            self._queued += 1
            # The high-water mark counts EVERYTHING the batcher owns:
            # queued + staged + executing.  The old queue-only reading
            # undercounted under continuous batching, where a full
            # flush can be in the pipeline while the queue looks short.
            self.stats["max_observed_depth"] = max(
                self.stats["max_observed_depth"],
                self._queued + self._inflight_locked())
            self.stats["enqueued"] += 1
            self.stats["lanes"] = len(self._lanes)
            self._depth_gauge.set(self._queued)
            self._set_lane_depth(lane, len(q))
            self._cv.notify_all()
        return item.slot

    def depth(self) -> int:
        """QUEUED items — the admission-bound reading (in-flight items
        already left the queue; ``max_observed_depth`` counts them)."""
        with self._cv:
            return self._queued

    def _inflight_locked(self) -> int:
        return self._executing + sum(len(b) for _, b, _ in self._staged)

    def _set_lane_depth(self, lane, n: int) -> None:
        label = _lane_label(lane)
        if label not in self._lane_labels:
            if len(self._lane_labels) >= _LANE_LABEL_CAP:
                label = "overflow"
            self._lane_labels.add(label)
        if n > 0:
            self._lane_gauge.set(n, lane=label)
            self.stats[f"lane_depth:{label}"] = n  # stats-lock: held by callers (_cv)
        else:
            self._lane_gauge.remove(lane=label)
            self.stats.pop(f"lane_depth:{label}", None)
            self._lane_labels.discard(label)

    # -- worker side ---------------------------------------------------------
    def start(self) -> None:
        if self._collector is None or not self._collector.is_alive():
            self._collector_done = False
            self._collector = threading.Thread(
                target=self._collector_loop, name="pctpu-batcher-collect",
                daemon=True)
            self._collector.start()
        if self._executor is None or not self._executor.is_alive():
            self._executor = threading.Thread(
                target=self._executor_loop, name="pctpu-batcher-exec",
                daemon=True)
            self._executor.start()

    def close(self, drain: bool = True, timeout: float = 30.0) -> None:
        """Stop accepting; optionally wait for queue + pipeline to
        drain (both stages exit after flushing everything pending)."""
        with self._cv:
            self._closed = True
            self._cv.notify_all()
        if not drain:
            return
        deadline = time.monotonic() + timeout
        for t in (self._collector, self._executor):
            if t is not None and t.is_alive():
                t.join(max(0.0, deadline - time.monotonic()))

    # -- collector stage ------------------------------------------------------
    def _room_locked(self) -> bool:
        """May the collector assemble another flush right now?  Drain
        mode (depth 0) waits for an IDLE pipeline — the old barrier;
        pipelined mode keeps up to ``pipeline_depth`` flushes staged."""
        if self.pipeline_depth == 0:
            return not self._staged and not self._exec_busy
        return len(self._staged) < self.pipeline_depth

    def _pick_lane_locked(self, now: float):
        """``(due_lane_or_None, earliest_due_at)`` under the lock.

        A lane is due when its head aged past ``max_delay_s``, its head
        cannot afford the batching window (deadline sooner than the
        flush — flush NOW rather than gamble its remaining budget on
        hypothetical batch-mates), it holds a full batch, or the
        batcher is closed (final drain).  Among several due lanes the
        cheapest head wins (cost-priced priority), except a badly
        overdue head (STARVATION_MULT windows) which wins on age.
        """
        best = None
        best_score = None
        earliest = None
        for lane, q in self._lanes.items():
            head = q[0]
            flush_at = head.enqueued_at + self.max_delay_s
            if head.deadline_at is not None and head.deadline_at < flush_at:
                flush_at = head.enqueued_at
            if len(q) >= self.max_batch or self._closed:
                flush_at = now
            if flush_at <= now:
                overdue = (now - head.enqueued_at
                           > STARVATION_MULT * self.max_delay_s)
                score = ((0, head.enqueued_at, 0.0) if overdue
                         else (1, head.units, head.enqueued_at))
                if best_score is None or score < best_score:
                    best, best_score = lane, score
            elif earliest is None or flush_at < earliest:
                earliest = flush_at
        return best, earliest

    def _pop_batch_locked(self, lane) -> list[_Item]:
        q = self._lanes[lane]
        batch = [q.popleft() for _ in range(min(self.max_batch, len(q)))]
        if not q:
            del self._lanes[lane]
        self._queued -= len(batch)
        self.stats["flushes"] += 1  # stats-lock: held by caller (_cv)
        self.stats["flushed_items"] += len(batch)  # stats-lock: held by caller (_cv)
        self.stats["lanes"] = len(self._lanes)  # stats-lock: held by caller (_cv)
        self._depth_gauge.set(self._queued)
        self._set_lane_depth(lane, len(q) if q else 0)
        # Pad-waste accounting: a mixed-shape flush executes at the
        # lane's bucket extent; the difference is padded throwaway.
        lane_px = _area(lane)
        if lane_px:
            useful = sum(_area(it.key) or lane_px for it in batch)
            total = lane_px * len(batch)
            uniform = all(it.key == batch[0].key for it in batch)
            self._total_px += (useful if uniform else total)
            if not uniform:
                self._pad_px += total - useful
            self.stats["pad_waste_ratio"] = round(  # stats-lock: held by caller (_cv)
                self._pad_px / self._total_px, 4) if self._total_px else 0.0
        return batch

    def _collect(self):
        """Block until a flush is due AND the pipeline has room;
        returns (lane, items) or None when closed and drained."""
        with self._cv:
            while True:
                if not self._room_locked():
                    self._cv.wait(timeout=0.05)
                    continue
                if not self._queued:
                    if self._closed:
                        return None
                    self._cv.wait(timeout=0.1)
                    continue
                now = time.monotonic()
                lane, earliest = self._pick_lane_locked(now)
                if lane is not None:
                    batch = self._pop_batch_locked(lane)
                    self._cv.notify_all()
                    return lane, batch
                wait = 0.1 if earliest is None else min(
                    0.1, max(0.0, earliest - now))
                self._cv.wait(timeout=wait or 0.001)

    def _collector_loop(self) -> None:
        try:
            while True:
                got = self._collect()
                if got is None:
                    return
                lane, batch = got
                prepared = None
                if self._prepare is not None:
                    try:
                        # Host-side assembly OUTSIDE the lock: this is
                        # the work that overlaps the executing flush.
                        prepared = self._prepare(lane, batch)
                    except BaseException as e:  # noqa: BLE001
                        for it in batch:
                            if not it.slot.done():
                                it.slot.set(e)
                        continue
                with self._cv:
                    self._staged.append((lane, batch, prepared))
                    if self._exec_busy or len(self._staged) > 1:
                        # The device (executor) was already occupied
                        # when this flush became ready: a mid-flight
                        # refill, the no-barrier proof counter.
                        self.stats["refills"] += 1
                    self._cv.notify_all()
        finally:
            with self._cv:
                self._collector_done = True
                self._cv.notify_all()

    # -- executor stage --------------------------------------------------------
    def _executor_loop(self) -> None:
        while True:
            with self._cv:
                while not self._staged:
                    if self._closed and self._collector_done:
                        return
                    self._cv.wait(timeout=0.1)
                lane, batch, prepared = self._staged.popleft()
                self._exec_busy = True
                self._executing = len(batch)
                self._cv.notify_all()
            try:
                if self._prepare is not None:
                    self._execute(lane, batch, prepared)
                else:
                    self._execute(lane, batch)
            except BaseException as e:  # noqa: BLE001 — never kill the worker
                # The executor's contract is typed results; if it leaked
                # an exception anyway, fail its items rather than hanging
                # their waiters (and keep serving subsequent batches).
                for it in batch:
                    if not it.slot.done():
                        it.slot.set(e)
            finally:
                with self._cv:
                    self._exec_busy = False
                    self._executing = 0
                    self._cv.notify_all()
