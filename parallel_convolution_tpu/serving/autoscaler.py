"""Fleet autoscaling: the serving control loop over the replica router.

The r14 router made N replicas survive failure; N itself was still a
boot-time constant — sustained throughput capped by whatever the
operator guessed, idle replicas burning capacity overnight.  This module
closes the loop: scale the replica count from signals the stack already
exports, with warm-cache-aware placement so growing the pool never turns
into a compile storm.

**Signals** (gathered per tick from surfaces that already exist):

* queue pressure — per-replica ``queue_depth / queue_bound`` from the
  ``/readyz`` payload (the r13 probe), plus router-side ``in_flight``
  and progressive-stream occupancy;
* latency — the p99 of the ``pctpu_request_phase_seconds`` total-phase
  histogram (obs.metrics), when obs is on;
* health — ``ready`` flags and degrade tiers from the same probe (an
  unready replica contributes load but no capacity).

**Decision** (deterministic, clock-injectable — the breaker's pattern,
so the whole loop unit-tests without sleeping): pressure above
``up_pressure`` (or p99 above ``p99_up_ms``) for ``up_ticks``
CONSECUTIVE ticks scales up one replica; pressure below
``down_pressure`` for ``down_ticks`` consecutive ticks scales down one.
``down_ticks > up_ticks`` is the hysteresis asymmetry (grow fast, shrink
reluctantly), a mixed signal resets both streaks, and ``cooldown_s``
separates consecutive actions so the loop can never flap faster than
replicas warm.

**Warm placement** (the process-to-node-mapping analogue: put work next
to the state it needs): on scale-up the new replica is REGISTERED but
kept out of the ring while the router's key-config observatory replays
its future shard — exactly the configs whose consistent-hash home the
newcomer is about to become — through ``/v1/warm`` (→
``service.warmup`` → the plan cache + ``WarmEngine.warmup``).  Only
then do its vnodes join.  Post-join traffic for the remapped keys hits
warm executables; the per-key compile ledger stays flat (gated in
``scripts/scale_smoke.py``).

**Drain** (scale-down): ring removal first — the consistent-hash
property remaps ONLY the leaver's keys — then bounded in-flight drain,
then close; racing requests surface as the router's existing typed
retryable outcomes, never drops.  Victims are chosen LIFO among
scaler-added replicas: the boot pool is the operator's floor, and the
newest replica holds the least warm state worth keeping.

stdlib-only; jax stays inside the replicas.
"""

from __future__ import annotations

import itertools
import threading
import time

from parallel_convolution_tpu.obs import (
    events as obs_events, metrics as obs_metrics,
)

__all__ = ["AutoScaler", "ScaleDecision"]


class ScaleDecision:
    """One tick's verdict: ``action`` ∈ {up, down, hold} + why."""

    __slots__ = ("action", "reason", "signals")

    def __init__(self, action: str, reason: str, signals: dict):
        self.action = action
        self.reason = reason
        self.signals = signals

    def __repr__(self) -> str:
        return f"ScaleDecision({self.action!r}, {self.reason!r})"


class AutoScaler:
    """The control loop (see module docstring).

    ``factory(name) -> transport`` builds one new replica (an
    ``InProcessReplica`` for the CPU mesh, an ``HTTPReplica`` over a
    provisioner for deployment).  ``router`` is the live
    :class:`~parallel_convolution_tpu.serving.router.ReplicaRouter`.
    ``clock`` is injectable (cooldown/hysteresis are wall-free in
    tests); :meth:`tick` is the whole loop body — drive it from
    :meth:`start`'s thread in production or directly in tests.
    """

    def __init__(self, router, factory, *, min_replicas: int = 1,
                 max_replicas: int = 4, up_pressure: float = 0.5,
                 down_pressure: float = 0.05, up_ticks: int = 2,
                 down_ticks: int = 8, p99_up_ms: float | None = None,
                 cooldown_s: float = 5.0, interval_s: float = 0.5,
                 drain_s: float = 10.0, prewarm: bool = True,
                 clock=time.monotonic):
        if min_replicas < 1 or max_replicas < min_replicas:
            raise ValueError("need 1 <= min_replicas <= max_replicas")
        if up_ticks < 1 or down_ticks < 1:
            raise ValueError("up_ticks and down_ticks must be >= 1")
        self.router = router
        self.factory = factory
        self.min_replicas = int(min_replicas)
        self.max_replicas = int(max_replicas)
        self.up_pressure = float(up_pressure)
        self.down_pressure = float(down_pressure)
        self.up_ticks = int(up_ticks)
        self.down_ticks = int(down_ticks)
        self.p99_up_ms = p99_up_ms
        self.cooldown_s = float(cooldown_s)
        self.interval_s = float(interval_s)
        self.drain_s = float(drain_s)
        self.prewarm = bool(prewarm)
        self._clock = clock
        self._ids = itertools.count(1)
        self._up_streak = 0
        self._down_streak = 0
        self._last_change: float | None = None
        self._added: list[str] = []   # scaler-grown replicas, LIFO victims
        self._lock = threading.Lock()
        self.stats = obs_metrics.MirroredStats(obs_metrics.gauge(
            "pctpu_autoscaler_stats", "control-loop tick/action counters",
            ("key",)), initial={
            "ticks": 0, "scale_ups": 0, "scale_downs": 0, "holds": 0,
            "prewarmed_configs": 0, "replicas": 0,
        })
        self._closed = threading.Event()
        self._thread: threading.Thread | None = None
        # Last-tick cumulative bucket counts of the total-phase latency
        # histogram, per label set: the p99 signal is computed over the
        # DELTA (this tick's new samples only) — a process-lifetime
        # quantile goes numb as uptime grows (an overload must outweigh
        # every sample ever taken before it moves the lifetime p99).
        self._hist_last: dict[tuple, list[int]] = {}

    # -- signals --------------------------------------------------------------
    def _windowed_p99_ms(self) -> float | None:
        """p99 (ms) of the request-latency samples observed SINCE the
        last tick, pooled across backends (bucket-interpolated, the
        Prometheus estimate).  None until a tick-over-tick delta with
        samples exists."""
        snap = obs_metrics.snapshot()
        deltas: list[int] | None = None
        buckets: list[float] | None = None
        for m in snap.get("metrics", []):
            if m.get("name") != "pctpu_request_phase_seconds":
                continue
            for s in m.get("series", []):
                if s.get("labels", {}).get("phase") != "total":
                    continue
                key = tuple(sorted(s.get("labels", {}).items()))
                counts = list(s.get("counts", ()))
                prev = self._hist_last.get(key)
                self._hist_last[key] = counts
                if prev is None or len(prev) != len(counts):
                    continue   # first sight of this series: no window
                d = [max(0, a - b) for a, b in zip(counts, prev)]
                if buckets is None:
                    buckets = list(s.get("buckets", ()))
                    deltas = [0] * len(d)
                if len(d) == len(deltas):
                    deltas = [x + y for x, y in zip(deltas, d)]
        if not deltas or not buckets or sum(deltas) == 0:
            return None
        total = sum(deltas)
        rank = 0.99 * total
        cum = 0.0
        for i, c in enumerate(deltas):
            prev_cum = cum
            cum += c
            if cum >= rank and c > 0:
                if i >= len(buckets):
                    return buckets[-1] * 1e3   # +Inf bucket: floor
                lo = buckets[i - 1] if i > 0 else 0.0
                hi = buckets[i]
                return (lo + (hi - lo) * (rank - prev_cum) / c) * 1e3
        return buckets[-1] * 1e3

    def signals(self) -> dict:
        """One tick's inputs, from surfaces the stack already exports."""
        snap = self.router.snapshot()
        reps = snap.get("replicas", {})
        n = len(reps)
        live = 0
        in_flight = 0
        queue_depth = 0
        queue_bound = 0
        degraded = 0
        for rep in reps.values():
            in_flight += int(rep.get("in_flight") or 0)
            if rep.get("ready"):
                live += 1
                queue_depth += int(rep.get("queue_depth") or 0)
                queue_bound += int(rep.get("queue_bound") or 0)
                if rep.get("degraded"):
                    degraded += 1
        # Pressure: outstanding work over the LIVE pool's admission
        # capacity.  queue_bound can be unknown (a replica not yet
        # polled) — fall back to counting in-flight against a nominal
        # per-replica depth so a cold loop still sees overload.
        capacity = queue_bound if queue_bound > 0 else 64 * max(1, live)
        pressure = (queue_depth + in_flight) / max(1, capacity)
        p99_ms = None
        if obs_metrics.enabled():
            p99_ms = self._windowed_p99_ms()
        return {
            "replicas": n, "live": live, "in_flight": in_flight,
            "queue_depth": queue_depth, "queue_bound": queue_bound,
            "pressure": round(pressure, 4), "degraded": degraded,
            "p99_ms": round(p99_ms, 3) if p99_ms is not None else None,
        }

    # -- the decision ---------------------------------------------------------
    def decide(self, sig: dict) -> ScaleDecision:
        """Pure hysteresis walk over one tick's signals (mutates only
        the streak counters — callers drive it with synthetic signals
        in tests)."""
        over = sig["pressure"] >= self.up_pressure
        reason = f"pressure {sig['pressure']} >= {self.up_pressure}"
        if (not over and self.p99_up_ms is not None
                and sig.get("p99_ms") is not None
                and sig["p99_ms"] >= self.p99_up_ms):
            over = True
            reason = f"p99 {sig['p99_ms']}ms >= {self.p99_up_ms}ms"
        under = not over and sig["pressure"] <= self.down_pressure
        if over:
            self._up_streak += 1
            self._down_streak = 0
        elif under:
            self._down_streak += 1
            self._up_streak = 0
        else:
            # The dead band between the thresholds: a mixed signal
            # resets BOTH streaks — hysteresis means N consecutive
            # agreeing ticks, not N eventually.
            self._up_streak = self._down_streak = 0
        now = self._clock()
        if (self._last_change is not None
                and now - self._last_change < self.cooldown_s):
            return ScaleDecision("hold", "cooldown", sig)
        if (over and self._up_streak >= self.up_ticks
                and sig["replicas"] < self.max_replicas):
            return ScaleDecision("up", reason, sig)
        if (under and self._down_streak >= self.down_ticks
                and sig["replicas"] > self.min_replicas):
            return ScaleDecision(
                "down",
                f"pressure {sig['pressure']} <= {self.down_pressure} "
                f"for {self._down_streak} ticks", sig)
        return ScaleDecision("hold", "within band", sig)

    # -- actions --------------------------------------------------------------
    def scale_up(self) -> str:
        """Grow the pool by one WARM replica; returns its name."""
        name = f"as{next(self._ids)}"
        transport = self.factory(name)
        prewarmed = 0
        registered = False
        try:
            self.router.add_replica(transport, join_ring=False)
            registered = True
            if self.prewarm:
                configs = self.router.shard_configs(name)
                if configs:
                    status, body = transport.warm(configs)
                    if status == 200:
                        prewarmed = len(configs)
                    # A failed pre-warm is a WARNING, not a veto: a cold
                    # join serves correctly (it just compiles on demand)
                    # while refusing to join under load makes overload
                    # worse.
            self.router.join_ring(name)
        except Exception:
            # A half-added replica must not linger registered-but-dead —
            # but roll back ONLY what this call registered: a duplicate-
            # name failure means someone ELSE's healthy replica holds
            # the name, and removing it would tear down live capacity.
            if registered:
                try:
                    self.router.remove_replica(name, drain_s=0.0)
                except Exception:  # noqa: BLE001 — best-effort rollback
                    pass
            else:
                try:
                    transport.close()
                except Exception:  # noqa: BLE001 — best-effort teardown
                    pass
            raise
        with self._lock:
            self._added.append(name)
            self.stats["scale_ups"] += 1
            self.stats["prewarmed_configs"] += prewarmed
        self._last_change = self._clock()
        if obs_metrics.enabled():
            obs_events.emit("autoscale", action="up", replica=name,
                            prewarmed=prewarmed,
                            replicas=len(self.router.ring.members()))
        return name

    def scale_down(self) -> str | None:
        """Shrink the pool by one replica (LIFO among scaler-added;
        never below the boot pool); returns the drained name."""
        with self._lock:
            victim = self._added.pop() if self._added else None
        if victim is None:
            # The scaler never shrinks the operator's boot pool: min
            # replicas is a floor the decision already enforces, and the
            # boot replicas may be the only ones with special placement.
            return None
        info = self.router.remove_replica(victim, drain_s=self.drain_s)
        with self._lock:
            self.stats["scale_downs"] += 1
        self._last_change = self._clock()
        if obs_metrics.enabled():
            obs_events.emit("autoscale", action="down", replica=victim,
                            drained=bool(info.get("drained")),
                            replicas=len(self.router.ring.members()))
        return victim

    # -- the loop -------------------------------------------------------------
    def tick(self) -> ScaleDecision:
        """One control-loop iteration: gather → decide → act."""
        sig = self.signals()
        decision = self.decide(sig)
        with self._lock:
            self.stats["ticks"] += 1
            self.stats["replicas"] = sig["replicas"]
        if decision.action == "up":
            self.scale_up()
            self._up_streak = 0
        elif decision.action == "down":
            if self.scale_down() is None:
                decision = ScaleDecision("hold", "no scaler-added victim",
                                         sig)
            self._down_streak = 0
        else:
            with self._lock:
                self.stats["holds"] += 1
        if obs_metrics.enabled() and decision.action != "hold":
            obs_events.emit("autoscale", action="decision",
                            verdict=decision.action, reason=decision.reason,
                            **{k: v for k, v in sig.items()
                               if v is not None})
        return decision

    def start(self) -> None:
        """Drive :meth:`tick` on ``interval_s`` from a daemon thread."""
        if self._thread is None or not self._thread.is_alive():
            self._closed.clear()
            self._thread = threading.Thread(
                target=self._loop, name="pctpu-autoscaler", daemon=True)
            self._thread.start()

    def _loop(self) -> None:
        while not self._closed.wait(self.interval_s):
            try:
                self.tick()
            except Exception as e:  # noqa: BLE001 — the loop must survive
                if obs_metrics.enabled():
                    obs_events.emit("autoscale", action="error",
                                    error=repr(e)[:200])

    def close(self) -> None:
        self._closed.set()
        t = self._thread
        if t is not None and t.is_alive():
            t.join(5.0)

    def snapshot(self) -> dict:
        with self._lock:
            return {"stats": dict(self.stats),
                    "added": list(self._added),
                    "streaks": {"up": self._up_streak,
                                "down": self._down_streak},
                    "bounds": {"min": self.min_replicas,
                               "max": self.max_replicas}}
