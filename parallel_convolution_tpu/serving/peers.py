"""Sharded control plane — N active routers over one partitioned ring
(round 21).

Round 19 made the single router crash-safe (WAL + fenced takeover);
this module removes it as the single point of failure AND the
throughput ceiling, the same way the stencil papers decompose the
domain: the consistent-hash key space is partitioned into ``n_shards``
contiguous ownership units, each owned by one ACTIVE router with its
own WAL lineage and epoch (``serving/wal.py`` with ``shard=``), so
recovery of one shard never blocks — or quarantines — the others.

Pieces:

* :func:`shard_of` — the stable key→shard partition (SHA-1, like
  :class:`HashRing`'s placement, so every router and client computes
  the same answer with no coordination).
* :class:`ShardMap` — who owns which shard, at which epoch.  The map
  VERSION is the sum of the per-shard epochs: monotonic under
  takeovers (a takeover bumps that shard's epoch), identical on every
  converged peer, and needs no counter coordination.  Merging is
  per-shard higher-epoch-wins — the WAL lineage's fencing epoch is
  the single source of ownership truth.
* :class:`DebtLog` — seq-numbered tenant-debt deltas for fleet-wide
  quota enforcement: every local charge/refund appends ``(seq,
  tenant, delta)``; peers pull deltas since their cursor and ABSORB
  them into their own buckets (no journal echo, no re-replication).
  A cursor that fell off the bounded log gets a cumulative-totals
  reset instead of silent loss.
* :class:`InProcessPeer` / :class:`HTTPPeer` — the peer links (the
  drills' in-process twin and the deployment's ``POST /v1/peersync``).
* :class:`ShardRouter` — one active router process: a
  :class:`~parallel_convolution_tpu.serving.router.ReplicaRouter` per
  OWNED shard (each over its own WAL lineage, all sharing one
  :class:`TenantQuotas`), typed ``wrong_shard`` (421, retryable)
  redirects for keys it does not own, versioned anti-entropy pulls
  from its peers, and — the headline — cross-shard fenced TAKEOVER:
  when a peer stops answering, the deterministic successor re-opens
  each orphaned WAL lineage (the r19 takeover: epoch bump, per-shard
  ``/v1/fence`` sweep, zombie writes rejected typed ``stale_epoch``,
  interrupted converge jobs resumed byte-identically from their
  newest durable token) while every other shard keeps serving.
* :class:`ShardClient` — the client half of the contract: fetch the
  version-stamped shard map from any router, route straight to the
  owner, and on a ``wrong_shard``/``stale_epoch`` typed reject refresh
  the map and retry — a takeover is client-observable, never a client
  failure.

stdlib-only, jax-free, like the rest of the control plane.
"""

from __future__ import annotations

import hashlib
import json
import threading
import time
from collections import deque
from pathlib import Path

from parallel_convolution_tpu.obs import events as obs_events
from parallel_convolution_tpu.obs import metrics as obs_metrics
from parallel_convolution_tpu.serving.router import (
    ReplicaRouter,
    TenantQuotas,
    route_key,
)

__all__ = ["DebtLog", "HTTPPeer", "InProcessPeer", "ShardClient",
           "ShardMap", "ShardRouter", "shard_of", "wal_path"]

# Typed rejects that tell a shard-aware client its routing state is
# stale (refresh the map and retry) rather than "the job failed".
_REROUTE_REJECTS = frozenset({"wrong_shard", "stale_epoch"})


def shard_of(key: str, n_shards: int) -> str:
    """The stable key→shard assignment.  SHA-1 over the route key (the
    same digest family as HashRing placement): every router and client
    computes the identical partition with no coordination."""
    h = hashlib.sha1(str(key).encode("utf-8")).digest()
    return str(int.from_bytes(h[:8], "big") % max(1, int(n_shards)))


def wal_path(state_dir, shard: str) -> Path:
    """One shard's WAL lineage file.  The name ends ``.wal`` on
    purpose: RouterWAL refuses lineage names with a trailing numeric
    suffix (they collide with rotated-generation naming when sibling
    lineages share the directory)."""
    return Path(state_dir) / f"shard-{shard}.wal"


class ShardMap:
    """Who owns which shard, at which fencing epoch.

    ``version`` is DERIVED: the sum of per-shard epochs.  Takeovers
    bump the orphaned shard's epoch (the r19 WAL takeover), so the
    version is monotonic, convergent, and coordination-free; two peers
    with the same version hold the same ownership map (per-shard
    higher-epoch-wins merging makes epoch the single authority)."""

    def __init__(self, n_shards: int):
        self.n_shards = int(n_shards)
        # shard -> {"owner": router name, "addr": url|None, "epoch": int}
        self.shards: dict[str, dict] = {}
        self._lock = threading.Lock()

    def seed(self, shard: str, owner: str, addr=None, epoch: int = 0):
        with self._lock:
            self.shards[str(shard)] = {
                "owner": str(owner),
                "addr": None if addr is None else str(addr),
                "epoch": int(epoch)}

    def version(self) -> int:
        with self._lock:
            return sum(int(e.get("epoch", 0)) for e in
                       self.shards.values())

    def owner(self, shard: str) -> dict | None:
        with self._lock:
            e = self.shards.get(str(shard))
            return None if e is None else dict(e)

    def set_owner(self, shard: str, owner: str, epoch: int,
                  addr=None) -> bool:
        """Record ``owner`` at ``epoch`` for ``shard`` iff ``epoch``
        is NEWER than what we hold (epoch is the authority — a stale
        gossip echo can never roll ownership back).  Returns True if
        the map changed."""
        s = str(shard)
        with self._lock:
            cur = self.shards.get(s)
            if cur is not None and int(epoch) <= int(cur["epoch"]):
                return False
            self.shards[s] = {"owner": str(owner),
                              "addr": (None if addr is None
                                       else str(addr)),
                              "epoch": int(epoch)}
            return True

    def merge(self, wire: dict) -> bool:
        """Fold a peer's map in (per-shard higher-epoch-wins).
        Returns True if anything changed."""
        changed = False
        for shard, entry in dict(wire.get("shards") or {}).items():
            try:
                changed |= self.set_owner(
                    shard, str(entry.get("owner", "")),
                    int(entry.get("epoch", 0)),
                    addr=entry.get("addr"))
            except (TypeError, ValueError):
                continue
        return changed

    def to_wire(self) -> dict:
        with self._lock:
            return {
                "version": sum(int(e.get("epoch", 0))
                               for e in self.shards.values()),
                "n_shards": self.n_shards,
                "shards": {s: dict(e) for s, e in self.shards.items()},
            }


class DebtLog:
    """Seq-numbered tenant-debt deltas, one log per ORIGIN router.

    Every local quota charge (+) / refund (−) appends ``(seq, tenant,
    delta)``.  Peers pull ``since(cursor)`` and absorb the deltas into
    their own buckets — fleet-wide quota enforcement without a shared
    store.  The log is count-bounded; a cursor older than the retained
    window gets a RESET reply carrying the cumulative per-tenant
    totals, from which the puller reconstructs the missed difference
    (it tracks what it already applied per origin)."""

    def __init__(self, cap: int = 4096):
        self.cap = int(cap)
        self._deltas: deque = deque()   # (seq, tenant, delta)
        self._seq = 0
        self._totals: dict[str, float] = {}
        self._lock = threading.Lock()

    def record(self, tenant: str, delta: float) -> int:
        with self._lock:
            self._seq += 1
            self._deltas.append((self._seq, str(tenant), float(delta)))
            t = str(tenant)
            self._totals[t] = self._totals.get(t, 0.0) + float(delta)
            while len(self._deltas) > self.cap:
                self._deltas.popleft()
            return self._seq

    def since(self, cursor: int) -> dict:
        """The anti-entropy reply body for one origin: either the
        deltas after ``cursor``, or a totals RESET when the cursor
        fell off the bounded window."""
        c = int(cursor)
        with self._lock:
            floor = self._deltas[0][0] - 1 if self._deltas else self._seq
            if c < floor:
                return {"reset": True, "seq": self._seq,
                        "totals": {t: round(v, 9)
                                   for t, v in self._totals.items()}}
            return {"reset": False, "seq": self._seq,
                    "deltas": [[s, t, round(d, 9)]
                               for (s, t, d) in self._deltas if s > c]}

    def snapshot(self) -> dict:
        with self._lock:
            return {"seq": self._seq, "retained": len(self._deltas),
                    "tenants": len(self._totals)}


class InProcessPeer:
    """A peer link to another :class:`ShardRouter` in the same process
    (the drills' transport).  ``kill()`` makes every sync raise —
    the in-process stand-in for SIGKILL."""

    def __init__(self, target):
        self._target = target
        self.name = target.name
        self._dead = False

    def kill(self) -> None:
        self._dead = True

    def sync(self, payload: dict) -> dict:
        if self._dead or getattr(self._target, "_dead", False):
            raise ConnectionError(f"peer {self.name} is dead")
        return self._target.handle_peersync(dict(payload))

    def shardmap(self) -> dict:
        if self._dead or getattr(self._target, "_dead", False):
            raise ConnectionError(f"peer {self.name} is dead")
        return self._target.shardmap_wire()


class HTTPPeer:
    """A peer link over the existing HTTP plane (``POST /v1/peersync``
    + ``GET /v1/shardmap`` on the peer's router frontend)."""

    def __init__(self, name: str, url: str, timeout: float = 2.0):
        self.name = str(name)
        self.base = url.rstrip("/")
        self.timeout = float(timeout)

    def sync(self, payload: dict) -> dict:
        import urllib.request

        req = urllib.request.Request(
            self.base + "/v1/peersync",
            data=json.dumps(payload).encode("utf-8"),
            headers={"Content-Type": "application/json"})
        with urllib.request.urlopen(req, timeout=self.timeout) as r:
            return json.loads(r.read())

    def shardmap(self) -> dict:
        import urllib.request

        with urllib.request.urlopen(self.base + "/v1/shardmap",
                                    timeout=self.timeout) as r:
            return json.loads(r.read())


class ShardRouter:
    """One active router in an N-router fleet (see module docstring).

    ``transports`` is the replica pool (shared by every owned shard's
    sub-router — the DATA plane is common; only control-plane
    ownership is partitioned).  ``owned`` is the iterable of shard
    labels this router boots owning; ``assignments`` maps EVERY shard
    label to its boot owner name so redirects can name the owner
    before the first peer sync.  ``peers`` are the links
    (:class:`InProcessPeer` / :class:`HTTPPeer`).  Each owned shard
    gets its own WAL lineage at ``wal_path(state_dir, shard)`` —
    constructing the sub-router over an existing lineage IS the r19
    fenced takeover.
    """

    def __init__(self, name: str, transports, *, n_shards: int,
                 owned, state_dir, assignments=None, addrs=None,
                 quotas: TenantQuotas | None = None, pricer=None,
                 peers=(), sync_interval_s: float = 0.25,
                 suspect_after: int = 3, start_sync: bool = True,
                 wal_fsync: bool = True, clock=time.monotonic,
                 **router_kwargs):
        self.name = str(name)
        self.n_shards = int(n_shards)
        self.state_dir = Path(state_dir)
        self.quotas = quotas
        self.clock = clock
        self.peers = list(peers)
        self.sync_interval_s = float(sync_interval_s)
        self.suspect_after = int(suspect_after)
        self._addrs = dict(addrs or {})
        self._dead = False
        self._lock = threading.Lock()
        self._closed = threading.Event()
        self.debts = DebtLog()
        # Per-origin pull cursors + per-origin/tenant applied sums (the
        # reset-reply reconstruction input).
        self._cursors: dict[str, int] = {}
        self._applied: dict[str, dict[str, float]] = {}
        self._misses: dict[str, int] = {}
        self._taken_over: set[str] = set()
        self.map = ShardMap(self.n_shards)
        for shard, owner in dict(assignments or {}).items():
            self.map.seed(shard, owner, addr=self._addrs.get(owner))
        self.stats = {"peer_syncs": 0, "peer_sync_errors": 0,
                      "wrong_shard": 0, "takeovers": 0,
                      "debt_deltas_absorbed": 0, "map_merges": 0}
        self._transports = list(transports)
        self._router_kwargs = dict(router_kwargs)
        self._pricer = pricer
        self._wal_fsync = bool(wal_fsync)
        self._sub: dict[str, ReplicaRouter] = {}
        for shard in owned:
            self._open_shard(str(shard))
        self._publish_map()
        self._sync_thread: threading.Thread | None = None
        if start_sync and self.peers:
            self.start_sync()

    # -- shard lifecycle ------------------------------------------------------
    def _open_shard(self, shard: str) -> ReplicaRouter:
        """Construct the sub-router that owns ``shard`` — over a fresh
        lineage at boot, over an ORPHANED one during takeover (the r19
        fenced recovery runs inside ReplicaRouter._recover: epoch
        bump past the WAL's and every replica's fence, per-shard fence
        sweep, durable jobs re-seeded)."""
        from parallel_convolution_tpu.serving.wal import RouterWAL

        wal = RouterWAL(wal_path(self.state_dir, shard), shard=shard,
                        fsync=self._wal_fsync)
        sub = ReplicaRouter(
            self._transports, quotas=self.quotas, pricer=self._pricer,
            shard=shard, wal=wal, on_debt=self._on_debt,
            clock=self.clock, **self._router_kwargs)
        self._sub[shard] = sub
        self.map.set_owner(shard, self.name, sub.epoch,
                           addr=self._addrs.get(self.name))
        return sub

    def _publish_map(self) -> None:
        """Push the current map version onto every owned sub-router so
        response ``router:`` stamps carry it."""
        v = self.map.version()
        for sub in self._sub.values():
            sub.map_version = v

    def _on_debt(self, tenant: str, delta: float) -> None:
        """Every local quota charge/refund lands in the origin debt
        log for the peers to pull (fleet-wide quota enforcement)."""
        self.debts.record(tenant, delta)

    # -- the serving surface --------------------------------------------------
    def _route_shard(self, body: dict) -> str:
        return shard_of(route_key(dict(body)), self.n_shards)

    def _wrong_shard_wire(self, body: dict, shard: str) -> dict:
        ent = self.map.owner(shard) or {}
        with self._lock:
            self.stats["wrong_shard"] += 1
        return {
            "ok": False, "rejected": "wrong_shard", "retryable": True,
            "request_id": str(body.get("request_id") or ""),
            "shard": shard, "owner": ent.get("owner", ""),
            "owner_addr": ent.get("addr"),
            "map_version": self.map.version(),
            "detail": f"key shard {shard} is owned by "
                      f"{ent.get('owner', '?')!r}, not {self.name!r}; "
                      "refresh /v1/shardmap and retry at the owner",
        }

    def request(self, body: dict, timeout: float | None = None,
                tenant: str | None = None):
        if self._dead:
            raise ConnectionError(f"router {self.name} is dead")
        shard = self._route_shard(body)
        sub = self._sub.get(shard)
        if sub is None:
            return 421, self._wrong_shard_wire(body, shard)
        return sub.request(body, timeout=timeout, tenant=tenant)

    def converge(self, body: dict, timeout: float | None = None,
                 tenant: str | None = None):
        if self._dead:
            raise ConnectionError(f"router {self.name} is dead")
        shard = self._route_shard(body)
        sub = self._sub.get(shard)
        if sub is None:
            wire = self._wrong_shard_wire(body, shard)
            wire["kind"] = "rejected"
            return 421, iter([wire])
        return sub.converge(body, timeout=timeout, tenant=tenant)

    # -- peer anti-entropy ----------------------------------------------------
    def shardmap_wire(self) -> dict:
        """``GET /v1/shardmap``: the version-stamped ownership map any
        client can fetch from any router."""
        # Refresh our own shards' epochs first (cheap; epochs only
        # move on takeover but the map might have been seeded at 0).
        for shard, sub in self._sub.items():
            self.map.set_owner(shard, self.name, sub.epoch,
                               addr=self._addrs.get(self.name))
        wire = self.map.to_wire()
        wire["ok"] = True
        wire["from"] = self.name
        return wire

    def handle_peersync(self, payload: dict) -> dict:
        """``POST /v1/peersync``: a peer's versioned anti-entropy pull.
        The reply carries our map and, for every origin the caller
        sent a cursor for (plus ourselves), the debt deltas since it."""
        cursors = dict(payload.get("cursors") or {})
        out_debts = {self.name:
                     self.debts.since(int(cursors.get(self.name, 0)))}
        return {"ok": True, "from": self.name,
                "map": self.shardmap_wire(), "debts": out_debts}

    def sync_now(self) -> None:
        """One synchronous anti-entropy pass over every peer (the
        drills call this; the background thread just loops it)."""
        for peer in list(self.peers):
            try:
                reply = peer.sync({
                    "from": self.name,
                    "cursors": {peer.name:
                                self._cursors.get(peer.name, 0)}})
            except Exception as e:  # noqa: BLE001 — a dead/slow peer
                self._note_miss(peer, repr(e)[:200])
                continue
            with self._lock:
                self._misses[peer.name] = 0
                self.stats["peer_syncs"] += 1
            self._absorb(reply)

    def _note_miss(self, peer, detail: str) -> None:
        with self._lock:
            self.stats["peer_sync_errors"] += 1
            n = self._misses.get(peer.name, 0) + 1
            self._misses[peer.name] = n
        if n == self.suspect_after and obs_metrics.enabled():
            obs_events.emit("shard", event="peer_suspect",
                            peer=peer.name, misses=n,
                            detail=detail)
        if n >= self.suspect_after:
            self._takeover_dead_peer(peer.name)

    def _absorb(self, reply: dict) -> None:
        """Fold one peer's sync reply in: map merge (per-shard
        higher-epoch-wins) + debt-delta absorption into the SHARED
        quota buckets (never echoing our own origin)."""
        before = self.map.version()
        if self.map.merge(dict(reply.get("map") or {})):
            with self._lock:
                self.stats["map_merges"] += 1
            after = self.map.version()
            self._publish_map()
            if after != before and obs_metrics.enabled():
                obs_events.emit("shard", event="map_version",
                                version=after, router=self.name)
        for origin, body in dict(reply.get("debts") or {}).items():
            if origin == self.name:
                continue
            self._absorb_debts(str(origin), dict(body or {}))

    def _absorb_debts(self, origin: str, body: dict) -> None:
        applied = self._applied.setdefault(origin, {})
        n_absorbed = 0
        if body.get("reset"):
            # The bounded log no longer holds our cursor's suffix:
            # reconstruct the missed difference from cumulative totals
            # (what the origin charged overall minus what we already
            # applied for it).
            for tenant, total in dict(body.get("totals") or {}).items():
                diff = float(total) - applied.get(str(tenant), 0.0)
                if abs(diff) < 1e-12:
                    continue
                if self.quotas is not None:
                    self.quotas.absorb(str(tenant), diff)
                applied[str(tenant)] = float(total)
                n_absorbed += 1
            self._cursors[origin] = int(body.get("seq", 0))
        else:
            cur = self._cursors.get(origin, 0)
            for seq, tenant, delta in list(body.get("deltas") or ()):
                if int(seq) <= cur:
                    continue
                if self.quotas is not None:
                    self.quotas.absorb(str(tenant), float(delta))
                applied[str(tenant)] = (applied.get(str(tenant), 0.0)
                                        + float(delta))
                cur = int(seq)
                n_absorbed += 1
            self._cursors[origin] = max(cur,
                                        int(body.get("seq", cur)))
        if n_absorbed:
            with self._lock:
                self.stats["debt_deltas_absorbed"] += n_absorbed
            if obs_metrics.enabled():
                obs_events.emit("shard", event="peer_sync",
                                origin=origin, absorbed=n_absorbed,
                                router=self.name)

    # -- cross-shard fenced takeover ------------------------------------------
    def _takeover_dead_peer(self, peer_name: str) -> None:
        """A peer stopped answering: the deterministic successor of
        each of its shards re-opens the orphaned WAL lineage (the r19
        fenced takeover).  Determinism (shard index mod survivor
        count over the sorted survivor names) keeps two survivors
        from racing for the same lineage in the common case; the WAL
        sidecar flock makes the race SAFE regardless — the loser's
        construction simply observes the winner's rotation."""
        wire = self.map.to_wire()
        orphaned = sorted(
            s for s, e in wire["shards"].items()
            if e.get("owner") == peer_name and s not in self._sub)
        if not orphaned:
            return
        with self._lock:
            suspected = {p for p, n in self._misses.items()
                         if n >= self.suspect_after}
        survivors = sorted({self.name}
                           | {p.name for p in self.peers
                              if p.name not in suspected})
        for shard in orphaned:
            successor = survivors[int(shard) % len(survivors)]
            if successor != self.name:
                continue
            if shard in self._taken_over or shard in self._sub:
                continue
            self.takeover(shard, from_owner=peer_name)

    def takeover(self, shard: str, from_owner: str = "") -> None:
        """Fenced takeover of one orphaned shard lineage: re-open its
        WAL (epoch bump past the dead owner's), sweep the per-shard
        fence across the replicas, re-seed its durable jobs — the
        exact r19 single-lineage drill, scoped so every OTHER shard
        keeps serving uninterrupted."""
        shard = str(shard)
        with self._lock:
            if shard in self._sub or shard in self._taken_over:
                return
            self._taken_over.add(shard)
        t0 = time.perf_counter()
        sub = self._open_shard(shard)
        self._publish_map()
        with self._lock:
            self.stats["takeovers"] += 1
        if obs_metrics.enabled():
            obs_metrics.counter(
                "pctpu_shard_takeovers_total",
                "orphaned shard lineages taken over by a surviving "
                "peer", ("shard",)).inc(shard=shard)
            obs_events.emit(
                "shard", event="takeover", shard=shard,
                router=self.name, from_owner=from_owner,
                epoch=sub.epoch, map_version=self.map.version(),
                jobs_restored=sub.recovery.get("jobs_restored", 0),
                dur_s=round(time.perf_counter() - t0, 4))

    # -- background sync ------------------------------------------------------
    def start_sync(self) -> None:
        if (self._sync_thread is None
                or not self._sync_thread.is_alive()):
            self._sync_thread = threading.Thread(
                target=self._sync_loop,
                name=f"pctpu-peer-sync-{self.name}", daemon=True)
            self._sync_thread.start()

    def _sync_loop(self) -> None:
        while not self._closed.wait(self.sync_interval_s):
            if self._dead:
                return
            self.sync_now()

    # -- operator surface / lifecycle -----------------------------------------
    def readyz(self):
        subs = {s: r.readyz() for s, r in self._sub.items()}
        ready = any(status == 200 for status, _ in subs.values())
        return (200 if ready else 503), {
            "ready": ready, "router": self.name,
            "owned_shards": sorted(self._sub),
            "map_version": self.map.version(),
            "shards": {s: payload for s, (_, payload) in subs.items()},
        }

    def snapshot(self) -> dict:
        with self._lock:
            stats = dict(self.stats)
            misses = dict(self._misses)
        return {
            "name": self.name,
            "owned_shards": sorted(self._sub),
            "map": self.map.to_wire(),
            "peers": {p.name: {"misses": misses.get(p.name, 0)}
                      for p in self.peers},
            "debt_log": self.debts.snapshot(),
            "shard_router": stats,
            "shards": {s: r.snapshot() for s, r in self._sub.items()},
        }

    def sub(self, shard: str) -> ReplicaRouter:
        """The owned shard's sub-router (drills reach through it)."""
        return self._sub[str(shard)]

    def hard_stop(self) -> None:
        """The in-process stand-in for SIGKILL: stop serving and
        RELEASE the WAL flocks (a dead process's locks vanish) without
        any graceful fencing — the successor must win ownership via
        the r19 takeover, not via a polite handoff."""
        self._dead = True
        self._closed.set()
        for sub in self._sub.values():
            try:
                sub.close(close_replicas=False)
            except Exception:  # noqa: BLE001 — already-dying state
                pass

    def close(self, close_replicas: bool = True) -> None:
        self._closed.set()
        t = self._sync_thread
        if t is not None and t.is_alive():
            t.join(5.0)
        for sub in self._sub.values():
            sub.close(close_replicas=False)
        if close_replicas:
            for tr in self._transports:
                try:
                    tr.close()
                except Exception:  # noqa: BLE001 — best-effort teardown
                    pass


class ShardClient:
    """The shard-aware client: fetch the version-stamped map from any
    router, route to the owner, and on a ``wrong_shard`` /
    ``stale_epoch`` typed reject refresh the map and retry (bounded).
    ``routers`` are the in-process :class:`ShardRouter`s (the drills'
    transport; the HTTP twin is loadgen's multi-URL mode)."""

    def __init__(self, routers, max_redirects: int = 4):
        self._routers = {r.name: r for r in routers}
        self.max_redirects = int(max_redirects)
        self.map_version = -1
        self._map: dict = {}
        self.refreshes = 0
        self.refresh()

    def refresh(self) -> None:
        for r in self._routers.values():
            if getattr(r, "_dead", False):
                continue
            try:
                wire = r.shardmap_wire()
            except Exception:  # noqa: BLE001 — a dead router
                continue
            if int(wire.get("version", -1)) >= self.map_version:
                self.map_version = int(wire.get("version", -1))
                self._map = dict(wire.get("shards") or {})
            self.refreshes += 1
            return
        raise ConnectionError("no live router to fetch the shard "
                              "map from")

    def _target(self, body: dict):
        n = max(1, len(self._map) or max(
            (r.n_shards for r in self._routers.values()), default=1))
        shard = shard_of(route_key(dict(body)), n)
        owner = (self._map.get(shard) or {}).get("owner", "")
        r = self._routers.get(owner)
        if r is None or getattr(r, "_dead", False):
            live = [x for x in self._routers.values()
                    if not getattr(x, "_dead", False)]
            if not live:
                raise ConnectionError("no live router")
            r = live[0]
        return r

    def request(self, body: dict, timeout: float | None = None,
                tenant: str | None = None):
        status = 503
        wire: dict = {}
        for _ in range(self.max_redirects):
            try:
                status, wire = self._target(body).request(
                    dict(body), timeout=timeout, tenant=tenant)
            except ConnectionError:
                self.refresh()
                continue
            if wire.get("rejected") in _REROUTE_REJECTS:
                self.refresh()
                continue
            return status, wire
        return status, wire

    def converge(self, body: dict, timeout: float | None = None,
                 tenant: str | None = None):
        status = 503
        rows = iter(())
        for _ in range(self.max_redirects):
            try:
                status, rows = self._target(body).converge(
                    dict(body), timeout=timeout, tenant=tenant)
            except ConnectionError:
                self.refresh()
                continue
            if status != 200:
                first = next(iter(rows), None)
                if (first is not None and first.get("rejected")
                        in _REROUTE_REJECTS):
                    self.refresh()
                    continue
                return status, iter(() if first is None else (first,))
            return status, rows
        return status, rows
