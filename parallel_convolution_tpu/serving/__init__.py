"""Serving layer: micro-batched convolution as a long-lived service.

Every pre-round-8 entry point (CLI, bench.py, scripts/) is a one-shot
batch run that pays compile + mesh setup per invocation.  This package is
the sustained-throughput regime the ROADMAP north star actually names —
"serves heavy traffic" — built as three thin layers over the existing
stack, none of which duplicate compute code:

``engine.py``    warm-executable cache keyed on the full compile identity
                 (shape, filter, storage, iters, fuse, mesh, backend) with
                 LRU eviction, startup warmup, and per-key single-flight
                 compilation.  The persistent-communication idea of
                 "Persistent & Partitioned MPI for Stencil Communication"
                 (PAPERS.md): set the schedule up once, amortize it across
                 many executions.
``batcher.py``   bounded request queue + micro-batching: same-key requests
                 coalesce into a stacked leading dim, flushed on
                 max-batch-size or max-latency deadline.
``service.py``   admission control (queue depth, per-request deadlines,
                 typed load-shedding) wired into the resilience stack:
                 transient failures retry via ``with_retry``; compile
                 faults walk the ``degrade`` backend ladder per key;
                 ``effective_backend`` is stamped into every response.
``frontend.py``  stdlib-only HTTP/JSON frontend plus an in-process
                 transport so tier-1 tests need no sockets.
``router.py``    the replica-set front tier (round 14): consistent-hash
                 routing by compile key over N independent replicas,
                 active (``/readyz`` poll) + passive (circuit breaker)
                 health, bounded-load spill, idempotent failover with
                 request_id dedup, per-tenant token-bucket admission,
                 and progressive-result streaming for convergence jobs.
                 Round 17 adds pool MUTATION (add/join/remove with
                 drain) and the key-config observatory that feeds warm
                 placement.
``pricing.py``   cost-priced admission (round 17): one wire request's
                 predicted device-seconds from the tuning cost model —
                 the work units tenant buckets are charged, so a huge
                 multigrid job pays its real price and thumbnail blurs
                 keep their latency floor.
``autoscaler.py``the fleet control loop (round 17): scale the replica
                 count from queue-depth/latency/health signals with
                 hysteresis + cooldown, pre-warming a joining replica's
                 ring shard before its vnodes take traffic and draining
                 leavers through the ring-remove path.
``jobs.py``      durable convergence jobs (round 18): the router's
                 resume-token ledger keyed on request_id — per-row
                 bounded tokens (iteration/cycle index, residual, f32
                 field state), mid-stream failover/resume seeding, and
                 the exactly-once final-row gate.
``chaos.py``     the chaos transport (round 18): seeded network-shaped
                 failure injection (latency, drops, mid-stream
                 disconnects, corrupt bodies, black-holes, flapping
                 readiness) at the PCTPU_FAULTS transport sites, so the
                 serving plane's failover/resume machinery is drilled
                 under replayable schedules.

CLI surfaces: ``scripts/serve.py`` (boot one replica's HTTP server),
``scripts/router.py`` (boot the router over N replicas, optionally
autoscaled), and ``scripts/loadgen.py`` (closed/open-loop load
generator emitting p50/p95/p99 + phase-breakdown rows in the bench-row
schema; ``--rps``/``--duration-s`` is the sustained-load harness).
"""

from parallel_convolution_tpu.serving.autoscaler import AutoScaler
from parallel_convolution_tpu.serving.chaos import ChaosTransport
from parallel_convolution_tpu.serving.engine import EngineKey, WarmEngine
from parallel_convolution_tpu.serving.jobs import JobLedger
from parallel_convolution_tpu.serving.pricing import WorkPricer
from parallel_convolution_tpu.serving.router import (
    CorruptReplicaBody, HTTPReplica, InProcessReplica, ReplicaRouter,
    TenantQuotas,
)
from parallel_convolution_tpu.serving.service import (
    ConvolutionService, Rejected, Request, Response, Snapshot,
)

__all__ = [
    "AutoScaler", "ChaosTransport", "ConvolutionService",
    "CorruptReplicaBody", "EngineKey", "HTTPReplica", "InProcessReplica",
    "JobLedger", "Rejected", "ReplicaRouter", "Request", "Response",
    "Snapshot", "TenantQuotas", "WarmEngine", "WorkPricer",
]
