"""Write-ahead journal for the router control plane (round 19).

Round 18 made convergence jobs survive *replica* loss, but every piece
of control-plane state that makes that work — the ``JobLedger``'s resume
tokens, the exactly-once finalized set, ring membership, tenant debt —
lived in router process memory.  A router crash mid-stream therefore
lost every in-flight job even though the replicas held perfectly good
resume tokens.  This module is the durability substrate that fixes it:
an append-only, CRC-per-record, segment-rotated journal the router
writes BEFORE acting (write-ahead), and replays at startup.

Design points, in the ``obs/events.py`` atomic-rotation discipline:

* **One record per line**: ``<crc32-hex> <compact-json>``.  The CRC is
  over the JSON payload bytes, so a torn write, a flipped bit, or a
  truncated tail is detected per record — never silently replayed.
* **Segment rotation with compaction.**  When the live file would
  exceed ``max_bytes`` it is renamed to ``.1`` (older generations shift
  up, oldest dropped) via ``os.replace``, and the fresh live file BEGINS
  with a ``snapshot`` record holding the full folded state — so dropped
  generations lose nothing.  ``seq`` continues across generations; a
  mid-stream gap is corruption, not rotation.
* **Torn-tail tolerance vs loud quarantine.**  A crash can tear exactly
  one record: the last line of the NEWEST file (the writer flushes per
  record; rotated generations were complete when rotated).  Replay
  tolerates that one torn tail (reported, state = everything before
  it).  Damage anywhere else is :class:`WALCorrupt` with a typed cause
  (``crc`` / ``json`` / ``format`` / ``seq_gap`` / ``unknown_kind``);
  :class:`RouterWAL` then QUARANTINES the damaged files (renamed
  ``*.quarantined``, warned loudly, obs event) and starts empty — the
  epoch fence is re-derived from the replicas' own fences during router
  reconciliation, so even a quarantined WAL cannot mint a zombie.
* **The state machine is shared.**  :meth:`WALState.apply` folds one
  record into the recovered image; the SAME method runs on the live
  append path, so "what replay reconstructs" and "what the writer
  thought it had" cannot drift — the rotation snapshot is just the live
  state serialized.
* **Fault sites** ``wal_write`` / ``wal_fsync``
  (``resilience.faults.SITE_TABLE``): consulted before each append and
  each fsync THROUGH ``resilience.diskio``, so the chaos drills can
  fail durability without failing serving — and the round-24 disk
  modes can shape the failure (ENOSPC / EIO / a torn write that lands
  garbage bytes / a slow write that stalls).  A failed append HEALS
  its own tail: partial bytes from the failed record are amputated so
  the next successful append lands on a clean record boundary instead
  of turning a survivable torn tail into mid-log corruption.
* **Degraded-window re-arm** (:meth:`RouterWAL.compact`): appends that
  failed never folded into ``self.state``, so after a degraded window
  the folded image is STALE.  The router re-arms by handing a fresh
  state image built from its LIVE structures; ``compact`` rotates
  immediately so the new generation's head snapshot carries that live
  image and replay can never resurrect the pre-window world.

Record vocabulary (see DESIGN.md "Durable control plane"):

``epoch``        the router's monotonic fencing epoch (takeover bump)
``admit``        one durable converge admission (lid + route key)
``token``        the newest resume token a job's stream row carried
``final``        a job's exactly-once final row went out
``resume``       one mid-stream/client-retry resume (stamp provenance)
``ring_add`` / ``ring_remove``   consistent-hash ring membership
``debt``         a tenant bucket's post-charge/refund level (+ delta)
``cache``        a result-cache eviction/invalidation (op + entry key)
``snapshot``     full folded state (rotation compaction head)

Round 21 generalizes the journal from epoch-per-router to
epoch-per-SHARD: a :class:`RouterWAL` opened with ``shard="02"`` stamps
``shard`` onto every record it appends, and replay refuses a record
stamped for a different shard (crossed lineage files are loud
corruption, not silent splice).  One router process may own several
lineages — one file per shard, each with its own flock sidecar,
generations, epoch, and quarantine namespace.

stdlib-only, jax-free: the router must be able to recover on a host
with no accelerator attached.
"""

from __future__ import annotations

import contextlib
import errno
import json
import os
import threading
import warnings
import zlib
from pathlib import Path

try:
    import fcntl
except ImportError:  # non-unix: lineage fencing stays inode-only
    fcntl = None

from parallel_convolution_tpu.resilience import diskio

__all__ = ["RECORD_KINDS", "RouterWAL", "WALCorrupt", "WALFenced",
           "WALState", "encode_record", "parse_line", "read_wal"]

RECORD_KINDS = frozenset({
    "epoch", "admit", "token", "final", "resume", "job_settled",
    "ring_add", "ring_remove", "debt", "cache", "snapshot",
})

# Bounds on the folded state so a long-lived WAL cannot grow its
# recovery image without bound (mirrors JobLedger's count-bounded rule;
# the ledger re-bounds to its own capacity on restore anyway).
_JOBS_CAP = 256
_FINALIZED_CAP = 1024
_CACHE_DEAD_CAP = 4096


class WALFenced(RuntimeError):
    """This writer lost the WAL lineage: a takeover rotated the live
    file out from under its fd.  Appending anyway would interleave a
    zombie's records into a journal another router now owns — the
    append REFUSES instead (the router counts it as a durability
    error; replica-side epoch fencing already rejects the zombie's
    actual writes)."""


class WALCorrupt(RuntimeError):
    """Mid-log WAL damage — NOT a torn tail.  Carries a typed ``cause``
    (``crc`` | ``json`` | ``format`` | ``seq_gap`` | ``unknown_kind``)
    so recovery can quarantine with a reason instead of guessing."""

    def __init__(self, cause: str, path, line_no: int, detail: str = ""):
        super().__init__(
            f"WAL corrupt ({cause}) at {path}:{line_no}: {detail}")
        self.cause = cause
        self.path = str(path)
        self.line_no = int(line_no)


def encode_record(rec: dict) -> str:
    """One WAL line: 8-hex-digit CRC32 of the payload bytes, a space,
    the compact sorted-key JSON payload, a newline."""
    payload = json.dumps(rec, separators=(",", ":"), sort_keys=True)
    crc = zlib.crc32(payload.encode("utf-8")) & 0xFFFFFFFF
    return f"{crc:08x} {payload}\n"


def parse_line(line: str) -> dict:
    """Decode one WAL line; raises :class:`ValueError` whose first
    word is the typed cause (``format`` / ``crc`` / ``json``)."""
    if len(line) < 10 or line[8] != " ":
        raise ValueError("format: not '<crc8> <json>'")
    crc_hex, payload = line[:8], line[9:]
    try:
        want = int(crc_hex, 16)
    except ValueError:
        raise ValueError(f"format: bad crc field {crc_hex!r}") from None
    # surrogateescape: a flipped byte can make the payload invalid
    # UTF-8 — that must surface as a typed CRC mismatch, not as a
    # UnicodeEncodeError escaping the corruption classifier.
    got = zlib.crc32(payload.encode("utf-8", "surrogateescape")) \
        & 0xFFFFFFFF
    if got != want:
        raise ValueError(f"crc: payload crc {got:08x} != recorded "
                         f"{want:08x}")
    try:
        rec = json.loads(payload)
    except ValueError as e:
        # CRC passed but JSON didn't: either a hand-edited file or a
        # collision-grade fluke — either way typed, never silent.
        raise ValueError(f"json: {e}") from None
    if not isinstance(rec, dict):
        raise ValueError("json: record is not an object")
    return rec


class WALState:
    """The folded control-plane image one WAL replay reconstructs."""

    def __init__(self):
        self.epoch = 0
        # lid -> {"key", "token", "resume_count", "resumed_from"}
        self.jobs: dict[str, dict] = {}
        # lids whose final row went out (dict-as-ordered-set, bounded)
        self.finalized: dict[str, bool] = {}
        self.ring: set[str] = set()
        self.ring_ever: set[str] = set()
        self.debts: dict[str, float] = {}
        # Result-cache entry keys journaled dead (evicted/invalidated);
        # dict-as-ordered-set, bounded like ``finalized``.  A cache
        # rebuilt over this state refuses to serve these entries even
        # if their disk-tier bytes survived the crash.
        self.cache_dead: dict[str, bool] = {}

    # -- record folding -------------------------------------------------------
    def _job(self, lid: str, key: str) -> dict:
        job = self.jobs.pop(lid, None)
        if job is None or job["key"] != key:
            job = {"key": key, "token": None, "resume_count": 0,
                   "resumed_from": [], "cost": None, "budget": 0.0,
                   "wu_start": 0.0}
        # Re-insert at the end: every touch (admit/token/resume) is a
        # recency signal, so the cap evicts the STALEST job — an
        # active long-runner whose token records keep arriving can
        # never be evicted ahead of abandoned entries (the JobLedger's
        # own LRU rule, mirrored).
        self.jobs[lid] = job
        while len(self.jobs) > _JOBS_CAP:
            self.jobs.pop(next(iter(self.jobs)))
        return job

    def apply(self, rec: dict) -> None:
        """Fold one record in.  Raises ValueError on an unknown kind or
        a missing field (the read path reports that as corruption)."""
        kind = rec.get("kind")
        if kind == "snapshot":
            self.load_wire(rec["state"])
        elif kind == "epoch":
            self.epoch = max(self.epoch, int(rec["epoch"]))
        elif kind == "admit":
            # A fresh admission re-opens the id (mirrors
            # JobLedger.begin clearing the exactly-once mark) and
            # carries its charge identity (cost / budget / wu_start)
            # so a crash-interrupted job's UNEXECUTED fraction can be
            # refunded at recovery — the incremental-charge rule
            # extended across a router restart.
            self.finalized.pop(rec["lid"], None)
            job = self._job(rec["lid"], rec["key"])
            job["cost"] = rec.get("cost")
            job["budget"] = float(rec.get("budget", 0.0) or 0.0)
            job["wu_start"] = float(rec.get("wu_start", 0.0) or 0.0)
        elif kind == "token":
            self._job(rec["lid"], rec["key"])["token"] = rec["token"]
        elif kind == "final":
            self.jobs.pop(rec["lid"], None)
            self.finalized[rec["lid"]] = True
            while len(self.finalized) > _FINALIZED_CAP:
                self.finalized.pop(next(iter(self.finalized)))
        elif kind == "resume":
            job = self._job(rec["lid"], rec["key"])
            job["resume_count"] += 1
            job["resumed_from"].append(str(rec["from_replica"]))
        elif kind == "job_settled":
            # The job's charge identity is SETTLED — refunded (an
            # exhausted walk or a previous recovery) or deliberately
            # kept (the request's own terminal fault, which stays
            # charged).  Either way a LATER recovery must not
            # reconcile it again; the token stays (the job may still
            # be client-retried).
            job = self.jobs.get(rec["lid"])
            if job is not None:
                job["cost"] = None
        elif kind == "ring_add":
            self.ring.add(rec["name"])
            self.ring_ever.add(rec["name"])
        elif kind == "ring_remove":
            self.ring.discard(rec["name"])
            self.ring_ever.add(rec["name"])
        elif kind == "debt":
            self.debts[str(rec["tenant"])] = float(rec["level"])
        elif kind == "cache":
            # ``op`` is "dead" (evict/invalidate: the entry key must
            # never be served after recovery) or "live" (a re-store of
            # the same key after a later miss re-executed it — lifts
            # the tombstone so the fresh bytes are servable again).
            # "tier_demoted"/"tier_restored" (round 24) journal the
            # disk tier's degrade-ladder transitions: durable TRACE
            # records, not tombstones — the rebuilt cache re-probes
            # its own disk at startup anyway.  Any other op tombstones
            # conservatively (an unknown future op must not serve).
            op = rec.get("op", "dead")
            ckey = str(rec["ckey"])
            if op in ("tier_demoted", "tier_restored"):
                pass
            elif op == "live":
                self.cache_dead.pop(ckey, None)
            else:
                # Re-insert at the end: recency-ordered so the cap
                # evicts the stalest tombstone first.
                self.cache_dead.pop(ckey, None)
                self.cache_dead[ckey] = True
                while len(self.cache_dead) > _CACHE_DEAD_CAP:
                    self.cache_dead.pop(next(iter(self.cache_dead)))
        else:
            raise ValueError(f"unknown_kind: {kind!r}")

    # -- wire (the snapshot record's body) ------------------------------------
    def to_wire(self) -> dict:
        return {
            "epoch": self.epoch,
            "jobs": {lid: dict(j) for lid, j in self.jobs.items()},
            "finalized": list(self.finalized),
            "ring": sorted(self.ring),
            "ring_ever": sorted(self.ring_ever),
            "debts": dict(self.debts),
            "cache_dead": list(self.cache_dead),
        }

    def load_wire(self, wire: dict) -> None:
        self.epoch = int(wire.get("epoch", 0))
        self.jobs = {str(lid): {
            "key": str(j.get("key", "")),
            "token": j.get("token"),
            "resume_count": int(j.get("resume_count", 0)),
            "resumed_from": [str(x) for x in j.get("resumed_from", [])],
            "cost": j.get("cost"),
            "budget": float(j.get("budget", 0.0) or 0.0),
            "wu_start": float(j.get("wu_start", 0.0) or 0.0),
        } for lid, j in dict(wire.get("jobs") or {}).items()}
        self.finalized = {str(r): True
                          for r in wire.get("finalized") or ()}
        self.ring = {str(n) for n in wire.get("ring") or ()}
        self.ring_ever = {str(n) for n in wire.get("ring_ever") or ()}
        self.debts = {str(t): float(v)
                      for t, v in dict(wire.get("debts") or {}).items()}
        self.cache_dead = {str(k): True
                           for k in wire.get("cache_dead") or ()}


def _generations(path: Path) -> list[Path]:
    """Existing WAL files, oldest first (``.N`` ... ``.1``, then live)."""
    gens = []
    i = 1
    while True:
        g = path.with_name(f"{path.name}.{i}")
        if not g.exists():
            break
        gens.append(g)
        i += 1
    out = list(reversed(gens))
    if path.exists():
        out.append(path)
    return out


def read_wal(path) -> tuple[list[dict], str | None]:
    """Read + validate every record (rotated generations oldest first).

    Returns ``(records, torn_tail)`` where ``torn_tail`` describes the
    one tolerated damaged record — the LAST line of the NEWEST file —
    or None.  Damage anywhere else raises :class:`WALCorrupt` with a
    typed cause: recovery must never silently replay a partial log.
    """
    records, torn, _ = _read_wal_detail(path)
    return records, torn


def _read_wal_detail(path) -> tuple[list[dict], str | None, int]:
    """``read_wal`` plus the LIVE file's valid-prefix byte length —
    :class:`RouterWAL` truncates a torn tail to exactly that length
    before the takeover rotation (otherwise the torn bytes would ride
    into the rotated ``.1`` generation, where the next restart's
    replay would rightly call them MID-log corruption and quarantine
    state the compaction snapshot had perfectly preserved)."""
    p = Path(path)
    files = _generations(p)
    records: list[dict] = []
    prev_seq: int | None = None
    torn: str | None = None
    live_valid_bytes = 0
    for fi, fp in enumerate(files):
        text = fp.read_text(encoding="utf-8", errors="surrogateescape")
        lines = text.split("\n")
        if lines and lines[-1] == "":
            lines.pop()   # the trailing newline of a complete file
        newest = fi == len(files) - 1
        for li, line in enumerate(lines):
            last = newest and li == len(lines) - 1
            try:
                rec = parse_line(line)
                seq = rec.get("seq")
                if not isinstance(seq, int) or seq < 1:
                    raise ValueError(f"format: bad seq {seq!r}")
                if prev_seq is not None and seq != prev_seq + 1:
                    raise ValueError(
                        f"seq_gap: seq {seq} after {prev_seq}")
                if rec.get("kind") not in RECORD_KINDS:
                    raise ValueError(
                        f"unknown_kind: {rec.get('kind')!r}")
            except ValueError as e:
                cause = str(e).split(":", 1)[0]
                if last and cause != "seq_gap":
                    # The one legitimate crash artifact: a torn final
                    # record in the live file.  (A seq GAP on the last
                    # line means earlier records vanished — that is
                    # mid-log damage wearing a tail costume.)
                    torn = f"{fp.name}:{li + 1}: {e}"
                    break
                raise WALCorrupt(cause, fp, li + 1, str(e)) from None
            records.append(rec)
            prev_seq = seq
            if newest:
                live_valid_bytes += len(line.encode(
                    "utf-8", "surrogateescape")) + 1
    return records, torn, live_valid_bytes


class RouterWAL:
    """The router's write-ahead journal (see module docstring).

    Constructing one REPLAYS any existing files at ``path``:
    ``self.state`` is the recovered :class:`WALState` and
    ``self.recovery_report`` says what happened (record count, torn
    tail, quarantine cause).  Appends then continue the sequence.

    ``fsync=True`` (the default) fsyncs after every append — the
    crash-safety contract; drills that only need ordering can turn it
    off.  Append failures raise (``InjectedFault`` from the fault
    sites, or a real ``OSError``); the ROUTER is the layer that decides
    a durability failure must not become a serving outage.
    """

    def __init__(self, path, *, max_bytes: int = 4 << 20, keep: int = 2,
                 fsync: bool = True, shard: str | None = None):
        if max_bytes < 4096:
            raise ValueError("max_bytes must be >= 4096")
        if keep < 1:
            raise ValueError("keep must be >= 1 (rotation relies on the "
                             "snapshot landing in a surviving file)")
        self.path = Path(path)
        # Multi-lineage guard: rotation names generations by appending
        # ``.1``, ``.2``, ... to the LIVE file's name, and
        # ``_generations`` probes the same pattern.  A lineage whose own
        # name ends in ``.<digits>`` (say ``ctl.wal.2`` living next to
        # ``ctl.wal``) would be read as a rotated generation of its
        # SIBLING — silently splicing one shard's records into
        # another's replay.  Refuse the name up front.
        stem, dot, suffix = self.path.name.rpartition(".")
        if dot and stem and suffix.isdigit():
            raise ValueError(
                f"WAL lineage name {self.path.name!r} ends in "
                f"'.{suffix}', which collides with rotated-generation "
                "naming when sibling lineages share the directory; "
                "pick a non-numeric suffix (e.g. 'shard-02.wal')")
        self.shard = None if shard is None else str(shard)
        self.max_bytes = int(max_bytes)
        self.keep = int(keep)
        self.fsync = bool(fsync)
        self._lock = threading.RLock()
        # Sidecar flock serializing append vs takeover ACROSS writers
        # (the inode check alone is a TOCTOU: a zombie's append racing
        # the successor's os.replace could land a stale-seq record in
        # the freshly rotated ``.1``, which the next replay would
        # rightly quarantine as mid-log corruption).  flock is per
        # open-file-description, so two RouterWALs in one process
        # exclude each other too — exactly the in-process drill shape.
        self.path.parent.mkdir(parents=True, exist_ok=True)
        self._flock_fh = open(
            self.path.with_name(self.path.name + ".lock"), "a+b")
        self._fh = None
        # The inode of the live file THIS writer owns — the fencing
        # identity.  Survives close(): a closed writer re-acquiring
        # the path after a successor's takeover rotation must fence,
        # not adopt the successor's journal.
        self._owned_ino: int | None = None
        self._size = 0
        self._seq = 0
        self.records_written = 0
        self.tail_heals = 0
        self.state = WALState()
        self.recovery_report: dict = {}
        with self._file_lock():
            self._load()

    @contextlib.contextmanager
    def _file_lock(self):
        """Cross-writer mutual exclusion for the read+truncate+rotate
        takeover sequence and every append's check+write (blocking:
        takeovers and appends are both short)."""
        if self._flock_fh.closed:
            raise WALFenced(
                f"WAL writer for {self.path} is closed; it cannot "
                "append (re-open the lineage to take it over)")
        if fcntl is None:
            yield
            return
        fcntl.flock(self._flock_fh.fileno(), fcntl.LOCK_EX)
        try:
            yield
        finally:
            fcntl.flock(self._flock_fh.fileno(), fcntl.LOCK_UN)

    # -- startup replay -------------------------------------------------------
    def _load(self) -> None:
        try:
            records, torn, live_valid_bytes = _read_wal_detail(
                self.path)
            for rec in records:
                # Per-shard lineage identity: every record this writer
                # appends is stamped with its shard label, and replay
                # refuses a record stamped for a DIFFERENT shard — the
                # on-disk symptom of two lineages' files getting
                # crossed (a mis-rotated generation, a copy/paste
                # restore into the wrong directory).  Legacy records
                # with no stamp are adoptable by any lineage.
                rec_shard = rec.get("shard")
                if (self.shard is not None and rec_shard is not None
                        and str(rec_shard) != self.shard):
                    raise WALCorrupt(
                        "format", self.path, rec.get("seq", 0),
                        f"record stamped for shard {rec_shard!r} in "
                        f"lineage owned by shard {self.shard!r}")
                try:
                    self.state.apply(rec)
                except (KeyError, TypeError, ValueError) as e:
                    # Parsed but un-foldable (a field missing/mistyped):
                    # same verdict as damaged bytes — typed quarantine.
                    raise WALCorrupt("format", self.path, rec.get(
                        "seq", 0), f"unfoldable record: {e}") from None
        except WALCorrupt as e:
            self.state = WALState()
            quarantined = self._quarantine()
            warnings.warn(
                f"router WAL quarantined ({e.cause}): {e} — moved "
                f"{len(quarantined)} file(s) aside as *.quarantined; "
                "recovery starts EMPTY (the epoch fence is re-derived "
                "from the replicas during reconciliation)",
                RuntimeWarning, stacklevel=3)
            self._emit("quarantined", cause=e.cause, detail=str(e)[:300],
                       files=[str(q) for q in quarantined])
            self.recovery_report = {"records": 0, "torn_tail": None,
                                    "quarantined": e.cause,
                                    "detail": str(e)[:300]}
            return
        self._seq = records[-1]["seq"] if records else 0
        self.recovery_report = {"records": len(records),
                                "torn_tail": torn, "quarantined": None}
        if torn is not None:
            warnings.warn(
                f"router WAL torn tail tolerated: {torn} (one record "
                "lost to the crash; replaying the rest)",
                RuntimeWarning, stacklevel=3)
            self._emit("torn_tail", detail=torn[:300])
            # Amputate the torn bytes from the live file before the
            # takeover rotation: tolerance is a property of the LIVE
            # tail, and these bytes are about to stop being one —
            # rotated into ``.1`` they would read as mid-log
            # corruption on the next restart, quarantining state the
            # compaction snapshot had preserved.  Truncating to the
            # valid-prefix length exactly keeps seq contiguity with
            # the snapshot the rotation writes next.
            if self.path.exists():
                with open(self.path, "r+b") as fh:
                    fh.truncate(live_valid_bytes)
        if self.path.exists():
            # TAKEOVER ROTATION: opening an existing lineage rotates it
            # immediately (fresh live file headed by a compaction
            # snapshot).  This is the WAL half of zombie fencing: the
            # previous writer's fd now points at the renamed ``.1``, so
            # its next append fails the per-append inode check
            # (:class:`WALFenced`) instead of interleaving stale
            # records — and it caps startup replay at one generation.
            # Gated on the file EXISTING, not on records surviving: a
            # live file that was nothing but a torn line must still
            # leave the lineage, or the next append would land in a
            # file whose name a future writer will rotate out from
            # under a zombie that was never fenced.
            with self._lock:
                self._ensure_open()
                self._rotate_locked()

    def _quarantine(self) -> list[Path]:
        """Move every generation aside as ``*.quarantined`` (atomic
        renames; a vanished source means a sibling got there first).

        Destinations are made UNIQUE (``.quarantined``,
        ``.quarantined.2``, ...) instead of ``os.replace`` clobbering:
        a second quarantine of the same lineage — or two shard
        lineages sharing a directory after a botched rename — must
        never destroy the forensic evidence of the first."""
        moved = []
        for fp in _generations(self.path):
            dst = fp.with_name(fp.name + ".quarantined")
            n = 1
            while dst.exists():
                n += 1
                dst = fp.with_name(f"{fp.name}.quarantined.{n}")
            try:
                os.replace(fp, dst)
                moved.append(dst)
            except FileNotFoundError:
                pass
        return moved

    @staticmethod
    def _emit(event: str, **fields) -> None:
        from parallel_convolution_tpu.obs import events, metrics

        if metrics.enabled():
            events.emit("wal", event=event, **fields)

    # -- appends --------------------------------------------------------------
    def _ensure_open(self) -> None:
        if self._fh is None:
            if self._owned_ino is not None:
                # REACQUISITION (the fh was closed, or never survived
                # a write): only legal if the live file is still the
                # one WE own — a closed writer must not silently
                # re-acquire a successor's journal (the inode check
                # against a live fd is vacuous when there is no fd).
                try:
                    cur = os.stat(self.path).st_ino
                except OSError:
                    cur = None
                if cur != self._owned_ino:
                    raise WALFenced(
                        f"WAL lineage at {self.path} was taken over by "
                        "another router while this writer was closed; "
                        "it is fenced")
            self._fh = open(self.path, "a", encoding="utf-8")
            self._size = self._fh.tell()
            if self._owned_ino is None:
                self._owned_ino = os.fstat(self._fh.fileno()).st_ino

    def _check_lineage_locked(self) -> None:
        """Refuse to append if a takeover rotated the live file away
        from the inode this writer owns (we would be a zombie writing
        into a journal a newer router now owns)."""
        try:
            same = os.stat(self.path).st_ino == self._owned_ino
        except OSError:
            same = False
        if not same:
            raise WALFenced(
                f"WAL lineage at {self.path} was taken over by another "
                "router (live inode changed); this writer is fenced")

    def _write_locked(self, kind: str, fields: dict,
                      prebuilt: tuple[dict, str] | None = None,
                      torn: bool = False) -> dict:
        """``prebuilt`` is ``(rec, line)`` already encoded for the
        CURRENT seq+1 (the append fast path — one json.dumps per
        record, not two); it is invalid after a rotation bumped the
        seq, so the rotation path passes None and re-encodes.
        ``torn=True`` is the injected torn-write shape: a prefix of the
        record's bytes lands, then EIO — after which the tail heal
        amputates them like any other failed write."""
        if prebuilt is not None and prebuilt[0]["seq"] == self._seq + 1:
            rec, line = prebuilt
        else:
            rec = {"seq": self._seq + 1, "kind": kind, **fields}
            line = encode_record(rec)
        nbytes = len(line.encode("utf-8"))
        start = self._size
        try:
            if torn:
                self._fh.write(line[:max(1, len(line) // 2)])
                self._fh.flush()
                raise OSError(
                    errno.EIO, "injected torn write at wal_write")
            self._fh.write(line)
            self._fh.flush()
        except OSError:
            # A failed write may have landed PARTIAL bytes.  Heal the
            # tail back to the last good record boundary now, while we
            # still know where it is: without this, the next successful
            # append would land after garbage, turning a survivable
            # torn TAIL into mid-log corruption that replay must
            # quarantine.  seq/size/state are untouched — the record
            # was never appended.
            self._heal_tail_locked(start)
            raise
        self._seq += 1
        self._size += nbytes
        self.state.apply(rec)
        self.records_written += 1
        if self.fsync:
            # After flush, before fsync: an fsync failure leaves the
            # record written-but-not-durable — the caller counts it;
            # the sequence stays consistent either way.
            diskio.guarded_fsync("wal_fsync", self._fh)
        return rec

    def _heal_tail_locked(self, valid_bytes: int) -> None:
        """Best-effort amputation of a failed append's partial bytes.
        The fh is dropped first — its buffer may still hold the failed
        record, and a later flush would resurrect those bytes AFTER
        the truncate — then the file is cut back to the last good
        boundary.  If the heal itself fails (the device is truly
        gone), the partial bytes remain: a crash now reads as the one
        tolerated torn tail; a later successful append reads as loud
        quarantine — never a silent replay of garbage."""
        with contextlib.suppress(OSError, ValueError):
            self._fh.close()
        self._fh = None
        try:
            os.truncate(self.path, valid_bytes)
            self.tail_heals += 1
        except OSError:
            pass

    def _rotate_locked(self) -> None:
        self._fh.close()
        self._fh = None
        for i in range(self.keep - 1, 0, -1):
            src = self.path.with_name(f"{self.path.name}.{i}")
            if src.exists():
                try:
                    os.replace(src, self.path.with_name(
                        f"{self.path.name}.{i + 1}"))
                except FileNotFoundError:
                    pass
        try:
            os.replace(self.path,
                       self.path.with_name(f"{self.path.name}.1"))
        except FileNotFoundError:
            pass
        extra = self.path.with_name(f"{self.path.name}.{self.keep + 1}")
        try:
            extra.unlink()
        except OSError:
            pass
        # Our OWN rotation is a legitimate ownership transfer: the
        # fresh live file's inode becomes the one this writer owns.
        self._owned_ino = None
        self._ensure_open()
        # Compaction head: the fresh live file opens with the FULL
        # folded state, so generations dropped off the end lose nothing.
        snap: dict = {"state": self.state.to_wire()}
        if self.shard is not None:
            snap["shard"] = self.shard
        self._write_locked("snapshot", snap)

    def append(self, kind: str, **fields) -> dict:
        """Append one record (write-ahead: call BEFORE acting on it).
        Returns the record written.  Raises on an unknown kind, an
        injected ``wal_write``/``wal_fsync`` fault (``OSError``-shaped
        when a ``resilience.diskio`` mode is installed, the raw
        ``InjectedFault`` otherwise), or a real I/O error — callers
        decide whether durability failure is fatal."""
        if kind not in RECORD_KINDS:
            raise ValueError(
                f"unknown WAL record kind {kind!r}; known: "
                f"{sorted(RECORD_KINDS)}")
        if self.shard is not None:
            fields.setdefault("shard", self.shard)
        with self._lock, self._file_lock():
            # One consult per append attempt (ENOSPC/EIO raise here,
            # before any byte lands; slow stalls; torn defers to the
            # actual record write below so the garbage hits the tail).
            torn = diskio.deferred_consult("wal_write") == "torn_write"
            self._ensure_open()
            self._check_lineage_locked()
            rec = {"seq": self._seq + 1, "kind": kind, **fields}
            line = encode_record(rec)
            if (self._size + len(line.encode("utf-8")) > self.max_bytes
                    and self._size > 0):
                self._rotate_locked()   # bumps seq: prebuilt invalid
            return self._write_locked(kind, fields,
                                      prebuilt=(rec, line), torn=torn)

    def compact(self, state: WALState | None = None) -> dict:
        """Rotate NOW, heading the fresh live file with a compaction
        snapshot — of ``state`` when given, else the WAL's own folded
        state.  Returns the wire image the snapshot carried.

        This is the degraded-durability RE-ARM entry point: records
        that failed to append during a degraded window never folded
        into ``self.state``, so the folded image is the PRE-window
        world — stale tokens, jobs whose finals already went out.  The
        router passes an image built from its LIVE structures the
        moment a write succeeds again; the degraded-window history
        stays in ``.1``, and replay of the new head can resurrect
        nothing stale."""
        with self._lock, self._file_lock():
            self._ensure_open()
            self._check_lineage_locked()
            if state is not None:
                self.state = state
            self._rotate_locked()
            return self.state.to_wire()

    # -- lifecycle ------------------------------------------------------------
    def close(self) -> None:
        with self._lock:
            if self._fh is not None:
                self._fh.close()
                self._fh = None
            self._flock_fh.close()

    def snapshot(self) -> dict:
        """Operator surface (rides the router's ``/stats``)."""
        with self._lock:
            return {
                "path": str(self.path),
                "shard": self.shard,
                "seq": self._seq,
                "records_written": self.records_written,
                "tail_heals": self.tail_heals,
                "size_bytes": self._size,
                "epoch": self.state.epoch,
                "jobs": len(self.state.jobs),
                "recovery": dict(self.recovery_report),
            }
