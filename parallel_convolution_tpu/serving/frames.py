"""Binary tensor-frame wire codec: the zero-copy data plane.

Until this round every tensor crossed the serving wire as base64 inside
JSON (``image_b64`` / ``state_b64``): encode pays a bytes copy plus a
4/3 inflation, decode pays the inverse, and the JSON parser walks the
whole payload as text.  At serving scale that Python wire tax dominates
the device time (PAPERS.md: measure pack vs direct; the interpreter
overhead on a communication hot path is real).  This module is the
binary alternative, negotiated per request via
``Content-Type: application/x-pctpu-frames`` and proven byte-identical
against the JSON arm (``scripts/wire_ab.py`` → ``evidence/wire_ab.jsonl``).

Wire layout — one **frame** per tensor (all integers little-endian)::

    offset  size       field
    0       4          magic  b"PCTF"
    4       1          version (currently 1)
    5       1          dtype code (DTYPE_CODES)
    6       1          ndim (0..MAX_NDIM)
    7       1          flags (reserved, must be 0)
    8       4*ndim     shape, uint32 per dim
    .       8          payload length, uint64
    .       4          CRC32 (zlib) of the payload, uint32
    .       len        payload: C-contiguous little-endian array bytes

A request/response/stream-row is an **envelope**: the existing JSON
control dict (minus its tensor fields) followed by the frames it names::

    offset  size       field
    0       4          magic  b"PCTE"
    4       1          version (currently 1)
    5       3          reserved (0)
    8       4          header length, uint32
    12      hl         header JSON (utf-8); its ``_frame_fields`` list
                       names each successive frame's body field
    .       ...        frames, concatenated in ``_frame_fields`` order

Contracts:

* **Zero-copy decode** — :func:`decode_frame` returns a read-only
  ``np.frombuffer`` view over the request buffer (buffer protocol /
  ``memoryview`` handoff); the first copy happens where compute needs
  one (the f32 conversion into the device put), never in the codec.
* **Typed failure** — every malformed input raises :class:`BadFrame`
  (a ``ValueError``), which the frontends map to the typed
  ``bad_frame`` 400 rejection; a truncated buffer, an unknown dtype
  code, a length mismatch, and a CRC mismatch are all ``BadFrame``,
  never an unhandled handler-thread exception.
* **Opaque forwarding** — :func:`split_envelope` parses ONLY the
  header (what routing/pricing/QoS need) and returns the frame bytes
  unparsed; :func:`join_envelope` re-wraps a restamped header around
  them, so the router forwards tensor payloads without ever decoding
  them (CRC verification happens once, at the replica).
* **JSON fallback** — nothing here replaces the JSON wire; it rides
  beside it as the negotiated fast path and the A/B control arm.
"""

from __future__ import annotations

import json
import struct
import zlib

import numpy as np

__all__ = ["BadFrame", "FRAMES_CONTENT_TYPE", "VERSION", "decode_envelope",
           "decode_frame", "encode_envelope", "encode_frame",
           "join_envelope", "split_envelope"]

FRAMES_CONTENT_TYPE = "application/x-pctpu-frames"

FRAME_MAGIC = b"PCTF"
ENVELOPE_MAGIC = b"PCTE"
VERSION = 1
MAX_NDIM = 4
# Per-frame payload bound (512 MB): a length field is attacker-supplied
# input until proven otherwise — reject absurd claims before any
# allocation or CRC walk.
MAX_PAYLOAD = 512 << 20
MAX_HEADER = 16 << 20

# dtype code <-> numpy dtype.  Little-endian on the wire; covers the
# serving tensors (u8 images, f32 carries) plus the round-trip set the
# codec test pins so future fields have codes waiting.
DTYPE_CODES = {
    1: np.dtype("uint8"),
    2: np.dtype("<f4"),
    3: np.dtype("<f8"),
    4: np.dtype("<i4"),
    5: np.dtype("<u2"),
    6: np.dtype("<i8"),
    7: np.dtype("<f2"),
}
_CODE_FOR = {dt: code for code, dt in DTYPE_CODES.items()}

_FIXED = struct.Struct("<4sBBBB")         # magic, version, dtype, ndim, flags
_ENV_FIXED = struct.Struct("<4sB3sI")     # magic, version, reserved, hlen
_LEN_CRC = struct.Struct("<QI")


class BadFrame(ValueError):
    """Typed malformed-frame error → the ``bad_frame`` 400 rejection."""


def encode_frame(arr) -> bytes:
    """One array → one self-delimiting frame (bytes)."""
    a = np.asarray(arr)
    if not a.flags["C_CONTIGUOUS"]:
        # ascontiguousarray only when needed: it promotes 0-d to 1-d.
        a = np.ascontiguousarray(a)
    if a.dtype.byteorder == ">":          # wire is little-endian
        a = a.astype(a.dtype.newbyteorder("<"))
    # dtype equality (and hashing) ignores the "=" native marker, so a
    # plain float32 array finds its "<f4" code on LE hosts directly.
    code = _CODE_FOR.get(a.dtype)
    if code is None:
        raise BadFrame(f"dtype {a.dtype} has no frame code")
    if a.ndim > MAX_NDIM:
        raise BadFrame(f"ndim {a.ndim} exceeds frame limit {MAX_NDIM}")
    payload = a.tobytes()                 # C order
    head = _FIXED.pack(FRAME_MAGIC, VERSION, code, a.ndim, 0)
    dims = struct.pack(f"<{a.ndim}I", *a.shape) if a.ndim else b""
    return (head + dims
            + _LEN_CRC.pack(len(payload), zlib.crc32(payload)) + payload)


def decode_frame(buf, offset: int = 0):
    """``(array_view, next_offset)`` — zero-copy over ``buf``.

    ``buf`` is anything the buffer protocol accepts; the returned array
    is a read-only view into it (``np.frombuffer``), so the caller must
    keep the buffer alive as long as the array.  Raises
    :class:`BadFrame` on any malformation, including CRC mismatch.
    """
    view = memoryview(buf).cast("B")
    n = len(view)
    if offset + _FIXED.size > n:
        raise BadFrame(
            f"truncated frame: {n - offset} bytes at offset {offset}, "
            f"need {_FIXED.size} for the fixed header")
    magic, version, code, ndim, flags = _FIXED.unpack_from(view, offset)
    if magic != FRAME_MAGIC:
        raise BadFrame(f"bad frame magic {magic!r} at offset {offset}")
    if version != VERSION:
        raise BadFrame(f"unsupported frame version {version}")
    if flags != 0:
        raise BadFrame(f"reserved frame flags set ({flags:#x})")
    if ndim > MAX_NDIM:
        raise BadFrame(f"frame ndim {ndim} exceeds limit {MAX_NDIM}")
    dtype = DTYPE_CODES.get(code)
    if dtype is None:
        raise BadFrame(f"unknown dtype code {code}")
    off = offset + _FIXED.size
    if off + 4 * ndim + _LEN_CRC.size > n:
        raise BadFrame("truncated frame: shape/length fields cut off")
    shape = struct.unpack_from(f"<{ndim}I", view, off) if ndim else ()
    off += 4 * ndim
    plen, crc = _LEN_CRC.unpack_from(view, off)
    off += _LEN_CRC.size
    if plen > MAX_PAYLOAD:
        raise BadFrame(f"frame payload {plen} exceeds {MAX_PAYLOAD} bytes")
    want = (int(np.prod(shape, dtype=np.int64)) if ndim else 1) \
        * dtype.itemsize
    if plen != want:
        raise BadFrame(
            f"frame payload {plen} bytes does not match shape {shape} "
            f"({want} bytes for {dtype})")
    if off + plen > n:
        raise BadFrame(
            f"truncated frame payload: {n - off} bytes present, "
            f"{plen} declared")
    payload = view[off:off + plen]
    if zlib.crc32(payload) != crc:
        raise BadFrame("frame CRC mismatch: payload corrupt in transit")
    arr = np.frombuffer(payload, dtype=dtype).reshape(shape)
    return arr, off + plen


def encode_envelope(header: dict, arrays: dict | None = None) -> bytes:
    """JSON control header + named tensor frames → envelope bytes.

    ``arrays`` maps body-field names to arrays; their names land in the
    header's ``_frame_fields`` so decode can bind each frame back to
    its field.  The header must not itself carry ``_frame*`` keys.
    """
    arrays = arrays or {}
    head = {k: v for k, v in header.items()
            if not str(k).startswith("_frame")}
    head["_frame_fields"] = list(arrays.keys())
    hjson = json.dumps(head, separators=(",", ":")).encode()
    out = [_ENV_FIXED.pack(ENVELOPE_MAGIC, VERSION, b"\0\0\0",
                           len(hjson)), hjson]
    out.extend(encode_frame(arrays[name]) for name in arrays)
    return b"".join(out)


def split_envelope(raw):
    """``(header_dict, frames_raw)`` — header parsed, frames OPAQUE.

    The router's surface: everything routing, pricing, and QoS read
    lives in the header; ``frames_raw`` is a ``memoryview`` over the
    unparsed frame bytes, forwarded verbatim (no decode, no CRC walk —
    integrity is verified once, at the replica).  Raises
    :class:`BadFrame` on a malformed envelope prefix.
    """
    view = memoryview(raw).cast("B")
    if len(view) < _ENV_FIXED.size:
        raise BadFrame(
            f"truncated envelope: {len(view)} bytes, need "
            f"{_ENV_FIXED.size}")
    magic, version, _resv, hlen = _ENV_FIXED.unpack_from(view, 0)
    if magic != ENVELOPE_MAGIC:
        raise BadFrame(f"bad envelope magic {magic!r}")
    if version != VERSION:
        raise BadFrame(f"unsupported envelope version {version}")
    if hlen > MAX_HEADER:
        raise BadFrame(f"envelope header {hlen} exceeds {MAX_HEADER}")
    if _ENV_FIXED.size + hlen > len(view):
        raise BadFrame("truncated envelope: header cut off")
    try:
        header = json.loads(bytes(view[_ENV_FIXED.size:
                                       _ENV_FIXED.size + hlen]))
    except ValueError as e:
        raise BadFrame(f"envelope header is not valid JSON: {e}") from e
    if not isinstance(header, dict):
        raise BadFrame("envelope header must be a JSON object")
    return header, view[_ENV_FIXED.size + hlen:]


def join_envelope(header: dict, frames_raw) -> bytes:
    """Re-wrap a (restamped) header around already-encoded frame bytes
    — the router's opaque-forward encoder.  ``header`` keeps whatever
    ``_frame_fields`` it already carries (the frames are not re-read)."""
    head = {k: v for k, v in header.items()
            if k == "_frame_fields" or not str(k).startswith("_frame")}
    hjson = json.dumps(head, separators=(",", ":")).encode()
    return (_ENV_FIXED.pack(ENVELOPE_MAGIC, VERSION, b"\0\0\0",
                            len(hjson)) + hjson + bytes(frames_raw))


def decode_envelope(raw):
    """``(header_dict, {field: array_view})`` — the full decode.

    Frame order and count come from the header's ``_frame_fields``;
    trailing garbage after the last declared frame is a
    :class:`BadFrame` (a length-confused client must hear about it).
    Array views are zero-copy into ``raw``.
    """
    header, frames_raw = split_envelope(raw)
    fields = header.pop("_frame_fields", [])
    if not isinstance(fields, list) or not all(
            isinstance(f, str) for f in fields):
        raise BadFrame("_frame_fields must be a list of field names")
    arrays: dict[str, np.ndarray] = {}
    off = 0
    for name in fields:
        arr, off = decode_frame(frames_raw, off)
        arrays[name] = arr
    if off != len(frames_raw):
        raise BadFrame(
            f"{len(frames_raw) - off} trailing bytes after the last "
            "declared frame")
    return header, arrays
