"""Content-addressed result cache: serve duplicate traffic from bytes.

Round 20 batched same-shape requests; round 21 sharded the control
plane.  The next ceiling at duplicate-heavy (Zipf) traffic is that two
byte-identical requests with different ``request_id``s both execute on
device.  This module keys *results* by content so the duplicate head of
the distribution is served without touching a lane, a compile, or a
chip:

* **Key = input digest + compile identity.**  :func:`input_digest` is a
  SHA-256 over the planar image's dtype/shape/bytes;
  :func:`result_key` folds in the full :class:`~.engine.EngineKey`
  (which already carries iters/fuse/boundary/solver/mg_levels/backend/
  grid — everything that changes the output bytes).  Two requests with
  equal result keys are guaranteed byte-identical answers, so a hit can
  be stamped into a Response without re-execution.  Convergence jobs
  use :func:`converge_key` — ``(rhs digest, tol, solver, mg_levels)`` —
  because their output identity is the *fixed point*, not the iteration
  count.
* **Two tiers.**  A bounded in-memory OrderedDict LRU (entries + bytes)
  spills evicted entries to a disk tier of content-addressed files
  (filename derived from the key), written atomically (temp +
  ``os.replace``) with a CRC32 over header and body — the
  ``utils.checkpoint`` shard discipline.  A corrupt disk entry is a
  loud miss (dropped + journaled dead), never bad bytes.
* **Evictions/invalidations are journaled.**  The constructor takes a
  ``journal(op, ckey)`` hook the service wires to the router WAL's new
  ``cache`` record kind (``op`` = ``dead`` | ``live``).  The journal is
  write-ahead: an entry is marked dead BEFORE its bytes are dropped, so
  a crash between the two can only over-invalidate, never resurrect.
  A recovered :class:`~.wal.WALState` hands its ``cache_dead`` set back
  in via the ``dead`` argument and the cache refuses to serve those
  keys even if their disk bytes survived the restart; a later re-store
  of the same key (a miss re-executed it) journals ``live`` first,
  lifting the tombstone for the *fresh* bytes.
* **Shard-local.**  A cache belongs to one shard's lineage: the journal
  hook appends to that shard's WAL, so a cross-shard takeover that
  adopts the dead shard's journal (r21) adopts its tombstones too.

stdlib + numpy only; jax-free (hits must be servable on a host with no
accelerator attached, same rule as the WAL).
"""

from __future__ import annotations

import dataclasses
import errno
import hashlib
import json
import os
import tempfile
import threading
import time
import zlib
from collections import OrderedDict
from pathlib import Path

import numpy as np

from parallel_convolution_tpu.resilience import diskio

__all__ = ["ResultCache", "converge_key", "input_digest", "result_key"]

# Tombstone bound (mirrors the WAL's _CACHE_DEAD_CAP; the WAL re-bounds
# to its own cap on replay anyway).
_DEAD_CAP = 4096


def input_digest(planar) -> str:
    """SHA-256 hex over one planar image's dtype + shape + bytes.

    The dtype/shape prefix keeps a (1, 8, 8) u8 image from colliding
    with a (8, 8, 1) or f32 view of the same byte stream.
    """
    arr = np.ascontiguousarray(planar)
    h = hashlib.sha256()
    h.update(f"{arr.dtype.str}|{arr.shape}|".encode())
    h.update(arr.tobytes())
    return h.hexdigest()


def _key_fingerprint(fields: dict) -> str:
    payload = json.dumps(fields, sort_keys=True, separators=(",", ":"),
                         default=str)
    return hashlib.sha256(payload.encode()).hexdigest()[:16]


def result_key(digest: str, engine_key) -> str:
    """Cache key for the batch path: input digest + the full compile
    identity (EngineKey already includes iters/solver params)."""
    return f"{digest}-{_key_fingerprint(dataclasses.asdict(engine_key))}"


def converge_key(digest: str, *, tol, solver: str,
                 mg_levels, engine_key=None) -> str:
    """Cache key for a convergence job's FINAL row: the fixed point is
    determined by ``(rhs digest, tol, solver, mg_levels)`` plus the
    stencil identity (filter/boundary/storage ride in via
    ``engine_key`` when given) — NOT by check_every/max_iters, which
    only change how often the stream reports progress."""
    fields = {"tol": repr(tol), "solver": solver, "mg_levels": mg_levels}
    if engine_key is not None:
        kf = dataclasses.asdict(engine_key)
        # iters is the snapshot cadence on the converge path, not part
        # of the fixed point's identity.
        kf.pop("iters", None)
        fields["key"] = kf
    return f"{digest}-cv{_key_fingerprint(fields)}"


class ResultCache:
    """Bounded two-tier content-addressed result store.

    Entries are ``(arrays, meta)``: a dict of named numpy arrays (the
    result bytes) plus a JSON-safe metadata dict (effective_backend,
    plan provenance, ... — whatever the service needs to rebuild a
    Response).  ``get``/``put``/``invalidate`` are thread-safe; the
    journal hook is called under the cache lock so the WAL's ordering
    matches the cache's.
    """

    def __init__(self, *, capacity_entries: int = 256,
                 capacity_bytes: int = 256 << 20,
                 disk_dir=None, disk_capacity_entries: int = 1024,
                 journal=None, dead=None, shard: str | None = None,
                 demote_after: int = 2, reprobe_s: float = 5.0,
                 clock=time.monotonic):
        if capacity_entries < 1:
            raise ValueError("capacity_entries must be >= 1")
        self.capacity_entries = int(capacity_entries)
        self.capacity_bytes = int(capacity_bytes)
        self.disk_dir = None if disk_dir is None else Path(disk_dir)
        self.disk_capacity_entries = int(disk_capacity_entries)
        self.shard = None if shard is None else str(shard)
        self._journal = journal
        self._lock = threading.Lock()
        # Disk-tier degrade ladder (round 24): ``demote_after``
        # consecutive spill failures demote the tier to memory-only
        # (a journaled ``tier_demoted`` transition — the WAL shows WHEN
        # the cross-restart spill surface went dark); while demoted,
        # one spill attempt per ``reprobe_s`` re-probes the disk, and
        # the first success journals ``tier_restored`` and re-arms.
        self.demote_after = max(1, int(demote_after))
        self.reprobe_s = float(reprobe_s)
        self._clock = clock
        self._spill_fail_streak = 0
        self._disk_demoted = False
        self._reprobe_at = 0.0
        # ckey -> (arrays, meta, nbytes)
        self._mem: OrderedDict[str, tuple] = OrderedDict()
        self._mem_bytes = 0
        # ckey -> disk path (LRU order; oldest evicted+journaled dead)
        self._disk: OrderedDict[str, Path] = OrderedDict()
        # Tombstones: journaled-dead keys this cache must never serve
        # (seeded from a recovered WALState.cache_dead on restart).
        self._dead: OrderedDict[str, bool] = OrderedDict()
        for k in dead or ():
            self._mark_dead_local(str(k))
        self.stats = {
            "hits_mem": 0, "hits_disk": 0, "misses": 0, "stores": 0,
            "spills": 0, "evictions": 0, "invalidations": 0,
            "corrupt_drops": 0, "dead_refusals": 0, "journal_errors": 0,
            "spill_failures": 0, "tier_demotions": 0,
            "tier_restores": 0, "reprobes": 0,
        }
        if self.disk_dir is not None:
            self.disk_dir.mkdir(parents=True, exist_ok=True)
            self._adopt_disk_locked()

    # -- tombstones -----------------------------------------------------------
    def _mark_dead_local(self, ckey: str) -> None:
        self._dead.pop(ckey, None)
        self._dead[ckey] = True
        while len(self._dead) > _DEAD_CAP:
            self._dead.pop(next(iter(self._dead)))

    def _journal_locked(self, op: str, ckey: str) -> None:
        if self._journal is None:
            return
        try:
            self._journal(op, ckey)
        except Exception:
            # Durability failure must not become a serving outage (the
            # WAL's own rule) — but an unjournaled DEATH would let a
            # restart resurrect the bytes, so the local tombstone above
            # still stands; only the cross-restart guarantee degrades,
            # and loudly.
            self.stats["journal_errors"] += 1  # stats-lock: held by caller (_locked suffix)

    def _kill_locked(self, ckey: str, *, reason: str) -> None:
        """Write-ahead death: journal + local tombstone BEFORE the
        bytes are dropped, so a crash mid-removal over-invalidates
        instead of resurrecting."""
        self._journal_locked("dead", ckey)
        self._mark_dead_local(ckey)
        ent = self._mem.pop(ckey, None)
        if ent is not None:
            self._mem_bytes -= ent[2]
        path = self._disk.pop(ckey, None)
        if path is not None:
            try:
                os.unlink(path)
            except OSError:
                pass
        self.stats[reason] += 1  # stats-lock: held by caller (_locked suffix)

    # -- disk tier ------------------------------------------------------------
    def _disk_path(self, ckey: str) -> Path:
        return self.disk_dir / f"{ckey}.rc"

    def _adopt_disk_locked(self) -> None:
        """Adopt surviving ``*.rc`` files at startup — EXCEPT the ones
        the recovered journal marked dead (the never-resurrect rule)."""
        for p in sorted(self.disk_dir.glob("*.rc")):
            ckey = p.name[:-3]
            if ckey in self._dead:
                try:
                    os.unlink(p)
                except OSError:
                    pass
                continue
            self._disk[ckey] = p
        while len(self._disk) > self.disk_capacity_entries:
            self._kill_locked(next(iter(self._disk)),
                              reason="evictions")

    def _spill_locked(self, ckey: str, arrays: dict, meta: dict) -> None:
        """Memory -> disk: content-addressed file, atomic write, CRC32
        over header and body (the checkpoint-shard discipline).  The
        ``cache_spill`` fault site guards the write (ENOSPC / EIO /
        torn / slow via ``resilience.diskio``); failures feed the
        demote ladder — the entry leaves the cache (journaled dead,
        never servable-stale) and a failure streak takes the whole
        tier memory-only until a re-probe heals it."""
        if self._disk_demoted:
            if self._clock() < self._reprobe_at:
                # Tier is dark and the probe window hasn't opened:
                # leaving memory IS leaving the cache.
                self._kill_locked(ckey, reason="evictions")
                return
            self._reprobe_at = self._clock() + self.reprobe_s
            self.stats["reprobes"] += 1  # stats-lock: held by caller (_locked suffix)
        names = sorted(arrays)
        body = b"".join(np.ascontiguousarray(arrays[n]).tobytes()
                        for n in names)
        header = {
            "ckey": ckey,
            "arrays": [{"name": n, "dtype": arrays[n].dtype.str,
                        "shape": list(arrays[n].shape)} for n in names],
            "body_crc": zlib.crc32(body) & 0xFFFFFFFF,
            "meta": meta,
        }
        hjson = json.dumps(header, separators=(",", ":"), sort_keys=True)
        hcrc = zlib.crc32(hjson.encode()) & 0xFFFFFFFF
        blob = f"{hcrc:08x} {hjson}\n".encode() + body
        path = self._disk_path(ckey)
        tmp = None
        try:
            # torn_write is deferred so the torn bytes actually get
            # PUBLISHED (tmp + replace, then the error): the shape an
            # unsynced page loss leaves behind, which the read path's
            # CRC must refuse.
            torn = diskio.deferred_consult("cache_spill") == "torn_write"
            fd, tmp = tempfile.mkstemp(dir=str(self.disk_dir),
                                       prefix=".rc-", suffix=".tmp")
            with os.fdopen(fd, "wb") as fh:
                fh.write(blob[:max(1, len(blob) // 2)] if torn else blob)
            os.replace(tmp, path)
            tmp = None
            if torn:
                raise OSError(errno.EIO,
                              "injected torn write at cache_spill")
        except OSError:
            if tmp is not None:
                try:
                    os.unlink(tmp)
                except OSError:
                    pass
            # Spill failure: the entry leaves the cache entirely —
            # including any bytes the failure left at its final path
            # (a torn publish must not await adoption).
            try:
                os.unlink(path)
            except OSError:
                pass
            self._kill_locked(ckey, reason="evictions")
            self.stats["spill_failures"] += 1  # stats-lock: held by caller (_locked suffix)
            self._spill_fail_streak += 1
            if (not self._disk_demoted
                    and self._spill_fail_streak >= self.demote_after):
                self._demote_tier_locked()
            return
        self._spill_fail_streak = 0
        if self._disk_demoted:
            self._restore_tier_locked()
        self._disk.pop(ckey, None)
        self._disk[ckey] = path
        self.stats["spills"] += 1  # stats-lock: held by caller (_locked suffix)
        while len(self._disk) > self.disk_capacity_entries:
            self._kill_locked(next(iter(self._disk)),
                              reason="evictions")

    def _demote_tier_locked(self) -> None:
        """Disk tier -> memory-only (journaled, so the WAL's record
        stream shows when the cross-restart spill surface went dark).
        Resident disk entries stay servable — their bytes landed
        before the device degraded, and every read re-verifies CRC."""
        self._disk_demoted = True
        self._reprobe_at = self._clock() + self.reprobe_s
        self.stats["tier_demotions"] += 1  # stats-lock: held by caller (_locked suffix)
        self._journal_locked("tier_demoted", "disk")

    def _restore_tier_locked(self) -> None:
        self._disk_demoted = False
        self._spill_fail_streak = 0
        self.stats["tier_restores"] += 1  # stats-lock: held by caller (_locked suffix)
        self._journal_locked("tier_restored", "disk")

    def _read_disk_locked(self, ckey: str):
        path = self._disk.get(ckey)
        if path is None:
            return None
        try:
            # cache_promote guard: a failed disk read on a hit is a
            # loud journaled miss (killed below), never a stale serve.
            diskio.consult("cache_promote")
            blob = path.read_bytes()
            nl = blob.index(b"\n")
            line = blob[:nl].decode("utf-8")
            if len(line) < 10 or line[8] != " ":
                raise ValueError("header format")
            hcrc, hjson = int(line[:8], 16), line[9:]
            if zlib.crc32(hjson.encode()) & 0xFFFFFFFF != hcrc:
                raise ValueError("header crc")
            header = json.loads(hjson)
            if header.get("ckey") != ckey:
                raise ValueError("key mismatch")
            body = blob[nl + 1:]
            if zlib.crc32(body) & 0xFFFFFFFF != header["body_crc"]:
                raise ValueError("body crc")
            arrays: dict[str, np.ndarray] = {}
            off = 0
            for spec in header["arrays"]:
                dt = np.dtype(spec["dtype"])
                shape = tuple(int(x) for x in spec["shape"])
                n = dt.itemsize * int(np.prod(shape, dtype=np.int64))
                arrays[spec["name"]] = np.frombuffer(
                    body[off:off + n], dtype=dt).reshape(shape)
                off += n
            if off != len(body):
                raise ValueError("body length")
            return arrays, dict(header.get("meta") or {})
        except (OSError, ValueError, KeyError, TypeError):
            # Damaged shard: loud miss, journaled dead — a torn write
            # or flipped bit must never become served bytes.
            self._kill_locked(ckey, reason="corrupt_drops")
            return None

    # -- memory tier ----------------------------------------------------------
    def _insert_mem_locked(self, ckey: str, arrays: dict,
                           meta: dict) -> None:
        nbytes = sum(int(a.nbytes) for a in arrays.values())
        old = self._mem.pop(ckey, None)
        if old is not None:
            self._mem_bytes -= old[2]
        self._mem[ckey] = (arrays, meta, nbytes)
        self._mem_bytes += nbytes
        while (len(self._mem) > self.capacity_entries
               or self._mem_bytes > self.capacity_bytes):
            if len(self._mem) == 1:
                break   # a single over-budget entry still serves
            victim, ent = self._mem.popitem(last=False)
            self._mem_bytes -= ent[2]
            if self.disk_dir is not None:
                self._spill_locked(victim, ent[0], ent[1])
            else:
                # No disk tier: leaving memory IS leaving the cache.
                self._kill_locked(victim, reason="evictions")

    # -- public API -----------------------------------------------------------
    def get(self, ckey: str):
        """``(arrays, meta)`` or None.  A journaled-dead key is refused
        even if bytes for it still exist (the never-resurrect rule); a
        disk hit is promoted back into the memory tier."""
        with self._lock:
            if ckey in self._dead:
                self.stats["dead_refusals"] += 1
                self.stats["misses"] += 1
                return None
            ent = self._mem.get(ckey)
            if ent is not None:
                self._mem.move_to_end(ckey)
                self.stats["hits_mem"] += 1
                return ent[0], ent[1]
            got = self._read_disk_locked(ckey)
            if got is not None:
                self.stats["hits_disk"] += 1
                self._insert_mem_locked(ckey, got[0], got[1])
                return got
            self.stats["misses"] += 1
            return None

    def put(self, ckey: str, arrays: dict, meta: dict) -> None:
        """Store one result.  Arrays are copied (the caller's buffers
        may be reused); a tombstoned key is journaled ``live`` first —
        fresh bytes from a re-execution lift the tombstone."""
        arrays = {str(n): np.ascontiguousarray(a).copy()
                  for n, a in arrays.items()}
        with self._lock:
            if ckey in self._dead:
                self._journal_locked("live", ckey)
                self._dead.pop(ckey, None)
            self._insert_mem_locked(ckey, arrays, dict(meta))
            self.stats["stores"] += 1

    def invalidate(self, ckey: str) -> None:
        """Journal + drop one entry (write-ahead: dead before drop)."""
        with self._lock:
            if ckey in self._mem or ckey in self._disk:
                self._kill_locked(ckey, reason="invalidations")
            else:
                self._journal_locked("dead", ckey)
                self._mark_dead_local(ckey)
                self.stats["invalidations"] += 1

    def invalidate_all(self) -> None:
        """Drop every resident entry (engine swap / reshape: the plan
        provenance stamped in cached metadata is stale)."""
        with self._lock:
            for ckey in list(self._mem) + list(self._disk):
                self._kill_locked(ckey, reason="invalidations")

    def __len__(self) -> int:
        with self._lock:
            return len(self._mem) + len(self._disk)

    def keys(self) -> list[str]:
        """Resident entry keys, memory tier first (LRU order within
        each tier) — the drill/test surface for naming an entry."""
        with self._lock:
            return list(self._mem) + [k for k in self._disk
                                      if k not in self._mem]

    def snapshot(self) -> dict:
        with self._lock:
            s = dict(self.stats)
            s.update(mem_entries=len(self._mem),
                     mem_bytes=self._mem_bytes,
                     disk_entries=len(self._disk),
                     dead=len(self._dead), shard=self.shard)
            return s
