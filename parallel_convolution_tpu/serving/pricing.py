"""Cost-priced admission: what one wire request COSTS, before it runs.

Round 14's tenant QoS charged every request ONE token — so an 8192²
multigrid converge job and a 48×64 thumbnail blur drew the same quota,
and one greedy tenant submitting big jobs could consume a thousand small
requests' worth of device time while staying inside a request-count
budget.  This module prices admission in the cost model's own currency:
**predicted device-seconds** (``tuning.costmodel`` — the same roofline
that ranks backends), so a tenant bucket's refill rate becomes a share
of MACHINE TIME (``rate=2.0`` = "this tenant may consume two
device-seconds per wall second"), not a request count.

* Batch requests price as ``predict_seconds_per_px_iter × pixels ×
  iters / devices`` — linear in the work the device will actually do.
* Convergence jobs price their ``max_iters`` WORK BUDGET (the bound the
  stream enforces): jacobi as ``max_iters`` fine-grid sweeps; multigrid
  through :func:`costmodel.predict_mg_cycle_seconds` — the budget in
  fine-grid work units divided by one cycle's work units, times one
  cycle's seconds — so a converge job pays for the V-cycle schedule it
  will drive, not a flat fee.
* Accuracy contract is the cost model's own: it RANKS (a big job costs
  proportionally more than a small one); absolute error is absorbed by
  the bucket rate knob.  Every price is floored (``min_units``) so
  free-looking requests still meter, and clamped (``max_units``) so one
  absurd request cannot poison a bucket beyond recovery.

stdlib + numpy-free + jax-free: prices are pure arithmetic on wire
fields, cached by the router's ``route_key`` (bounded LRU — the price
of a config is as stable as its compile identity).
"""

from __future__ import annotations

import threading
from collections import OrderedDict

from parallel_convolution_tpu.tuning import costmodel

__all__ = ["WorkPricer"]


class WorkPricer:
    """Predicted device-seconds for one wire-format request.

    ``grid``/``platform``/``device_kind`` describe the replicas the
    router fronts (the pricer lives router-side, which has no mesh);
    they shape the exchange/roofline terms only — pricing is RELATIVE,
    so a router fronting heterogeneous replicas still meters fairly as
    long as one model prices every request.
    """

    def __init__(self, grid: tuple[int, int] = (1, 1),
                 platform: str = "cpu", device_kind: str = "", *,
                 min_units: float = 1e-4, max_units: float = 600.0,
                 cache_size: int = 512):
        self.grid = (max(1, int(grid[0])), max(1, int(grid[1])))
        self.hw = costmodel.hardware_for(platform, device_kind)
        self.min_units = float(min_units)
        self.max_units = float(max_units)
        self._cache: OrderedDict[tuple, float] = OrderedDict()
        self._cache_size = max(16, int(cache_size))
        self._lock = threading.Lock()

    # -- the public surface ---------------------------------------------------
    def hit_units(self) -> float:
        """What a content-addressed CACHE HIT costs: the floor.

        A hit consumes no device time — it is a digest, a dict probe,
        and a memcpy — so it meters at ``min_units``, the same floor a
        malformed body prices at.  Charging hits near-zero is the
        incentive side of the result cache (serving/cache.py): a tenant
        whose traffic is duplicate-heavy spends almost none of its
        device-seconds budget on the duplicate head.  The router settles
        the difference AFTER the response comes back stamped
        ``cache: hit`` (it cannot know at admission), refunding
        ``charged - hit_units()`` through the journaled refund path.
        """
        return self.min_units

    def price(self, body: dict, converge: bool = False,
              cache_hit: bool = False) -> float:
        """Work units (predicted device-seconds) one request will cost.

        Never raises: a malformed body prices at the floor — admission
        pricing must not pre-empt the typed ``invalid`` rejection the
        replica owns (charging garbage the minimum keeps the quota path
        orthogonal to validation).  ``cache_hit=True`` prices the
        request as a served-from-cache duplicate: :meth:`hit_units`.
        """
        if cache_hit:
            return self.hit_units()
        try:
            ck = self._cache_key(body, converge)
            with self._lock:
                units = self._cache.get(ck)
                if units is not None:
                    self._cache.move_to_end(ck)
                    return units
            units = self._clamp(self._price_uncached(body, converge))
            with self._lock:
                self._cache[ck] = units
                while len(self._cache) > self._cache_size:
                    self._cache.popitem(last=False)
            return units
        except Exception:  # noqa: BLE001 — never pre-empt typed invalid
            return self.min_units

    # -- internals ------------------------------------------------------------
    def _clamp(self, units: float) -> float:
        return max(self.min_units, min(self.max_units, float(units)))

    @staticmethod
    def _cache_key(body: dict, converge: bool) -> tuple:
        fields = ("rows", "cols", "mode", "filter", "iters", "backend",
                  "storage", "fuse", "boundary", "quantize", "solver",
                  "max_iters", "mg_levels", "depth")
        return (converge,) + tuple(repr(body.get(k)) for k in fields)

    def _price_uncached(self, body: dict, converge: bool) -> float:
        from parallel_convolution_tpu.ops.filters import get_filter

        if str(body.get("mode") or "") == "volume":
            return self._price_volume(body, converge)
        rows = max(1, int(body.get("rows", 1)))
        cols = max(1, int(body.get("cols", 1)))
        channels = 3 if body.get("mode") == "rgb" else 1
        filt = get_filter(str(body.get("filter") or "blur3"))
        storage = str(body.get("storage") or "f32")
        if storage not in costmodel.STORAGE_BYTES:
            storage = "f32"
        quantize = bool(body.get("quantize", not converge))
        backend = str(body.get("backend") or "shifted")
        if backend == "auto":
            # Pricing needs no plan resolution: the normative compiled
            # tier is a fair stand-in, and relative cost is what meters.
            backend = "shifted"
        try:
            fuse = max(1, int(body.get("fuse") or 1))
        except (TypeError, ValueError):
            fuse = 1
        R, Q = self.grid
        shape = (channels, rows, cols)
        block_hw = (max(1, -(-rows // R)), max(1, -(-cols // Q)))
        n_dev = R * Q
        px = channels * rows * cols

        if converge and str(body.get("solver") or "jacobi") == "multigrid":
            max_iters = max(1, int(body.get("max_iters", 500)))
            levels = body.get("mg_levels")
            cycle_s, wu_per_cycle = costmodel.predict_mg_cycle_seconds(
                shape, self.grid, filt.size, "f32", False, self.hw,
                levels=(None if levels is None else int(levels)),
                backend=backend)
            # max_iters bounds FINE-GRID WORK UNITS (the stream's own
            # budget semantics) — the job runs at most this many cycles.
            cycles = max(1.0, max_iters / max(wu_per_cycle, 1e-9))
            return cycles * cycle_s / n_dev
        iters = (max(1, int(body.get("max_iters", 500))) if converge
                 else max(1, int(body.get("iters", 1))))
        spp = costmodel.predict_seconds_per_px_iter(
            backend, storage, fuse, None, shape, block_hw, self.grid,
            filt.size, filt.separable() is not None, quantize, self.hw)
        return spp * px * iters / n_dev

    def _price_volume(self, body: dict, converge: bool) -> float:
        """Rank-3 bodies (``mode="volume"``): predicted device-seconds
        through the rank-3 roofline — ``rows``/``cols`` are the (H, W)
        plane, ``depth`` the resident D axis, cells counted over the
        two live fields."""
        from parallel_convolution_tpu.utils.config import (
            VOLUME_FIELDS, VOLUME_RADII,
        )

        rows = max(1, int(body.get("rows", 1)))
        cols = max(1, int(body.get("cols", 1)))
        depth = max(1, int(body.get("depth", 1)))
        name = str(body.get("filter") or "fd7")
        radius = VOLUME_RADII.get(name, 1)
        try:
            fuse = max(1, int(body.get("fuse") or 1))
        except (TypeError, ValueError):
            fuse = 1
        R, Q = self.grid
        block_hw = (max(1, -(-rows // R)), max(1, -(-cols // Q)))
        n_dev = R * Q
        cells = VOLUME_FIELDS * depth * rows * cols
        iters = (max(1, int(body.get("max_iters", 500))) if converge
                 else max(1, int(body.get("iters", 1))))
        spc = costmodel.predict_volume_seconds_per_cell_iter(
            self.grid, block_hw, depth, radius, fuse, name, self.hw,
            fields=VOLUME_FIELDS)
        return spc * cells * iters / n_dev
