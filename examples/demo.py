#!/usr/bin/env python
"""End-to-end demo: the reference's full workflow in ~40 lines.

Generates the waterfall-stand-in image, blurs it 100 iterations on the
device mesh (every perf knob on), validates byte-identity against the
serial oracle, converts the result to a viewable PGM, and prints phase
timings — serial-vs-parallel the way the reference's README does.

Run:  python examples/demo.py [rows cols]
"""

import sys
import tempfile
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))

import numpy as np

from parallel_convolution_tpu.models import ConvolutionModel
from parallel_convolution_tpu.ops import filters, oracle
from parallel_convolution_tpu.utils import imageio
from parallel_convolution_tpu.utils.platform import apply_platform_env
from parallel_convolution_tpu.utils.tracing import PhaseTimer

# Honor JAX_PLATFORMS even when a site hook pre-pinned another platform
# programmatically (utils/platform.py) — without this, JAX_PLATFORMS=cpu
# runs on (or hangs waiting for) the ambient accelerator instead.
apply_platform_env()


def main() -> int:
    rows, cols = (int(sys.argv[1]), int(sys.argv[2])) if len(sys.argv) > 2 \
        else (480, 630)  # 1/4-scale waterfall geometry
    iters = 100
    t = PhaseTimer()

    with t.phase("generate"):
        img = imageio.generate_test_image(rows, cols, "grey", seed=0)

    with t.phase("serial-oracle"):
        golden = oracle.run_serial_u8(img, filters.get_filter("blur3"), iters)

    model = ConvolutionModel(filt="blur3", storage="bf16", fuse=4)
    with t.phase("mesh-compile+run"):
        out = model.run_image(img, iters)

    with t.phase("mesh-run-cached"):
        out = model.run_image(img, iters)

    identical = np.array_equal(out, golden)
    with tempfile.TemporaryDirectory() as d:
        pgm = Path(d) / "blurred.pgm"
        with open(pgm, "wb") as f:
            f.write(b"P5\n%d %d\n255\n" % (cols, rows) + out.tobytes())
        size = pgm.stat().st_size

    rep = t.report()
    print(f"{rows}x{cols} grey, {iters} iters on mesh "
          f"{model.mesh.shape}: bit-identical to serial oracle: {identical}")
    for name, ph in rep["phases"].items():
        print(f"  {name:>18}: {ph['wall_s']*1e3:9.1f} ms")
    speedup = rep["phases"]["serial-oracle"]["wall_s"] / \
        rep["phases"]["mesh-run-cached"]["wall_s"]
    print(f"  speedup vs serial oracle (cached compile): {speedup:.1f}x")
    print(f"  viewable PGM written ({size} bytes) — the visual check")
    return 0 if identical else 1


if __name__ == "__main__":
    sys.exit(main())
