#!/usr/bin/env python
"""Reconstruct span trees from the event log; critical paths; Chrome JSON.

The read side of the round-13 tracing layer: given the JSONL event log
(``PCTPU_OBS_EVENTS``) containing ``span`` events (obs.trace), produce

* per-trace tree integrity (exactly one root, zero orphan spans — the
  trace-smoke gate);
* the BATCH critical-path attribution: for every batch span, which
  request's trace paid for the compile (the batch's native trace; the
  single-flight waiters carry links instead), which requests rode along
  (the batch's links), and how much of the device wall was EXPOSED
  exchange vs compute (the model-attributed children record_step emits —
  the reference C code's per-phase MPI_Wtime breakdown, now per batch);
* per-span-name duration stats (count / total / p50 / p95);
* the longest-child critical path of the slowest traces;
* optionally ``--chrome out.json``: Chrome ``trace_event`` JSON —
  open chrome://tracing (or https://ui.perfetto.dev) and load the file
  to scrub the actual request timeline.

  python scripts/trace_report.py --events evidence/trace_events.jsonl \\
      --out evidence/trace_report.json --chrome evidence/trace_chrome.json

Exit status: 0 on a clean reconstruction; 1 when the log has no spans,
any trace has orphan spans or more than one root, or an input is
unreadable.
"""

from __future__ import annotations

import argparse
import json
import sys
from pathlib import Path

import _path  # noqa: F401  (repo root on sys.path)

from parallel_convolution_tpu.obs import events as events_lib
from parallel_convolution_tpu.obs import trace as trace_lib


def _percentile(vals: list[float], q: float) -> float | None:
    if not vals:
        return None
    s = sorted(vals)
    i = min(len(s) - 1, int(round(q * (len(s) - 1))))
    return s[i]


def name_stats(spans: list[dict]) -> dict:
    """count / total / p50 / p95 duration (ms) per span name."""
    by: dict[str, list[float]] = {}
    for r in spans:
        by.setdefault(r.get("name", ""), []).append(
            float(r.get("dur_s", 0.0)))
    return {
        name: {
            "count": len(ds),
            "total_ms": round(1e3 * sum(ds), 3),
            "p50_ms": round(1e3 * _percentile(ds, 0.50), 3),
            "p95_ms": round(1e3 * _percentile(ds, 0.95), 3),
        }
        for name, ds in sorted(by.items())
    }


def critical_path(tree: dict, root_id: str) -> list[dict]:
    """Root-to-leaf path choosing the longest-duration child at every
    level — where a request's wall actually went."""
    path = []
    sid = root_id
    while sid is not None:
        r = tree["spans"][sid]
        path.append({"name": r.get("name", ""),
                     "dur_ms": round(1e3 * float(r.get("dur_s", 0.0)), 3)})
        kids = tree["children"].get(sid, [])
        sid = (max(kids, key=lambda k: tree["spans"][k].get("dur_s", 0.0))
               if kids else None)
    return path


def analyze_batches(trees: dict) -> list[dict]:
    """Per-batch attribution: payer, riders, exchange share of device."""
    out = []
    for tid, tree in trees.items():
        for sid, r in tree["spans"].items():
            if r.get("name") != "batch":
                continue
            kids = {tree["spans"][k]["name"]: tree["spans"][k]
                    for k in tree["children"].get(sid, [])}
            compile_s = float(kids.get("compile", {}).get("dur_s", 0.0))
            device = kids.get("device")
            dev_s = float(device.get("dur_s", 0.0)) if device else 0.0
            ex_s = hid_s = comp_s = 0.0
            if device:
                for k in tree["children"].get(device["span_id"], []):
                    kr = tree["spans"][k]
                    if kr["name"] == "exchange":
                        ex_s += float(kr.get("dur_s", 0.0))
                        hid_s += float(kr.get("attrs", {}).get(
                            "hidden_s", 0.0))
                    elif kr["name"] == "compute":
                        comp_s += float(kr.get("dur_s", 0.0))
            attrs = r.get("attrs", {})
            out.append({
                "trace_id": tid,              # the PAYER: whose trace owns
                #                               the shared compile/device
                "span_id": sid,
                "batch_size": attrs.get("batch_size",
                                        attrs.get("n_requests")),
                "effective_backend": attrs.get("effective_backend", ""),
                "plan_key": attrs.get("plan_key", ""),
                "linked_traces": sorted({l["trace_id"]
                                         for l in r.get("links", [])}),
                "compile_ms": round(1e3 * compile_s, 3),
                "device_ms": round(1e3 * dev_s, 3),
                # The per-phase breakdown the span tree makes first-class:
                # exposed exchange share of the device wall (+ the r12
                # hidden-under-compute share as its own number).
                "exposed_exchange_ms": round(1e3 * ex_s, 3),
                "hidden_exchange_ms": round(1e3 * hid_s, 3),
                "compute_ms": round(1e3 * comp_s, 3),
                "exposed_exchange_fraction_of_device": (
                    round(ex_s / dev_s, 4) if dev_s > 0 else None),
            })
    return out


def chrome_trace(spans: list[dict]) -> dict:
    """Chrome ``trace_event`` JSON: one complete ('X') event per span.

    pid = the emitting process; tid = a stable small index per trace, so
    each request's tree reads as one row in the chrome://tracing UI.
    """
    t0 = min((float(r.get("start_ts", 0.0)) for r in spans),
             default=0.0)
    tids: dict[str, int] = {}
    rows: set[tuple[int, int]] = set()   # (pid, tid) pairs actually used
    evs = []
    for r in sorted(spans, key=lambda r: r.get("start_ts", 0.0)):
        trace_id = r.get("trace_id", "")
        tid = tids.setdefault(trace_id, len(tids) + 1)
        rows.add((r.get("pid", 0), tid))
        evs.append({
            "name": r.get("name", ""),
            "cat": "pctpu",
            "ph": "X",
            "ts": round(1e6 * (float(r.get("start_ts", 0.0)) - t0), 1),
            "dur": max(0.1, round(1e6 * float(r.get("dur_s", 0.0)), 1)),
            "pid": r.get("pid", 0),
            "tid": tid,
            "args": {
                "trace_id": trace_id,
                "span_id": r.get("span_id", ""),
                "parent_id": r.get("parent_id", ""),
                "status": r.get("status", ""),
                **r.get("attrs", {}),
            },
        })
    # Name the per-trace rows so the UI shows the trace id, not "tid 3".
    # Viewers key thread_name by (pid, tid), so emit one per REAL pair —
    # a hardcoded pid would label a phantom process instead.
    by_tid = {i: t for t, i in tids.items()}
    meta = [{"name": "thread_name", "ph": "M", "pid": pid, "tid": tid,
             "args": {"name": f"trace {by_tid[tid][:8]}"}}
            for pid, tid in sorted(rows)]
    return {"traceEvents": meta + evs, "displayTimeUnit": "ms"}


def analyze(recs: list[dict], max_paths: int = 10) -> tuple[dict, int]:
    """The report dict + exit code."""
    spans = trace_lib.span_records(recs)
    trees = trace_lib.build_trees(spans)
    problems = []
    multi_root, orphaned = [], []
    for tid, t in trees.items():
        if len(t["roots"]) != 1:
            multi_root.append(tid)
        if t["orphans"]:
            orphaned.append(tid)
    if not spans:
        problems.append("no span events in the log")
    if multi_root:
        problems.append(f"{len(multi_root)} traces with != 1 root")
    if orphaned:
        problems.append(f"{len(orphaned)} traces with orphan spans")
    # Critical paths of the slowest traces (by root duration).
    rooted = [(tid, t) for tid, t in trees.items() if len(t["roots"]) == 1]
    rooted.sort(key=lambda kv: -float(
        kv[1]["spans"][kv[1]["roots"][0]].get("dur_s", 0.0)))
    paths = {
        tid: critical_path(t, t["roots"][0])
        for tid, t in rooted[:max_paths]
    }
    report = {
        "spans": len(spans),
        "traces": len(trees),
        "roots_per_trace_ok": not multi_root,
        "orphan_spans": sum(len(t["orphans"]) for t in trees.values()),
        "multi_root_traces": multi_root[:10],
        "orphaned_traces": orphaned[:10],
        "by_name": name_stats(spans),
        "batches": analyze_batches(trees),
        "critical_paths": paths,
        "problems": problems,
    }
    return report, (1 if problems else 0)


def _print_human(report: dict) -> None:
    print(f"spans: {report['spans']} across {report['traces']} traces, "
          f"{report['orphan_spans']} orphans")
    for name, st in report["by_name"].items():
        print(f"  {name:14s} n={st['count']:<5d} p50={st['p50_ms']}ms "
              f"p95={st['p95_ms']}ms total={st['total_ms']}ms")
    for b in report["batches"]:
        print(f"batch {b['span_id'][:8]} (payer {b['trace_id'][:8]}, "
              f"{len(b['linked_traces'])} riders): "
              f"compile={b['compile_ms']}ms device={b['device_ms']}ms "
              f"exposed_exchange={b['exposed_exchange_ms']}ms "
              f"(hidden {b['hidden_exchange_ms']}ms) "
              f"share={b['exposed_exchange_fraction_of_device']}")
    for p in report["problems"]:
        print(f"PROBLEM: {p}", file=sys.stderr)


def main() -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--events", required=True,
                    help="JSONL event log (rotated generations included)")
    ap.add_argument("--out", default=None,
                    help="also write the JSON report to this path")
    ap.add_argument("--chrome", default=None, metavar="JSON",
                    help="write Chrome trace_event JSON for "
                         "chrome://tracing / ui.perfetto.dev")
    ap.add_argument("--max-paths", type=int, default=10,
                    help="critical paths for the N slowest traces")
    ap.add_argument("--quiet", action="store_true",
                    help="suppress the human summary (JSON only)")
    args = ap.parse_args()

    try:
        recs = events_lib.read_events(args.events)
    except (OSError, ValueError) as e:
        print(f"trace_report: unreadable event log: {e}", file=sys.stderr)
        return 1
    report, rc = analyze(recs, max_paths=args.max_paths)

    if args.chrome:
        p = Path(args.chrome)
        p.parent.mkdir(parents=True, exist_ok=True)
        p.write_text(json.dumps(
            chrome_trace(trace_lib.span_records(recs))))
        report["chrome"] = str(p)
    if not args.quiet:
        _print_human(report)
    if args.out:
        p = Path(args.out)
        p.parent.mkdir(parents=True, exist_ok=True)
        p.write_text(json.dumps(report, indent=2))
    else:
        print(json.dumps({k: v for k, v in report.items()
                          if k != "critical_paths"}))
    return rc


if __name__ == "__main__":
    sys.exit(main())
