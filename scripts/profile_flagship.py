#!/usr/bin/env python
"""Profiler-trace check of the DESIGN.md VPU-ceiling claim (run on TPU).

DESIGN.md's roofline asserts the fused separable kernel is compute-bound
at ~1.47 TF/s f32 VPU throughput.  That figure was *derived* (op ledger ×
slope wall), never confirmed by a device trace.  This script:

1. slope-times the flagship workload (blur3, pallas_sep, bf16, fuse=T),
2. captures ONE execution of the compiled runner under
   ``jax.profiler.trace`` into ``evidence/traces/`` (xplane protobuf,
   parsed offline — tracing a full bench_iterate would record ~20 slope
   repetitions and inflate the capture ~20×),
3. prints a JSON row holding the wall plus both DESIGN.md ledger
   conventions side by side, so the chip leg confirms or corrects the
   claim under the SAME accounting DESIGN.md uses:
     - flops/px = 2·2k = 12 for blur3 separable (FMA = 2 flops, MACs
       only) → ``implied_vpu_gflops`` compares against 1 469.8,
     - ops/px/level = 2k FMA + 1 rint + 2 masks = 9 post-elision
       (FMA = 1 op) → ``implied_vpu_gops`` compares against ~1 350,
4. optionally (``--ab``) A/Bs the interior split, predicting its gain
   from the REAL tile geometry: interior_frac · (2 mask ops / 9), the
   DESIGN.md formula (≈ 0.66 · 2/9 ≈ 15% ceiling at the flagship point,
   before the ~2% concat cost) — not a 100%-interior upper bound.  The
   tile geometry comes from ``pallas_stencil.fused_tile_grid`` — the
   SAME helper the kernel launch uses — so the prediction cannot drift
   from the real launch.

Usage (chip session):
  python scripts/profile_flagship.py --size 8192 --fuse 32 --reps 3 --ab
"""

from __future__ import annotations

import argparse
import json
import os
import sys

import _path  # noqa: F401  (repo root onto sys.path)


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--size", type=int, default=8192)
    ap.add_argument("--iters", type=int, default=100)
    ap.add_argument("--fuse", type=int, default=32)
    ap.add_argument("--backend", default="pallas_sep")
    ap.add_argument("--storage", default="bf16")
    ap.add_argument("--tile", default="1024x512")
    ap.add_argument("--reps", type=int, default=3)
    ap.add_argument("--ab", action="store_true",
                    help="also run the interior-split A/B leg")
    ap.add_argument("--trace-dir", default="evidence/traces")
    args = ap.parse_args()

    import jax
    import numpy as np

    from parallel_convolution_tpu.ops import pallas_stencil
    from parallel_convolution_tpu.ops.filters import get_filter
    from parallel_convolution_tpu.parallel import step as step_lib
    from parallel_convolution_tpu.parallel.mesh import make_grid_mesh
    from parallel_convolution_tpu.utils import bench
    from parallel_convolution_tpu.utils.platform import on_tpu
    from parallel_convolution_tpu.utils.tracing import device_trace

    mesh = make_grid_mesh(jax.devices()[:1], (1, 1))
    filt = get_filter("blur3")
    tile = tuple(int(v) for v in args.tile.split("x"))
    kw = dict(mesh=mesh, backend=args.backend, storage=args.storage,
              fuse=args.fuse, tile=tile, reps=args.reps)

    # 1. Slope-timed wall (the number the roofline divides by).
    row = bench.bench_iterate((args.size, args.size), filt, args.iters, **kw)

    # 2. Trace exactly ONE execution of the compiled runner (compile +
    #    warmup happen before the trace starts).
    trace_dir = os.path.join(args.trace_dir,
                             f"flagship_{args.size}_fuse{args.fuse}")
    os.makedirs(trace_dir, exist_ok=True)
    xs, valid_hw, block_hw = step_lib._prepare(
        np.random.default_rng(0)
        .integers(0, 256, size=(1, args.size, args.size))
        .astype(np.float32),
        mesh, filt.radius, args.storage)
    # Keyword set matches bench_iterate's _build_iterate call exactly so
    # the lru_cache key collides and the already-compiled runner is
    # reused (a second 8192^2 Mosaic compile would waste tunnel minutes).
    fn = step_lib._build_iterate(mesh, filt, args.iters, True, valid_hw,
                                 block_hw, args.backend, args.fuse,
                                 tile=tile, interior_split=False)
    out = bench.fence(fn(xs))  # compile + warm, outside the trace
    with device_trace(trace_dir):
        out = bench.fence(fn(out))

    # 3. Both DESIGN.md ledger conventions (see module docstring).
    k = filt.size
    flops_px = 2 * 2 * k            # 12 for blur3: MACs only, FMA = 2
    ops_px = 2 * k + 1 + 2          # 9 post-elision: + rint + 2 masks
    gpx = row["gpixels_per_s_per_chip"]
    row.update(
        trace_dir=trace_dir,
        flops_per_px=flops_px,
        implied_vpu_gflops=round(gpx * flops_px, 1),   # vs 1469.8 claimed
        ops_per_px_level=ops_px,
        implied_vpu_gops=round(gpx * ops_px, 1),       # vs ~1350 derived
        on_tpu=on_tpu(),
    )
    print(json.dumps(row), flush=True)

    if args.ab:
        # Predicted split gain from the REAL geometry: the masked 2 of
        # ops_px ops disappear on the interior fraction of tiles only.
        # fused_tile_grid is the launch's own geometry helper.
        r, T = filt.radius, args.fuse
        sep = pallas_stencil._sep_taps(filt, args.backend == "pallas_sep")
        th, tw, gh, gw = pallas_stencil.fused_tile_grid(
            (args.size, args.size), step_lib.STORAGE_DTYPES[args.storage],
            tile, sep)
        split = pallas_stencil._interior_range(
            (args.size, args.size), (th, tw), r * T, (gh, gw))
        fi = fs = 0.0
        if split is not None:
            # Count tiles from the launch's OWN patch plan, so this
            # ledger cannot drift from what actually runs.
            for (r0b, r1b), (c0b, c1b), (mr, mc) in (
                    pallas_stencil.split_patches(split, (gh, gw))):
                n = (r1b - r0b) * (c1b - c0b) / (gh * gw)
                if not mr and not mc:
                    fi += n
                elif not mr or not mc:
                    fs += n

        row_b = bench.bench_iterate((args.size, args.size), filt, args.iters,
                                    **kw, interior_split=True)
        row_b.update(isplit=True, interior_tile_frac=round(fi, 3),
                     single_mask_tile_frac=round(fs, 3))
        print(json.dumps(row_b), flush=True)
        speedup = row_b["gpixels_per_s_per_chip"] / max(gpx, 1e-9)
        # 9-patch ledger: interior tiles drop 2 of ops_px mask ops,
        # pure-edge tiles drop 1; a ceiling (concat cost ~2% not
        # modeled), not a pass bar.
        predicted = 1.0 / (1.0 - (2.0 * fi + fs) / ops_px)
        print(json.dumps({
            "ab": "interior_split",
            "speedup": round(speedup, 4),
            "ledger_predicts": round(predicted, 4),
        }), flush=True)
    return 0


if __name__ == "__main__":
    sys.exit(main())
