#!/usr/bin/env python
"""Digest the round-5 chip-session evidence into doc-update suggestions.

Run after ``scripts/chip_session_r5.sh`` lands (or partially lands):
reads whatever evidence files exist, prints a compact report —

* best flagship row per sweep file vs the standing BENCH_r03 headline
  (123.0 Gpx/s/chip), with the tile/fuse that won,
* the interior-split A/B speedup vs the geometry-ledger prediction,
* the config-2 true-size vs working-set-matched gap,
* tiled-RDMA / validate_walls outcomes (pass-through status lines),

so the post-session doc updates (README headline ~line 59, BASELINE.md
provenance table, DESIGN.md "to be measured" lines, SEP_TILE/fuse
defaults) can be written from one screen.  Read-only; never edits docs.
"""

from __future__ import annotations

import json
import os
import sys

import _path  # noqa: F401

HEADLINE_R03 = 123.0  # Gpx/s/chip, BENCH_r03.json (pallas_sep/bf16/fuse32)

EV = os.path.join(os.path.dirname(__file__), "..", "evidence")


def rows(name):
    path = os.path.join(EV, name)
    if not os.path.exists(path):
        return None
    out = []
    with open(path) as f:
        for line in f:
            line = line.strip()
            if not line or line.startswith("#"):
                continue
            try:
                out.append(json.loads(line))
            except ValueError:
                continue
    return out


def best(rws):
    scored = [r for r in (rws or []) if "gpixels_per_s_per_chip" in r]
    return max(scored, key=lambda r: r["gpixels_per_s_per_chip"],
               default=None)


def main() -> int:
    any_file = False

    for name in ("tune_convex_r5.jsonl", "tune_convex_r5_recovered.jsonl",
                 "tune_convex_r5_u8.jsonl",
                 "tune_convex_r5b.jsonl", "tune_convex_r5b.jsonl.partial",
                 "tune_convex_r5b_fill.jsonl",
                 "config2_matched_r5.jsonl"):
        rws = rows(name)
        if rws is None:
            print(f"[absent] {name}")
            continue
        any_file = True
        b = best(rws)
        if b is None:
            print(f"[empty/errors] {name}: {len(rws)} rows, none scored")
            continue
        gpx = b["gpixels_per_s_per_chip"]
        line = (f"[{name}] best {gpx} Gpx/s/chip "
                f"tile={b.get('tile')} fuse={b.get('fuse')} "
                f"storage={b.get('storage')} timing={b.get('timing')}")
        if "config2" not in name:
            line += (f"  -> vs r03 headline {HEADLINE_R03}: "
                     f"{gpx / HEADLINE_R03:.3f}x")
        print(line)
        if "config2" in name and len(rws) >= 2:
            by_tag = {r.get("tag"): r for r in rws}
            t = by_tag.get("config2-true-size")
            m = by_tag.get("config2-working-set-matched")
            if t and m:
                print(f"  config2 cache-residency inflation: "
                      f"{t['gpixels_per_s_per_chip']} (true size) vs "
                      f"{m['gpixels_per_s_per_chip']} (matched) = "
                      f"{t['gpixels_per_s_per_chip'] / max(m['gpixels_per_s_per_chip'], 1e-9):.2f}x")

    ab = rows("profile_flagship_r5.jsonl")
    if ab is None:
        print("[absent] profile_flagship_r5.jsonl")
    else:
        any_file = True
        for r in ab:
            if r.get("ab") == "interior_split":
                print(f"[isplit A/B] measured {r.get('speedup')}x vs "
                      f"ledger ceiling {r.get('ledger_predicts')}x")
            elif r.get("isplit"):
                print(f"[isplit row] {r.get('gpixels_per_s_per_chip')} "
                      f"Gpx/s/chip (interior {r.get('interior_tile_frac')}, "
                      f"single-mask {r.get('single_mask_tile_frac')})")
            elif "implied_vpu_gflops" in r:
                print(f"[ceiling] {r.get('gpixels_per_s_per_chip')} "
                      f"Gpx/s/chip -> {r.get('implied_vpu_gflops')} Gflop/s "
                      f"(claim 1469.8) / {r.get('implied_vpu_gops')} Gops "
                      f"(derived ~1350); trace: {r.get('trace_dir')}")

    for name in ("rdma_silicon_r5.json", "tiled_repro_r5.jsonl",
                 "rdma_silicon_r5b.json", "rdma_silicon_r5b.json.partial",
                 "tiled_repro_r5b.jsonl", "tiled_repro_r5b.jsonl.partial",
                 "helper_crash_probe_r5.jsonl",
                 "helper_crash_probe_r5.jsonl.partial",
                 "validate_walls_r5.json"):
        rws = rows(name)
        if rws is None:
            print(f"[absent] {name}")
        elif not rws:
            print(f"[empty/errors] {name}: no parseable rows")
        else:
            any_file = True
            # Print EVERY row (the tiled-repro ladder's key result is the
            # first FAILING rung, usually not row 0).
            print(f"[{name}] {len(rws)} row(s):")
            for r in rws:
                print(f"  {json.dumps(r)[:220]}")

    if not any_file:
        print("no round-5 chip evidence found — session not landed yet")
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())
