#!/usr/bin/env python
"""Fast static gate: the ``run_t1.sh --static`` leg (round 19).

Five checks, all stdlib, no jax import, a few seconds total:

1. **compileall** — every ``.py`` under ``parallel_convolution_tpu/``,
   ``scripts/``, and ``tests/`` byte-compiles (``py_compile`` to a
   throwaway cache file; a syntax error anywhere fails the leg even if
   no test imports that module).
2. **no bare ``except:``** — a bare except swallows KeyboardInterrupt
   and SystemExit; every handler in this tree names its exceptions (the
   broad ones carry a ``# noqa: BLE001`` justification).  Regex over
   source lines.
3. **no unlocked mutation of shared ``stats`` dicts under
   ``serving/``** — the serving plane's counters are shared across
   handler/poll/batcher threads; every ``X.stats[...] = / += ...``
   must sit lexically inside a ``with`` block whose context expression
   names a lock (``_lock`` / ``_cv`` / ``lock``), or carry an explicit
   ``# stats-lock: held`` pragma naming where the lock is taken.
   AST-based (string matching can't see block structure).
4. **no direct writes to shared evidence curves** — shared curve files
   (``evidence/scale_curve.jsonl``) hold rows owned by SEVERAL smoke
   legs; the only sanctioned writer is
   ``parallel_convolution_tpu.utils.evidence_io.rewrite_shared_jsonl``
   (it preserves foreign lanes atomically).  Any write-mode ``open()``,
   ``Path.open()``, ``write_text``/``write_bytes`` whose target
   expression names a shared curve file or a ``curve``-named handle —
   outside the helper module itself — fails the leg.  The convention
   this enforces: shared-curve handles are named ``curve_*``, and
   nothing but evidence_io writes through them.
5. **no new dispatch ladders in ``parallel/step.py``** — the rank-3
   volume subsystem (round 23) landed as kernel-registry entries with
   ZERO new ``rank ==`` / ``backend ==`` arms in the step dispatcher;
   this check freezes those counts at the baseline so the next variant
   does too.
6. **no unguarded disk writes in the serving plane** (round 24) —
   every write-mode ``open()`` / ``Path.open()`` / ``os.fdopen`` /
   ``write_text`` / ``write_bytes`` / ``os.replace`` under ``serving/``,
   ``obs/``, or ``utils/`` must live in an allowlisted guarded-owner
   module (``diskio.py`` itself, plus the modules whose write paths
   consult a ``resilience.diskio`` fault site internally:
   ``evidence_io.py``, ``wal.py``, ``events.py``, ``cache.py``,
   ``checkpoint.py``) or carry a ``# diskio: exempt`` pragma naming why
   the write sits outside the durability plane (process-exit snapshot
   dumps, test-image scaffolding).  This is what keeps the storage
   chaos matrix honest: a new serving-plane write path that skips
   ``diskio`` would be invisible to every fault drill.

Exit 0 and ``{"failures": 0}`` in ``--out`` iff all six hold.
"""

from __future__ import annotations

import argparse
import ast
import json
import py_compile
import re
import sys
import tempfile
import time
from pathlib import Path

if __name__ == "__main__":
    import _path  # noqa: F401  (repo root on sys.path)

ROOT = Path(__file__).resolve().parent.parent
CHECK_DIRS = ("parallel_convolution_tpu", "scripts", "tests")
_BARE_EXCEPT = re.compile(r"^\s*except\s*:")


def _rel(p: Path) -> str:
    try:
        return str(p.relative_to(ROOT))
    except ValueError:
        return str(p)


_PRAGMA = "# stats-lock: held"


def py_files() -> list[Path]:
    out = []
    for d in CHECK_DIRS:
        out.extend(sorted((ROOT / d).rglob("*.py")))
    return [p for p in out if "__pycache__" not in p.parts]


def check_compiles(files) -> list[str]:
    problems = []
    with tempfile.NamedTemporaryFile(suffix=".pyc") as tmp:
        for f in files:
            try:
                py_compile.compile(str(f), cfile=tmp.name, doraise=True)
            except py_compile.PyCompileError as e:
                problems.append(
                    f"{_rel(f)}: does not compile: "
                    f"{e.msg.splitlines()[0][:200]}")
    return problems


def check_bare_except(files) -> list[str]:
    problems = []
    for f in files:
        for n, line in enumerate(
                f.read_text(encoding="utf-8").splitlines(), 1):
            if _BARE_EXCEPT.match(line):
                problems.append(
                    f"{_rel(f)}:{n}: bare 'except:' "
                    "(name the exceptions; bare swallows "
                    "KeyboardInterrupt/SystemExit)")
    return problems


def _locked_context(expr_src: str) -> bool:
    """Does a with-item's source look like a lock acquisition?"""
    s = expr_src.lower()
    return "lock" in s or "_cv" in s or ".cv" in s


def check_stats_locking(files) -> list[str]:
    """Every ``<obj>.stats[...]`` assignment/augassign under serving/
    must be inside a lock-holding ``with`` (or pragma'd)."""
    problems = []
    serving = [f for f in files
               if "serving" in f.parts and f.suffix == ".py"]
    for f in serving:
        src = f.read_text(encoding="utf-8")
        lines = src.splitlines()
        try:
            tree = ast.parse(src)
        except SyntaxError:
            continue  # check 1 reports it
        # Parent links so we can walk ancestors.
        parents: dict[ast.AST, ast.AST] = {}
        for node in ast.walk(tree):
            for child in ast.iter_child_nodes(node):
                parents[child] = node

        def is_stats_subscript(target) -> bool:
            return (isinstance(target, ast.Subscript)
                    and isinstance(target.value, ast.Attribute)
                    and target.value.attr == "stats")

        for node in ast.walk(tree):
            targets = []
            if isinstance(node, ast.AugAssign):
                targets = [node.target]
            elif isinstance(node, ast.Assign):
                targets = node.targets
            if not any(is_stats_subscript(t) for t in targets):
                continue
            line = lines[node.lineno - 1] if node.lineno <= len(
                lines) else ""
            if _PRAGMA in line:
                continue
            cur = node
            locked = False
            while cur in parents and not locked:
                cur = parents[cur]
                if isinstance(cur, ast.With):
                    for item in cur.items:
                        seg = ast.get_source_segment(
                            src, item.context_expr) or ""
                        if _locked_context(seg):
                            locked = True
                            break
            if not locked:
                problems.append(
                    f"{_rel(f)}:{node.lineno}: mutation of "
                    "a shared stats dict outside a lock-holding "
                    "'with' block (take the owning lock, or annotate "
                    f"'{_PRAGMA} <where>' if the caller holds it)")
    return problems


# Shared evidence curves: multiple smoke legs co-own rows in these
# files, so only evidence_io's lane-preserving rewrite may write them.
_SHARED_CURVES = ("scale_curve.jsonl",)
_CURVE_NAME = re.compile(r"\bcurve", re.IGNORECASE)
_EVIDENCE_IO = "evidence_io.py"


def _write_mode(call: ast.Call, pos: int) -> str:
    """The mode string of an open()-style call, '' if not a literal."""
    if len(call.args) > pos and isinstance(call.args[pos], ast.Constant):
        v = call.args[pos].value
        return v if isinstance(v, str) else ""
    for kw in call.keywords:
        if kw.arg == "mode" and isinstance(kw.value, ast.Constant):
            v = kw.value.value
            return v if isinstance(v, str) else ""
    return "r" if len(call.args) <= pos else ""


def check_shared_curve_writes(files) -> list[str]:
    """No write-mode open / write_text on a shared-curve target outside
    evidence_io (the one lane-preserving writer)."""
    problems = []
    for f in files:
        if f.name == _EVIDENCE_IO:
            continue
        src = f.read_text(encoding="utf-8")
        # Prefilter: a curve-named handle OR a shared-curve basename
        # anywhere in the file ("scale_curve" has no \b before "curve").
        if not (_CURVE_NAME.search(src)
                or any(b in src for b in _SHARED_CURVES)):
            continue
        try:
            tree = ast.parse(src)
        except SyntaxError:
            continue  # check 1 reports it
        for node in ast.walk(tree):
            if not isinstance(node, ast.Call):
                continue
            fn = node.func
            target = mode = None
            if (isinstance(fn, ast.Name) and fn.id == "open"
                    and node.args):
                target = ast.get_source_segment(src, node.args[0]) or ""
                mode = _write_mode(node, 1)
            elif isinstance(fn, ast.Attribute) and fn.attr == "open":
                target = ast.get_source_segment(src, fn.value) or ""
                mode = _write_mode(node, 0)
            elif (isinstance(fn, ast.Attribute)
                  and fn.attr in ("write_text", "write_bytes")):
                target = ast.get_source_segment(src, fn.value) or ""
                mode = "w"
            if not target or not mode:
                continue
            if not any(c in mode for c in "wax+"):
                continue
            if (any(b in target for b in _SHARED_CURVES)
                    or _CURVE_NAME.search(target)):
                problems.append(
                    f"{_rel(f)}:{node.lineno}: direct write to a shared "
                    f"evidence curve target ({target[:60]!r}) — use "
                    "parallel_convolution_tpu.utils.evidence_io."
                    "rewrite_shared_jsonl, the one lane-preserving "
                    "writer")
    return problems


# Dispatch-ladder freeze for parallel/step.py (round 23): new kernel
# variants land as REGISTRY entries (parallel/kernels.py — the rank-3
# volume forms did), never as another `if rank == ...` / `if backend ==
# ...` arm in the step dispatcher.  The baselines pin the seed's counts:
# exactly one historical `backend ==` comparison (the pallas_sep
# separability flag) and zero `rank ==`.  A count above baseline fails
# the leg; BELOW baseline is fine (someone refactored a ladder away).
_LADDER_FILE = Path("parallel_convolution_tpu") / "step.py"
_LADDER_BASELINE = {"rank ==": 0, "backend ==": 1}


def check_dispatch_ladders(files) -> list[str]:
    """``parallel/step.py`` must not grow ``rank ==`` / ``backend ==``
    comparison ladders beyond the frozen baseline."""
    step = next((f for f in files
                 if f.parts[-2:] == ("parallel", "step.py")), None)
    if step is None:
        return ["parallel/step.py missing: the dispatch-ladder freeze "
                "has nothing to check"]
    src = step.read_text(encoding="utf-8")
    problems = []
    for needle, allowed in _LADDER_BASELINE.items():
        count = src.count(needle)
        if count > allowed:
            problems.append(
                f"{_rel(step)}: {count} '{needle}' comparisons "
                f"(baseline {allowed}) — new kernel variants register "
                "through parallel/kernels.py forms, not another "
                "dispatch arm in step.py")
    return problems


# Disk-write guard (round 24): the storage chaos matrix can only drill
# write paths that consult resilience.diskio — so every write-mode
# open/os.replace in the serving plane must live in a module whose
# writes DO consult it (the owners below), or be pragma'd out of the
# durability plane with a reason.  Owners are basenames: each of these
# modules routes its write path through a diskio fault site
# (wal_write/wal_fsync, cache_spill/cache_promote, events_emit,
# evidence_write, checkpoint_write_*) or IS the guard layer.
_DISKIO_DIRS = ("serving", "obs", "utils")
_DISKIO_OWNERS = ("diskio.py", "evidence_io.py", "wal.py", "events.py",
                  "cache.py", "checkpoint.py")
_DISKIO_PRAGMA = "# diskio: exempt"


def check_guarded_disk_writes(files) -> list[str]:
    """Every write-mode open / os.replace under serving|obs|utils sits
    in a guarded-owner module or carries the exempt pragma."""
    problems = []
    for f in files:
        if not any(d in f.parts for d in _DISKIO_DIRS):
            continue
        if f.name in _DISKIO_OWNERS:
            continue
        src = f.read_text(encoding="utf-8")
        if not any(n in src for n in ("open(", "os.replace",
                                      "write_text", "write_bytes")):
            continue
        lines = src.splitlines()
        try:
            tree = ast.parse(src)
        except SyntaxError:
            continue  # check 1 reports it
        for node in ast.walk(tree):
            if not isinstance(node, ast.Call):
                continue
            fn = node.func
            what = None
            if (isinstance(fn, ast.Name) and fn.id == "open"
                    and node.args
                    and any(c in _write_mode(node, 1) for c in "wax+")):
                what = "open"
            elif isinstance(fn, ast.Attribute):
                is_os = (isinstance(fn.value, ast.Name)
                         and fn.value.id == "os")
                if (fn.attr == "open"
                        and any(c in _write_mode(node, 0)
                                for c in "wax+")):
                    what = ".open"
                elif (fn.attr == "fdopen" and is_os
                      and any(c in _write_mode(node, 1)
                              for c in "wax+")):
                    what = "os.fdopen"
                elif fn.attr in ("write_text", "write_bytes"):
                    what = fn.attr
                elif fn.attr == "replace" and is_os:
                    what = "os.replace"
            if what is None:
                continue
            line = lines[node.lineno - 1] if node.lineno <= len(
                lines) else ""
            if _DISKIO_PRAGMA in line:
                continue
            problems.append(
                f"{_rel(f)}:{node.lineno}: unguarded {what} in the "
                "serving plane — route the write through "
                "resilience.diskio (guarded_open/guarded_replace or a "
                "consult in the owning module), or annotate "
                f"'{_DISKIO_PRAGMA} <why>' if it sits outside the "
                "durability plane")
    return problems


def main() -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--out", default="evidence/static_check.json")
    args = ap.parse_args()

    t0 = time.time()
    files = py_files()
    failures: list[str] = []
    failures += check_compiles(files)
    failures += check_bare_except(files)
    failures += check_stats_locking(files)
    failures += check_shared_curve_writes(files)
    failures += check_dispatch_ladders(files)
    failures += check_guarded_disk_writes(files)

    row = {
        "workload": "static-check compileall+bare-except+stats-lock"
                    "+shared-curve-writes+dispatch-ladders"
                    "+guarded-disk-writes",
        "files_checked": len(files),
        "wall_s": round(time.time() - t0, 3),
        "failures": len(failures),
        "failure_detail": failures[:20],
    }
    out = Path(args.out)
    out.parent.mkdir(parents=True, exist_ok=True)
    out.write_text(json.dumps(row, indent=2))
    print(json.dumps(row), flush=True)
    return 1 if failures else 0


if __name__ == "__main__":
    sys.exit(main())
