#!/usr/bin/env python
"""Benchmark sweep (the reference's PBS/qsub process-count sweeps, C12).

The reference's cluster scripts launched ``mpiexec -np {1,4,9,16,...}``
and its README tables were filled by hand; this sweep walks mesh shapes ×
backends × fusion depths on whatever devices are attached and emits
machine-readable rows (JSONL) plus a markdown table for BASELINE.md.

Usage:
  python scripts/sweep.py                       # quick sweep, current devices
  python scripts/sweep.py --size 4096 --iters 50 --out sweep.jsonl
"""

from __future__ import annotations

import argparse
import json
import os
import sys

import _path  # noqa: F401  (repo root onto sys.path)


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--size", type=int, default=2048)
    ap.add_argument("--iters", type=int, default=20)
    ap.add_argument("--reps", type=int, default=2)
    ap.add_argument("--out", default=None, help="JSONL output path")
    ap.add_argument("--platform", default=None,
                    help="force jax platform (e.g. cpu)")
    args = ap.parse_args()

    import jax

    if args.platform:
        from parallel_convolution_tpu.utils.platform import force_platform

        force_platform(args.platform, warn=True)

    from parallel_convolution_tpu.ops.filters import get_filter
    from parallel_convolution_tpu.parallel.mesh import dims_create, make_grid_mesh
    from parallel_convolution_tpu.utils import bench

    n = len(jax.devices())
    mesh_shapes = sorted(
        {(1, 1), dims_create(n), (1, n), (n, 1)} if n > 1 else {(1, 1)}
    )
    filt = get_filter("blur3")
    rows = []
    for shape in mesh_shapes:
        ndev = shape[0] * shape[1]
        mesh = make_grid_mesh(jax.devices()[:ndev], shape)
        # pallas_rdma sweeps the same fuse grid since the in-kernel
        # temporal fusion landed; configs its guards reject (ghost depth
        # vs block/band) land as labeled error rows like any other.
        for backend in ("shifted", "pallas", "xla_conv", "pallas_rdma"):
            for storage in ("f32", "bf16"):
                for fuse in (1, 4):
                    try:
                        row = bench.bench_iterate(
                            (args.size, args.size), filt, args.iters,
                            mesh=mesh, backend=backend, storage=storage,
                            fuse=fuse, reps=args.reps,
                        )
                    except Exception as e:
                        row = {"mesh": f"{shape[0]}x{shape[1]}",
                               "backend": backend, "storage": storage,
                               "fuse": fuse, "error": repr(e)[:120]}
                    rows.append(row)
                    print(json.dumps(row), flush=True)

    if args.out:
        with open(args.out, "w") as f:
            for row in rows:
                f.write(json.dumps(row) + "\n")

    ok = [r for r in rows if "error" not in r]
    if ok:
        print("\n| mesh | backend | storage | fuse | Gpx/s | Gpx/s/chip |",
              file=sys.stderr)
        print("|---|---|---|---|---|---|", file=sys.stderr)
        for r in sorted(ok, key=lambda r: -r["gpixels_per_s"]):
            print(f"| {r['mesh']} | {r['backend']} | {r['storage']} | "
                  f"{r['fuse']} | {r['gpixels_per_s']} | "
                  f"{r['gpixels_per_s_per_chip']} |", file=sys.stderr)
    return 0


if __name__ == "__main__":
    sys.exit(main())
