#!/usr/bin/env python
"""North-star rehearsal: the 65536² workflow at 8192², end to end.

SURVEY.md §7 names the hard part of BASELINE config 4: the full-size
image must NEVER materialize in one host buffer — disk blocks stream
straight into the device sharding, iterate on-mesh (u8 carries), with a
checkpoint snapshot mid-run, and stream back out.  This script rehearses
exactly that pipeline on the 8-virtual-device CPU mesh and PROVES the
memory claim with the worker's peak-RSS delta:

1. parent stripe-writes a deterministic 8192×8192 RGB raw (192 MB u8;
   stripes, so the parent never holds it whole either);
2. a clean child process (8 CPU devices, 2×4 mesh) runs
   ``load_sharded → run_checkpointed (u8, fuse, snapshot mid-run) →
   save_sharded`` and reports wall + ru_maxrss before/after;
3. a second child runs the NAIVE pipeline — full-image host read,
   f32 planar conversion on the host, gather-and-write at the end —
   for the differential memory proof;
4. parent bit-checks windows of the output against the NumPy oracle run
   on just window+margin (zero-boundary conv: interior pixels at depth
   > iters·r from the window edge depend only on the window — full-image
   oracle never needed);
5. prints ONE JSON row (the evidence/ record).

Why differential: on a CPU mesh, *device* memory IS host RAM, so the
sharded worker's RSS delta still contains the on-mesh f32 working set
(~1.3 GB here — on a real pod that lives in HBM and the host would hold
only streaming blocks).  What the sharded-IO design eliminates is the
HOST-side full-image staging: the naive pipeline pays everything the
sharded one does PLUS full u8 read + f32 planar + pad copy + full
gather.  The assertion is that the sharded pipeline's delta is at least
one u8-image smaller than the naive one's — the streamed path provably
never stages the image on the host.
"""

from __future__ import annotations

import json
import os
import resource
import subprocess
import sys
import time

import _path  # noqa: F401

import numpy as np

# Size is env-overridable so the test suite can run the identical
# pipeline at a fast size (tests/test_sharded_io.py); the recorded
# rehearsal uses the defaults.
ROWS = int(os.environ.get("NS_ROWS", 8192))
COLS = int(os.environ.get("NS_COLS", 8192))
MODE = "rgb"
ITERS, CKPT_EVERY, FUSE = 4, 2, 2
STRIPE = min(512, ROWS)


def _stripe(r0: int, rows: int) -> np.ndarray:
    """Deterministic stripe of the test image (seeded per-stripe)."""
    rng = np.random.default_rng(1000 + r0)
    y = np.linspace(0.0, 4.0 * np.pi * rows / ROWS, rows)[:, None]
    x = np.linspace(0.0, 4.0 * np.pi, COLS)[None, :]
    base = (127.5 + 80.0 * np.sin(y + 4.0 * np.pi * r0 / ROWS)
            * np.cos(x) + 40.0 * np.sin(0.5 * (x + y)))
    out = np.stack([base + rng.normal(0, 12, size=(rows, COLS))
                    for _ in range(3)], axis=-1)
    return np.clip(out, 0, 255).astype(np.uint8)


def write_input(path: str) -> None:
    with open(path, "wb") as f:
        for r0 in range(0, ROWS, STRIPE):
            f.write(_stripe(r0, min(STRIPE, ROWS - r0)).tobytes())


def worker(tmp: str, pipeline: str) -> int:
    """Child: one pipeline variant under RSS accounting."""
    # The env var alone does not survive the site hook's programmatic
    # platform pin (utils/platform.py module docstring) — re-pin via
    # jax.config BEFORE any backend initializes, as halo_proxy does.
    from parallel_convolution_tpu.utils.platform import force_platform

    force_platform("cpu")

    from parallel_convolution_tpu.ops.filters import get_filter
    from parallel_convolution_tpu.parallel.mesh import make_grid_mesh
    from parallel_convolution_tpu.utils import checkpoint, imageio, sharded_io

    import jax

    devs = jax.devices()
    mesh = make_grid_mesh(devs)
    base_kb = resource.getrusage(resource.RUSAGE_SELF).ru_maxrss

    src = os.path.join(tmp, "in.raw")
    dst = os.path.join(tmp, f"out_{pipeline}.raw")
    filt = get_filter("blur3")
    t0 = time.perf_counter()
    row = {}
    if pipeline == "sharded":
        xs = sharded_io.load_sharded(src, ROWS, COLS, MODE, mesh,
                                     dtype=np.dtype(np.uint8))
        out = checkpoint.run_checkpointed(
            xs, filt, ITERS, mesh, (ROWS, COLS),
            ckpt_dir=os.path.join(tmp, "ck"), every=CKPT_EVERY,
            quantize=True, backend="shifted", fuse=FUSE,
        )
        sharded_io.save_sharded(dst, out, ROWS, COLS, MODE)
        row["snapshots"] = sorted(os.listdir(os.path.join(tmp, "ck")))
    else:
        # The pipeline sharded IO exists to avoid: whole image on the
        # host, f32 planar conversion, full gather at the end.
        from parallel_convolution_tpu.parallel import step as step_lib

        img = imageio.read_raw(src, ROWS, COLS, MODE)
        x = imageio.interleaved_to_planar(img).astype(np.float32)
        out = step_lib.sharded_iterate(x, filt, ITERS, mesh=mesh,
                                       quantize=True, backend="shifted",
                                       fuse=FUSE)
        imageio.write_raw(
            dst, imageio.planar_to_interleaved(
                np.asarray(out).astype(np.uint8)))
    wall = time.perf_counter() - t0
    peak_kb = resource.getrusage(resource.RUSAGE_SELF).ru_maxrss

    img_bytes = ROWS * COLS * 3
    delta = (peak_kb - base_kb) * 1024
    row.update({
        "pipeline": pipeline,
        "mesh": "x".join(str(s) for s in mesh.shape.values()),
        "devices": len(devs),
        "wall_s": round(wall, 2),
        "rss_base_mb": round(base_kb / 1024, 1),
        "rss_peak_mb": round(peak_kb / 1024, 1),
        "rss_delta_mb": round(delta / 2**20, 1),
        "image_mb": round(img_bytes / 2**20, 1),
        "rss_delta_vs_image": round(delta / img_bytes, 2),
    })
    print(json.dumps(row))
    return 0


def spot_check(tmp: str) -> dict:
    """Windows of out.raw vs the oracle on window+margin only."""
    from parallel_convolution_tpu.ops import oracle
    from parallel_convolution_tpu.ops.filters import get_filter

    filt = get_filter("blur3")
    m = ITERS * filt.radius  # influence radius of the iterated stencil
    out = np.memmap(os.path.join(tmp, "out_sharded.raw"), dtype=np.uint8,
                    mode="r", shape=(ROWS, COLS, 3))
    # Input windows re-generated from stripes (parent never holds the
    # full image): window rows r0-m .. r1+m must cover whole stripes.
    win = min(256, ROWS // 2, COLS // 2)
    results = {}
    for name, (wr, wc) in {
        "corner": (0, 0),
        "center": (ROWS // 2 - win // 2, COLS // 2 - win // 2),
        "edge": (ROWS - win, COLS // 3),
    }.items():
        r0, r1 = max(0, wr - m), min(ROWS, wr + win + m)
        c0, c1 = max(0, wc - m), min(COLS, wc + win + m)
        s0 = (r0 // STRIPE) * STRIPE
        s1 = min(ROWS, ((r1 + STRIPE - 1) // STRIPE) * STRIPE)
        block = np.concatenate(
            [_stripe(s, min(STRIPE, ROWS - s)) for s in
             range(s0, s1, STRIPE)], axis=0)[r0 - s0 : r1 - s0, c0:c1]
        # Oracle on the window+margin; its interior (≥ m from the window
        # edge, unless that edge IS the image boundary, where the real
        # zero ring applies) is exact.
        ref = oracle.run_serial_u8(block, filt, ITERS)
        ir0 = wr - r0
        ic0 = wc - c0
        got = np.asarray(out[wr : wr + win, wc : wc + win])
        want = ref[ir0 : ir0 + win, ic0 : ic0 + win]
        results[name] = bool(np.array_equal(got, want))
    return results


def main() -> int:
    if len(sys.argv) > 1 and sys.argv[1] == "--worker":
        return worker(sys.argv[2], sys.argv[3])

    import tempfile

    from parallel_convolution_tpu.utils.platform import child_env_cpu

    with tempfile.TemporaryDirectory() as tmp:
        write_input(os.path.join(tmp, "in.raw"))
        env = child_env_cpu(8)
        repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
        env["PYTHONPATH"] = os.pathsep.join(
            [repo, os.path.dirname(os.path.abspath(__file__))]
            + ([env["PYTHONPATH"]] if env.get("PYTHONPATH") else []))

        rows = {}
        for pipeline in ("sharded", "naive"):
            proc = subprocess.run(
                [sys.executable, os.path.abspath(__file__), "--worker",
                 tmp, pipeline],
                env=env, capture_output=True, text=True, timeout=3600,
            )
            if proc.returncode != 0:
                print(json.dumps({"error": proc.stderr[-2000:]}))
                return 1
            rows[pipeline] = json.loads(
                proc.stdout.strip().splitlines()[-1])

        row = rows["sharded"]
        row["workload"] = (f"blur3 {ROWS}x{COLS} {MODE} {ITERS} iters "
                           f"u8 sharded-io checkpoint(every={CKPT_EVERY}) "
                           f"fuse={FUSE}")
        row["naive_pipeline"] = rows["naive"]
        img_mb = row["image_mb"]
        saved = rows["naive"]["rss_delta_mb"] - row["rss_delta_mb"]
        row["host_staging_saved_mb"] = round(saved, 1)
        # The streamed path must save at least one whole u8 image of host
        # staging vs the naive full-buffer pipeline (it actually saves
        # read + planar-f32 + gather copies; see module docstring).  At
        # test-shrunk sizes (< 64 MB) allocator noise swamps RSS deltas,
        # so the differential proof only gates the full-size rehearsal.
        row["no_full_host_staging"] = bool(saved > img_mb or img_mb < 64)
        row["outputs_identical"] = _files_equal(
            os.path.join(tmp, "out_sharded.raw"),
            os.path.join(tmp, "out_naive.raw"))
        row["oracle_windows_bitexact"] = spot_check(tmp)
        row["ok"] = (row["no_full_host_staging"]
                     and row["outputs_identical"]
                     and all(row["oracle_windows_bitexact"].values()))
        print(json.dumps(row))
        return 0 if row["ok"] else 1


def _files_equal(a: str, b: str, chunk: int = 1 << 22) -> bool:
    if os.path.getsize(a) != os.path.getsize(b):
        return False
    with open(a, "rb") as fa, open(b, "rb") as fb:
        while True:
            ca, cb = fa.read(chunk), fb.read(chunk)
            if ca != cb:
                return False
            if not ca:
                return True


if __name__ == "__main__":
    sys.exit(main())
