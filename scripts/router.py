#!/usr/bin/env python
"""Boot the replica-set router (serving/router.py) behind HTTP.

Two deployment shapes, one wire format:

  # N in-process replicas (each its own ConvolutionService + mesh) —
  # the one-host / CPU-smoke shape:
  XLA_FLAGS=--xla_force_host_platform_device_count=8 JAX_PLATFORMS=cpu \\
    python scripts/router.py --port 8090 --replicas 3 --mesh 2x2 \\
      --tenant-rate 50 --tenant-burst 16

  # routing over already-running scripts/serve.py replicas:
  python scripts/router.py --port 8090 \\
      --target http://host-a:8080 --target http://host-b:8080

  # ONE member of a sharded control plane (round 21): three of these,
  # each owning one shard's WAL lineage under a shared --state-dir,
  # peer-synced and ready to take over a dead peer's shards:
  python scripts/router.py --port 8090 --replicas 2 --mesh 1x2 \\
      --shards 3 --name rA --state-dir /var/pctpu/ctl \\
      --advertise http://host-a:8090 \\
      --assign 0=rA --assign 1=rB --assign 2=rC \\
      --peer rB=http://host-b:8091 --peer rC=http://host-c:8092

  curl -s localhost:8090/readyz | python -m json.tool   # 200 iff any
  #   replica is ready; per-replica breaker states in the payload
  python scripts/loadgen.py --target http://127.0.0.1:8090 --n 200 ...

Clients cannot tell the router from a replica (same ``/v1/convolve`` /
``/v1/converge`` bodies) except for the extra ``router`` stamp in each
response: the serving replica, the consistent-hash home, and the
attempt/failover/spill counts.  Tenant identity rides the ``x-tenant``
header or a ``tenant`` body field; ``--tenant-rate 0`` disables quota.
"""

from __future__ import annotations

import argparse
import json
import signal
import sys

import _path  # noqa: F401  (repo root + JAX_PLATFORMS re-apply)


def main() -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--host", default="127.0.0.1")
    ap.add_argument("--port", type=int, default=8090,
                    help="0 = pick a free port (printed on boot)")
    ap.add_argument("--target", action="append", default=[], metavar="URL",
                    help="HTTP replica base URL (repeatable; "
                         "scripts/serve.py instances)")
    ap.add_argument("--replicas", type=int, default=0, metavar="N",
                    help="boot N in-process replicas instead of --target")
    ap.add_argument("--mesh", default=None,
                    help="RxC grid per in-process replica")
    ap.add_argument("--platform", default=None,
                    help="force a jax platform (e.g. cpu) before init")
    ap.add_argument("--plans", default=None, metavar="PLANS_JSON",
                    help="tuner plan file for in-process replicas")
    # Replica service knobs (in-process only):
    ap.add_argument("--max-batch", type=int, default=8)
    ap.add_argument("--max-delay-ms", type=float, default=5.0)
    ap.add_argument("--max-queue", type=int, default=64)
    # Router knobs:
    ap.add_argument("--tenant-rate", type=float, default=0.0,
                    help="per-tenant token refill rate (req/s); 0 = no "
                         "tenant quota")
    ap.add_argument("--tenant-burst", type=float, default=16.0,
                    help="per-tenant bucket capacity")
    ap.add_argument("--vnodes", type=int, default=64,
                    help="virtual nodes per replica on the hash ring")
    ap.add_argument("--breaker-threshold", type=int, default=3,
                    help="consecutive failures that open a replica's "
                         "circuit")
    ap.add_argument("--breaker-cooldown-s", type=float, default=1.0)
    ap.add_argument("--poll-interval-s", type=float, default=0.25,
                    help="active /readyz health-poll period")
    ap.add_argument("--load-factor", type=float, default=2.0,
                    help="bounded-load spill: a replica carries at most "
                         "this multiple of the fair in-flight share")
    ap.add_argument("--hedge-ms", type=float, default=None,
                    help="fire one extra attempt when the home replica "
                         "hasn't answered within this budget (off by "
                         "default)")
    # Round 17 — fleet autoscaling + cost-priced admission:
    ap.add_argument("--autoscale-max", type=int, default=0, metavar="N",
                    help="enable the autoscaler: grow the in-process "
                         "pool up to N replicas under load and shrink "
                         "back on idle (0 = fixed pool; in-process "
                         "replicas only)")
    ap.add_argument("--autoscale-interval-s", type=float, default=0.5,
                    help="control-loop tick period")
    ap.add_argument("--autoscale-cooldown-s", type=float, default=5.0,
                    help="minimum wall time between scale actions")
    ap.add_argument("--price-admission", action="store_true",
                    help="charge tenant buckets the cost model's "
                         "predicted device-seconds per request instead "
                         "of 1 token (--tenant-rate then means "
                         "device-seconds per second)")
    ap.add_argument("--wal", default=None, metavar="PATH",
                    help="arm the crash-safe control plane (round 19): "
                         "journal admissions/tokens/finals/ring/debt "
                         "to PATH and RECOVER from it at boot — "
                         "restarting this script on the same PATH is a "
                         "fenced takeover (the epoch bumps; a zombie "
                         "predecessor gets typed stale_epoch rejects)")
    # Round 21 — sharded control plane (N active routers):
    ap.add_argument("--shards", type=int, default=0, metavar="N",
                    help="partition the control plane into N shards: "
                         "this process becomes ONE active router of a "
                         "fleet, owning the shards --assign maps to "
                         "--name (each on its own WAL lineage under "
                         "--state-dir) and redirecting the rest with "
                         "typed wrong_shard rejects; requires "
                         "--state-dir")
    ap.add_argument("--name", default="r0",
                    help="this router's fleet-unique name (sharded "
                         "mode)")
    ap.add_argument("--state-dir", default=None, metavar="DIR",
                    help="directory holding the per-shard WAL "
                         "lineages (shard-N.wal); booting any fleet "
                         "member over the same DIR re-adopts its "
                         "shards via the fenced takeover")
    ap.add_argument("--peer", action="append", default=[],
                    metavar="NAME=URL",
                    help="peer router (repeatable): anti-entropy sync "
                         "target, debt-replication source, and "
                         "takeover candidate for this router's shards")
    ap.add_argument("--assign", action="append", default=[],
                    metavar="SHARD=NAME",
                    help="boot ownership of SHARD (repeatable; shards "
                         "left unassigned default to --name)")
    ap.add_argument("--advertise", default=None, metavar="URL",
                    help="own base URL published in the shard map "
                         "(what redirected clients should dial)")
    ap.add_argument("--sync-interval-s", type=float, default=0.25,
                    help="peer anti-entropy period (sharded mode)")
    ap.add_argument("--suspect-after", type=int, default=3,
                    help="consecutive failed syncs before a dead "
                         "peer's shards are taken over")
    args = ap.parse_args()

    if bool(args.target) == bool(args.replicas):
        ap.error("exactly one of --target ... or --replicas N required")

    if args.platform:
        from parallel_convolution_tpu.utils.platform import force_platform

        force_platform(args.platform, warn=True)

    from parallel_convolution_tpu.obs import events as obs_events
    from parallel_convolution_tpu.resilience import diskio, faults
    from parallel_convolution_tpu.serving.router import (
        HTTPReplica, InProcessReplica, ReplicaRouter, TenantQuotas,
        make_router_http_server,
    )

    faults.install_from_env()
    diskio.install_from_env()   # PCTPU_DISK_MODES: storage fault shapes
    obs_events.install_from_env()

    if args.target:
        replicas = [HTTPReplica(url, name=f"r{i}")
                    for i, url in enumerate(args.target)]
    else:
        from parallel_convolution_tpu.parallel.mesh import mesh_from_spec
        from parallel_convolution_tpu.serving.service import (
            ConvolutionService,
        )
        from parallel_convolution_tpu.utils.platform import (
            enable_compile_cache,
        )

        enable_compile_cache()

        def factory():
            return ConvolutionService(
                mesh_from_spec(args.mesh), max_batch=args.max_batch,
                max_delay_s=args.max_delay_ms / 1e3,
                max_queue=args.max_queue, plans=args.plans)

        replicas = [InProcessReplica(factory, name=f"r{i}")
                    for i in range(args.replicas)]

    quotas = (TenantQuotas(args.tenant_rate, args.tenant_burst)
              if args.tenant_rate > 0 else None)
    pricer = None
    if args.price_admission:
        from parallel_convolution_tpu.serving.pricing import WorkPricer

        grid = (1, 1)
        if args.mesh:
            r, c = args.mesh.lower().split("x")
            grid = (int(r), int(c))
        pricer = WorkPricer(grid=grid)
    if args.shards:
        if not args.state_dir:
            ap.error("--shards requires --state-dir (the per-shard "
                     "WAL lineages live there)")
        if args.wal:
            ap.error("--shards replaces --wal: every shard gets its "
                     "own lineage under --state-dir")
        if args.autoscale_max:
            ap.error("--autoscale-max is not supported in sharded "
                     "mode")
        from parallel_convolution_tpu.serving.peers import (
            HTTPPeer, ShardRouter,
        )

        peers, addrs = [], {}
        for spec in args.peer:
            nm, _, url = spec.partition("=")
            if not url:
                ap.error(f"--peer wants NAME=URL, got {spec!r}")
            peers.append(HTTPPeer(nm, url))
            addrs[nm] = url
        if args.advertise:
            addrs[args.name] = args.advertise
        assignments = {}
        for spec in args.assign:
            sh, _, nm = spec.partition("=")
            if not nm:
                ap.error(f"--assign wants SHARD=NAME, got {spec!r}")
            assignments[sh] = nm
        for s in range(args.shards):
            assignments.setdefault(str(s), args.name)
        owned = [s for s, o in assignments.items()
                 if o == args.name]
        router = ShardRouter(
            args.name, replicas, n_shards=args.shards, owned=owned,
            state_dir=args.state_dir, assignments=assignments,
            addrs=addrs, quotas=quotas, pricer=pricer, peers=peers,
            sync_interval_s=args.sync_interval_s,
            suspect_after=args.suspect_after, vnodes=args.vnodes,
            breaker_threshold=args.breaker_threshold,
            breaker_cooldown_s=args.breaker_cooldown_s,
            poll_interval_s=args.poll_interval_s,
            load_factor=args.load_factor,
            hedge_s=args.hedge_ms / 1e3 if args.hedge_ms else None)
    else:
        router = ReplicaRouter(
            replicas, quotas=quotas, pricer=pricer, vnodes=args.vnodes,
            breaker_threshold=args.breaker_threshold,
            breaker_cooldown_s=args.breaker_cooldown_s,
            poll_interval_s=args.poll_interval_s,
            load_factor=args.load_factor,
            hedge_s=args.hedge_ms / 1e3 if args.hedge_ms else None,
            wal=args.wal)

    scaler = None
    if args.autoscale_max:
        if args.target:
            ap.error("--autoscale-max needs in-process --replicas (HTTP "
                     "targets have no provisioner to grow through)")
        from parallel_convolution_tpu.serving.autoscaler import AutoScaler

        def transport_factory(name):
            return InProcessReplica(factory, name=name)

        scaler = AutoScaler(
            router, transport_factory, min_replicas=len(replicas),
            max_replicas=max(args.autoscale_max, len(replicas)),
            interval_s=args.autoscale_interval_s,
            cooldown_s=args.autoscale_cooldown_s)
        scaler.start()

    server = make_router_http_server(router, args.host, args.port)
    host, port = server.server_address[:2]
    obs_events.emit("router", event="boot", url=f"http://{host}:{port}",
                    replicas=[r.name for r in replicas])
    boot = {"routing": f"http://{host}:{port}",
            "replicas": [r.name for r in replicas],
            "tenant_quota": bool(quotas),
            "priced_admission": bool(pricer),
            "autoscale_max": args.autoscale_max or None}
    if args.shards:
        smw = router.shardmap_wire()
        boot.update(name=args.name, shards=args.shards,
                    owned=sorted(router.snapshot()["owned_shards"]),
                    map_version=smw["version"],
                    state_dir=args.state_dir,
                    peers=[p.name for p in router.peers])
    elif args.wal:
        boot.update(wal=args.wal, epoch=router.epoch,
                    recovery=router.recovery)
    print(json.dumps(boot), flush=True)

    stopping = []

    def _stop(signum, frame):
        import threading

        if stopping:
            return
        stopping.append(signum)
        print(json.dumps({"stopping": signum,
                          "final": router.snapshot()}), flush=True)
        threading.Thread(target=server.shutdown, daemon=True).start()

    signal.signal(signal.SIGINT, _stop)
    signal.signal(signal.SIGTERM, _stop)
    try:
        server.serve_forever()
    finally:
        server.server_close()
        if scaler is not None:
            scaler.close()
        router.close()
    return 0


if __name__ == "__main__":
    sys.exit(main())
