#!/usr/bin/env python
"""Fair MXU datapoints for the stencil workload (DESIGN.md roofline §).

Round-1's DESIGN.md dismissed the MXU partly on a strawman: 1-channel
NCHW ``lax.conv`` (0.08 Gpx/s, OOM at 8192²) is XLA's worst lowering, not
the MXU's best shot.  This script measures the honest alternatives:

1. ``xla_conv_nhwc`` — the TPU-native NHWC/HWIO layout of the same conv.
2. ``banded_matmul`` — the separable blur as two dense banded matmuls
   (Y = Bh @ X @ Bw, bf16): the formulation that actually fills the
   128×128 systolic array.

Measured on the attached v5e (2026-07-29, recorded in DESIGN.md):
``pallas_sep`` 119.2 Gpx/s, ``banded_matmul`` 11.2 Gpx/s (~11× slower),
``xla_conv_nhwc`` 0.23 Gpx/s (~500× slower).  So the honest MXU
formulation is within one order of magnitude — not the "orders of
magnitude" earlier prose claimed — but still clearly loses: the banded
matmul spends 16384 MXU flops/px where the separable VPU pass spends 12,
a ~1400× flop inflation that the MXU's peak-flops advantage repays only
down to that measured ~11× gap.  Emits one JSON row per candidate.
"""

from __future__ import annotations

import json
import sys

import _path  # noqa: F401


def main() -> int:
    from parallel_convolution_tpu.utils.platform import (
        apply_platform_env, enable_compile_cache, on_tpu,
    )

    apply_platform_env()
    enable_compile_cache()

    import jax
    import jax.numpy as jnp
    import numpy as np

    from parallel_convolution_tpu.ops.filters import get_filter
    from parallel_convolution_tpu.utils import bench

    N = 4096 if on_tpu() else 512
    iters = 10 if on_tpu() else 2
    filt = get_filter("blur3")
    taps = np.asarray(filt.taps, np.float32)
    sep = filt.separable()
    col_t, row_t = (np.asarray(v, np.float32) for v in sep)

    rng = np.random.default_rng(0)
    x = jnp.asarray(rng.integers(0, 256, (N, N)), jnp.float32)

    rows = []

    def emit(name, fn, arg, flops_per_px):
        # slope_wall, not wall: the MXU candidates and the VPU reference
        # must share the fence-constant-cancelling scheme or the ~140 ms
        # proxy readback charges only the candidates.
        secs = bench.slope_wall(fn, arg, reps=2)
        gpx = N * N * iters / secs / 1e9
        row = {
            "candidate": f"{name}@{N}",
            "wall_s": round(secs, 4),
            "gpixels_per_s": round(gpx, 3),
            "flops_per_px_per_iter": flops_per_px,
            "iters": iters,
        }
        rows.append(row)
        print(json.dumps(row), flush=True)

    # 1. NHWC conv — XLA's TPU-native layout for the same 3x3 conv.
    rhs_nhwc = jnp.asarray(taps[:, :, None, None], jnp.float32)  # HWIO

    @jax.jit
    def conv_nhwc(v):
        def body(_, a):
            out = jax.lax.conv_general_dilated(
                a[None, :, :, None], rhs_nhwc, (1, 1),
                [(1, 1), (1, 1)],
                dimension_numbers=("NHWC", "HWIO", "NHWC"),
                precision=jax.lax.Precision.HIGHEST,
            )
            return out[0, :, :, 0]
        return jax.lax.fori_loop(0, iters, body, v)

    try:
        emit("xla_conv_nhwc/f32", conv_nhwc, x, 18)
    except Exception as e:
        print(json.dumps({"candidate": f"xla_conv_nhwc/f32@{N}",
                          "error": repr(e)[:200]}), flush=True)

    # 2. Dense banded matmul (bf16): the MXU-native formulation.
    #    Bh (N,N) carries col taps on its three diagonals, Bw the row taps;
    #    one iteration is Y = (Bh @ X) @ Bw — 2 * 2*N^3 flops vs the
    #    stencil's 12*N^2: a x(N/3) flop inflation the MXU must repay.
    def banded(tvec):
        b = np.zeros((N, N), np.float32)
        i = np.arange(N)
        b[i, i] = tvec[1]
        b[i[:-1], i[:-1] + 1] = tvec[2]
        b[i[1:], i[1:] - 1] = tvec[0]
        return jnp.asarray(b, jnp.bfloat16)

    bh, bw = banded(col_t), banded(row_t)

    @jax.jit
    def banded_mm(v):
        def body(_, a):
            return ((bh @ a.astype(jnp.bfloat16)) @ bw).astype(jnp.float32)
        return jax.lax.fori_loop(0, iters, body, v)

    try:
        emit("banded_matmul/bf16", banded_mm, x, 4 * N)
    except Exception as e:
        print(json.dumps({"candidate": f"banded_matmul/bf16@{N}",
                          "error": repr(e)[:200]}), flush=True)

    # Reference row: the VPU Pallas separable path at the same size.
    if on_tpu():
        from parallel_convolution_tpu.parallel.mesh import make_grid_mesh

        r = bench.bench_iterate((N, N), filt, iters,
                                mesh=make_grid_mesh(), backend="pallas_sep",
                                storage="bf16", fuse=min(8, iters), reps=2)
        print(json.dumps({"candidate": f"pallas_sep/bf16@{N}",
                          "wall_s": r["wall_s"],
                          "gpixels_per_s": r["gpixels_per_s"],
                          "flops_per_px_per_iter": 12,
                          "iters": iters}), flush=True)
    return 0


if __name__ == "__main__":
    sys.exit(main())
