#!/bin/sh
# SUPERSEDED (resilience PR): express future chip sessions as a JSON legs
# file for scripts/run_supervised.py (tested retry/terminal logic in
# parallel_convolution_tpu/resilience/).  Kept as the round-5 record.
#
# Round-5 follow-up chip session.  First run (2026-07-31 ~05:57 UTC)
# got through the bf16 fuse-40/48 rows (preserved in
# evidence/tune_convex_r5b.jsonl.partial: 122.1 / 125.7 Gpx/s — the
# fuse curve has plateaued) before the tunnel died mid-compile; this
# revision reorders the remaining legs by value so the next window
# lands the proofs before any sweep:
#
#   1. tiled_repro_r5b  — the ladder WITH rung a0 (ANY operands alone),
#      completing the HBM-scratch attribution
#   2. rdma_silicon_r5b — monolithic re-proof + the tiled kernel via the
#      operand-backed pad: the bit-exactness-on-silicon record
#   3. helper_crash_probe — failure-class test (clean VMEM error vs
#      helper HTTP 500) motivated by the plain stencil crashing the
#      helper at 1536x512 tiles
#   4. fill-in tuner points (plateau region; lowest value)
#
# run_to_keep preserves a timed-out leg's partial rows as
# "$out.partial" instead of deleting them (the r5 runner lost real chip
# rows to its own cleanup).
set -x
cd "$(dirname "$0")/.."

# Dead-tunnel guard: a dead tunnel makes jax HANG on backend init.
timeout 60 python -c "import jax; print(jax.devices())" \
  || { echo "tunnel dead; aborting chip session" >&2; exit 1; }

LEG_TIMEOUT="${LEG_TIMEOUT:-1800}"

run_to_keep() {
  out="$1"; shift
  if timeout "$LEG_TIMEOUT" "$@" \
       > "$out.tmp" 2> "/tmp/$(basename "$out").err"; then
    mv "$out.tmp" "$out" && echo "$out OK"
  else
    if [ -s "$out.tmp" ]; then
      # APPEND to any existing partial — a re-armed retry that dies
      # early must not clobber rows a longer earlier attempt saved.
      cat "$out.tmp" >> "$out.partial" && rm -f "$out.tmp"
      echo "$out FAILED; partial rows appended to $out.partial" >&2
    else
      rm -f "$out.tmp"
      echo "$out FAILED (stderr: /tmp/$(basename "$out").err)" >&2
    fi
  fi
}

[ -e evidence/tiled_repro_r5b.jsonl ] || \
  run_to_keep evidence/tiled_repro_r5b.jsonl python scripts/tiled_repro_probe.py
[ -e evidence/rdma_silicon_r5b.json ] || \
  run_to_keep evidence/rdma_silicon_r5b.json python scripts/rdma_on_silicon.py
[ -e evidence/helper_crash_probe_r5.jsonl ] || \
  run_to_keep evidence/helper_crash_probe_r5.jsonl \
    python scripts/helper_crash_probe.py

# Fill-in tuner points past the measured plateau (1024x512 fuse 40/48
# already recorded in the .partial).
[ -e evidence/tune_convex_r5b_fill.jsonl ] || \
  run_to_keep evidence/tune_convex_r5b_fill.jsonl \
    python scripts/tune_pallas.py --backend pallas_sep --storage bf16 \
      --iters 100 --tiles 1024x512 --fuses 56
