#!/bin/sh
# Round-5 follow-up chip session: re-run what chip_session_r5.sh leg 1
# lost and extend past its sweep edge.  Context: leg 1 timed out after
# the third 1536x512 point hung (two prior 1536x512 points crashed the
# remote compile helper with the SAME HTTP 500 / tpu_compile_helper
# subprocess crash that blocks the tiled RDMA kernel — a key
# attribution datapoint: the crash is large-tile-related, not
# RDMA-specific), and run_to's cleanup deleted the partial .jsonl.tmp
# holding three good rows (recovered with labeled provenance in
# evidence/tune_convex_r5_recovered.jsonl).
#
# Differences from r5 leg 1:
#   - drops the 1536x512 / 2048x512 tiles (attributed crashers); keeps
#     1024x512 (measured good) and adds 1024x768,
#   - extends fuses past the 40 edge (fuse=40 was the best measured row),
#   - run_to_keep preserves a timed-out leg's partial rows as
#     "$out.partial" instead of deleting them.
set -x
cd "$(dirname "$0")/.."

timeout 60 python -c "import jax; print(jax.devices())" \
  || { echo "tunnel dead; aborting chip session" >&2; exit 1; }

LEG_TIMEOUT="${LEG_TIMEOUT:-2400}"

run_to_keep() {
  out="$1"; shift
  if timeout "$LEG_TIMEOUT" "$@" \
       > "$out.tmp" 2> "/tmp/$(basename "$out").err"; then
    mv "$out.tmp" "$out" && echo "$out OK"
  else
    # A timed-out tuner leg still printed real chip rows; keep them
    # under a name that cannot be mistaken for a completed record.
    if [ -s "$out.tmp" ]; then
      mv "$out.tmp" "$out.partial"
      echo "$out FAILED; partial rows kept at $out.partial" >&2
    else
      rm -f "$out.tmp"
      echo "$out FAILED (stderr: /tmp/$(basename "$out").err)" >&2
    fi
  fi
}

# 1. Focused flagship re-tune: surviving tile + fuse sweep past the edge.
run_to_keep evidence/tune_convex_r5b.jsonl \
  python scripts/tune_pallas.py --backend pallas_sep --storage bf16 \
    --iters 100 --tiles 1024x512,1024x768 --fuses 40,48,56,64

# 2. Re-run any r5 leg that failed (each guarded by [ -e ] so a leg that
#    landed in the main session is not repeated).
[ -e evidence/profile_flagship_r5.jsonl ] || \
  run_to_keep evidence/profile_flagship_r5.jsonl \
    python scripts/profile_flagship.py --size 8192 --fuse 32 --reps 3 --ab
[ -e evidence/tune_convex_r5_u8.jsonl ] || \
  run_to_keep evidence/tune_convex_r5_u8.jsonl \
    python scripts/tune_pallas.py --backend pallas_sep --storage u8 \
      --iters 100 --tiles 1024x512,2048x512 --fuses 32,40
[ -e evidence/rdma_silicon_r5.json ] || \
  run_to_keep evidence/rdma_silicon_r5.json python scripts/rdma_on_silicon.py
[ -e evidence/tiled_repro_r5.jsonl ] || \
  run_to_keep evidence/tiled_repro_r5.jsonl python scripts/tiled_repro_probe.py
[ -e evidence/validate_walls_r5.json ] || \
  run_to_keep evidence/validate_walls_r5.json python scripts/validate_walls.py

# 3. Failure-class attribution: is the helper HTTP 500 just a masked
#    VMEM resource error?  (Motivated by the plain stencil kernel
#    crashing the helper at 1536x512 tiles in the r5 leg-1 sweep.)
run_to_keep evidence/helper_crash_probe_r5.jsonl \
  python scripts/helper_crash_probe.py

# 4. Tiled-RDMA closure (VERDICT r4 item 2): the r5 ladder pinned the
#    crash to rung a (HBM scratch + ANY operands together); the ladder
#    now carries rung a0 (ANY operands alone) to split that ambiguity,
#    and fused_rdma_step gained the operand-backed pad workaround which
#    rdma_on_silicon picks up by default on silicon.  Fresh names: the
#    r5 records exist and stay as the pre-workaround baseline.
run_to_keep evidence/tiled_repro_r5b.jsonl python scripts/tiled_repro_probe.py
run_to_keep evidence/rdma_silicon_r5b.json python scripts/rdma_on_silicon.py
