#!/usr/bin/env python
"""Volumetric smoke: the rank-3 subsystem's claims, end-to-end on CPU.

The ``run_t1.sh --volume-smoke`` leg.  Gates, in order:

1. FORMS vs ORACLE + BYTE IDENTITY — a seeded 3D Poisson state run
   through every registered rank-3 form on the ``--mesh`` grid must
   match the independent float64 numpy oracle (``volumes.oracle3`` —
   global np.pad ghosting, different arithmetic); the _stack twins must
   be BYTE-identical to their planar siblings; and the 2x4 result must
   be byte-identical to the single-device (1x1) run — the decomposition
   is invisible.
2. EQUAL-ACCURACY CONVERGENCE WIN — the 8th-order 25-point star on an
   N=16 cube must reach the same manufactured-solution error
   (``--target-err``) in measurably fewer damped-Jacobi sweeps than the
   7-point star needs on the N=48 cube its 2nd-order accuracy demands:
   sweep ratio > ``--min-ratio`` (measured ~5x at the defaults).
   Manufactured problem: u* = sin(2 pi x)sin(2 pi y)sin(2 pi z) on the
   PERIODIC unit cube, f = 3 (2 pi)^2 u* h^2, x = i h, h = 1/N.
   Periodic ghosts are exact, so each star converges at its full
   interior order — with zero-ghost Dirichlet faces the radius-4 star
   drops below 8th order at the rim and the coarse grid can't reach
   the target (the reason this gate is periodic).
3. SERVING ROUND-TRIP — a typed volume request through the in-process
   service: the JSON and r20 binary-frame wires byte-identical, the
   response matching the oracle; plus a Gray-Scott converge stream
   whose final row matches the oracle at the same iteration count.
4. PERF SENTRY FOLD — the sweep-throughput rows (stamped ``rank: 3``,
   so ``perf_gate.row_key`` lanes them apart from every rank-2 row)
   seed and re-gate the smoke's OWN history through perf_gate.py.

One summary row lands in ``--out`` (``evidence/volume_smoke.json``, the
supervisor leg's done_file) with ``"failures": 0`` iff every gate held.
"""

from __future__ import annotations

import argparse
import base64
import json
import subprocess
import sys
import time
from pathlib import Path

import _path  # noqa: F401  (repo root + JAX_PLATFORMS re-apply)

SCRIPTS = Path(__file__).resolve().parent


def _poisson_state(n: int):
    """(state, u*) of the manufactured PERIODIC problem on an N^3
    cube: u0 = 0, rhs plane f = 3 (2 pi)^2 u* h^2, x = i h, h = 1/N."""
    import numpy as np

    h = 1.0 / n
    x = np.arange(n) * h
    s = np.sin(2.0 * np.pi * x)
    ustar = np.einsum("i,j,k->ijk", s, s, s)
    f = 3.0 * (2.0 * np.pi) ** 2 * ustar * h * h
    state = np.stack([np.zeros_like(ustar), f]).astype(np.float32)
    return state, ustar.astype(np.float64)


def _sweeps_to_err(driver, state, ustar, name, mesh, target, cap,
                   chunk=25):
    """(sweeps, final_err, cells_per_s) running ``name`` until the
    solution error vs u* reaches ``target`` (or the sweep cap)."""
    import numpy as np

    sweeps, err = 0, float("inf")
    cells = 2 * int(np.prod(state.shape[1:]))
    t0 = time.perf_counter()
    while sweeps < cap:
        n = min(chunk, cap - sweeps)
        state = driver.volume_iterate(state, name, n, mesh=mesh,
                                      boundary="periodic")
        sweeps += n
        err = float(np.abs(state[0].astype(np.float64) - ustar).max())
        if err <= target:
            break
    dt = max(time.perf_counter() - t0, 1e-9)
    return sweeps, err, cells * sweeps / dt


def main() -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--mesh", default="2x4")
    ap.add_argument("--depth", type=int, default=6)
    ap.add_argument("--rows", type=int, default=24)
    ap.add_argument("--cols", type=int, default=40)
    ap.add_argument("--iters", type=int, default=3,
                    help="fixed-count iterations for the oracle/byte "
                         "gates")
    ap.add_argument("--n7", type=int, default=48,
                    help="7-point Poisson cube extent")
    ap.add_argument("--n25", type=int, default=16,
                    help="25-point Poisson cube extent (equal accuracy)")
    ap.add_argument("--target-err", type=float, default=0.013,
                    help="manufactured-solution error both stars must "
                         "reach (above the N=48 7-point "
                         "discretization floor)")
    ap.add_argument("--min-ratio", type=float, default=1.5,
                    help="required 7-point/25-point sweep ratio")
    ap.add_argument("--sweep-cap", type=int, default=2000)
    ap.add_argument("--oracle-tol", type=float, default=2e-5)
    ap.add_argument("--out", default="evidence/volume_smoke.json")
    ap.add_argument("--history",
                    default="evidence/volume_smoke_history.jsonl",
                    help="the smoke's OWN perf history, seeded fresh "
                         "each run; never the committed "
                         "evidence/perf_history.jsonl")
    args = ap.parse_args()

    import numpy as np

    from parallel_convolution_tpu.parallel.mesh import mesh_from_spec
    from parallel_convolution_tpu.utils.config import VOLUME_FORMS
    from parallel_convolution_tpu.volumes import driver, oracle3

    failures: list[str] = []
    mesh = mesh_from_spec(args.mesh)
    mesh1 = mesh_from_spec("1x1")
    D, H, W = args.depth, args.rows, args.cols
    rng = np.random.default_rng(0)
    # Bounded [0, 1): the Gray-Scott cubic term needs bounded fields.
    vol = rng.random((2, D, H, W), dtype=np.float32)

    # ---- 1: every form vs the oracle; twins + meshes byte-identical.
    outs: dict[str, np.ndarray] = {}
    for name in VOLUME_FORMS:
        try:
            got = driver.volume_iterate(vol, name, args.iters, mesh=mesh,
                                        boundary="zero")
        except Exception as e:  # noqa: BLE001 — smoke gate, report all
            failures.append(f"form {name} failed on {args.mesh}: {e}")
            continue
        outs[name] = got
        want = oracle3.run_oracle(vol, name, args.iters, "zero")
        diff = float(np.abs(got.astype(np.float64) - want).max())
        if diff > args.oracle_tol:
            failures.append(
                f"form {name} drifted from the numpy oracle: "
                f"max|diff| = {diff:.3g} > {args.oracle_tol}")
        solo = driver.volume_iterate(vol, name, args.iters, mesh=mesh1,
                                     boundary="zero")
        if solo.tobytes() != got.tobytes():
            failures.append(
                f"form {name} not byte-identical across 1x1 vs "
                f"{args.mesh} — the decomposition leaked")
    for base in ("fd7", "fd25"):
        twin = base + "_stack"
        if base in outs and twin in outs and (
                outs[base].tobytes() != outs[twin].tobytes()):
            failures.append(
                f"{twin} not byte-identical to {base} — the twins must "
                "route the same weighted terms in the same order")

    # ---- 2: the 25-point equal-accuracy convergence win (1x1 mesh).
    st7, u7 = _poisson_state(args.n7)
    st25, u25 = _poisson_state(args.n25)
    s7, e7, cps7 = _sweeps_to_err(
        driver, st7, u7, "fd7", mesh1, args.target_err, args.sweep_cap)
    s25, e25, cps25 = _sweeps_to_err(
        driver, st25, u25, "fd25", mesh1, args.target_err,
        args.sweep_cap)
    for tag, sw, err in (("fd7", s7, e7), ("fd25", s25, e25)):
        if err > args.target_err:
            failures.append(
                f"{tag} never reached err <= {args.target_err} "
                f"({err:.4g} after {sw} sweeps)")
    ratio = s7 / max(1, s25)
    if ratio <= args.min_ratio:
        failures.append(
            f"25-point convergence win too small: {s7}/{s25} = "
            f"{ratio:.2f}x <= {args.min_ratio}x")

    # ---- 3: serving round-trip, both wires, plus a physics stream.
    from parallel_convolution_tpu.serving import frames as frames_mod
    from parallel_convolution_tpu.serving.frontend import InProcessClient
    from parallel_convolution_tpu.serving.service import ConvolutionService

    svc = ConvolutionService(mesh, max_delay_s=0.002)
    try:
        client = InProcessClient(svc)
        body = {"rows": H, "cols": W, "depth": D, "mode": "volume",
                "filter": "fd7", "iters": args.iters, "boundary": "zero",
                "volume_b64": base64.b64encode(vol.tobytes()).decode()}
        status, resp = client.request(dict(body))
        if status != 200:
            failures.append(f"serving volume batch rejected: {resp}")
        else:
            out = np.frombuffer(base64.b64decode(resp["image_b64"]),
                                np.float32).reshape(vol.shape)
            want = oracle3.run_oracle(vol, "fd7", args.iters, "zero")
            diff = float(np.abs(out.astype(np.float64) - want).max())
            if diff > args.oracle_tol:
                failures.append(
                    f"served volume drifted from the oracle: "
                    f"{diff:.3g} > {args.oracle_tol}")
            raw = frames_mod.encode_envelope(
                {k: v for k, v in body.items() if k != "volume_b64"},
                {"volume": vol})
            fstatus, data = client.request_frames(raw)
            if fstatus != 200:
                failures.append(f"frames-wire volume rejected: {fstatus}")
            else:
                hdr, arrs = frames_mod.decode_envelope(data)
                if not hdr.get("ok") or (
                        np.asarray(arrs["image"]).tobytes()
                        != out.tobytes()):
                    failures.append(
                        "frames wire not byte-identical to the JSON arm")
        # The classic Gray-Scott start: U=1, V=0, a perturbed center
        # blob, a whisper of noise.  Raw amplitude-1 noise is OUTSIDE
        # the reaction's stable basin at dt=1 (the cubic term blows up
        # in a handful of steps), so this gate seeds properly.
        gs = np.zeros((2, D, H, W), np.float32)
        gs[0] = 1.0
        gs[0, :, H // 2 - 3:H // 2 + 3, W // 2 - 4:W // 2 + 4] = 0.5
        gs[1, :, H // 2 - 3:H // 2 + 3, W // 2 - 4:W // 2 + 4] = 0.25
        gs += 0.01 * rng.random(gs.shape, dtype=np.float32)
        cbody = {"rows": H, "cols": W, "depth": D, "mode": "volume",
                 "filter": "grayscott", "boundary": "periodic",
                 "volume_b64": base64.b64encode(gs.tobytes()).decode(),
                 "tol": 0.0, "max_iters": 8, "check_every": 4}
        cstatus, rows = client.converge(dict(cbody))
        rows = list(rows) if cstatus == 200 else []
        finals = [r for r in rows if r.get("kind") == "final"]
        if cstatus != 200 or len(finals) != 1:
            failures.append(
                f"grayscott converge stream failed: status={cstatus}, "
                f"{len(finals)} finals")
        else:
            fin = np.frombuffer(
                base64.b64decode(finals[0]["image_b64"]),
                np.float32).reshape(finals[0]["image_shape"])
            want = oracle3.run_oracle(gs, "grayscott", 8, "periodic")
            diff = float(np.abs(fin.astype(np.float64) - want).max())
            # NaN-safe: a non-finite diff must FAIL, not slide past >.
            if not diff <= 1e-4:
                failures.append(
                    f"served grayscott final drifted from the oracle: "
                    f"{diff:.3g} > 1e-4")
    finally:
        svc.close()

    # ---- 4: perf sentry fold — rank-3 rows in their own history lane.
    bench_rows = [
        {"workload": f"volume-smoke fd7 poisson {args.n7}^3",
         "plan_key": f"vol|fd7|{args.n7}x{args.n7}x{args.n7}"
                     "|periodic|grid=1x1",
         "backend": "xla", "mesh": "1x1", "solver": "jacobi", "rank": 3,
         "sweeps_to_err": s7, "gpixels_per_s": cps7 / 1e9},
        {"workload": f"volume-smoke fd25 poisson {args.n25}^3",
         "plan_key": f"vol|fd25|{args.n25}x{args.n25}x{args.n25}"
                     "|periodic|grid=1x1",
         "backend": "xla", "mesh": "1x1", "solver": "jacobi", "rank": 3,
         "sweeps_to_err": s25, "gpixels_per_s": cps25 / 1e9},
    ]
    out_path = Path(args.out)
    out_path.parent.mkdir(parents=True, exist_ok=True)
    rows_path = out_path.with_suffix(".rows.json")
    rows_path.write_text(json.dumps(bench_rows))
    hist = Path(args.history)
    hist.parent.mkdir(parents=True, exist_ok=True)
    hist.write_text("")   # the smoke's OWN history: truncate per run
    gate = [sys.executable, str(SCRIPTS / "perf_gate.py"),
            "--history", str(hist), "--row", str(rows_path), "--quiet"]
    rc_seed = subprocess.run([*gate, "--update"], check=False).returncode
    rc_pass = subprocess.run(gate, check=False).returncode
    if rc_seed != 0:
        failures.append(f"perf_gate seed run exited {rc_seed}")
    if rc_pass != 0:
        failures.append(f"perf_gate re-gate exited {rc_pass}")

    row = {
        "workload": f"volume-smoke {D}x{H}x{W} mesh={args.mesh} "
                    f"iters={args.iters}",
        "rank": 3,
        "forms_checked": list(VOLUME_FORMS),
        "sweeps_fd7": s7, "err_fd7": e7,
        "sweeps_fd25": s25, "err_fd25": e25,
        "sweep_ratio": round(ratio, 2),
        "min_ratio_gate": args.min_ratio,
        "target_err": args.target_err,
        "failures": len(failures),
        "failure_detail": failures[:10],
    }
    out_path.write_text(json.dumps(row, indent=2))
    print(json.dumps(row), flush=True)
    return 1 if failures else 0


if __name__ == "__main__":
    sys.exit(main())
