#!/usr/bin/env python
"""Sharded control-plane smoke: the ``run_t1.sh --shard-smoke`` leg
(round 21).

Boot THREE active routers (``serving.peers.ShardRouter``) over one
3-shard partition of the consistent-hash key space — each router owns
one shard's WAL lineage — and prove the fleet end to end:

1. **Shard routing** — a shard-aware client fetches the version-stamped
   map (``/v1/shardmap``'s in-process twin) and routes every request to
   its key's owner; every response is byte-identical to the NumPy
   oracle and stamped ``router: {shard, epoch, map_version}``; all 3
   shards serve.  A request sent straight to a NON-owner is rejected
   typed, retryable ``wrong_shard`` (421) naming the real owner.  After
   one anti-entropy round every router reports the SAME map version
   (the sum of per-shard epochs — derived, monotonic, convergent).
2. **Kill one active router mid-stream** — a converge stream is cut by
   an in-process SIGKILL (``hard_stop``: WAL flocks released, nothing
   fenced gracefully).  Surviving peers notice via anti-entropy misses
   and the deterministic successor performs the r19 fenced takeover of
   the orphaned shard lineage: epoch bump, per-shard fence sweep,
   durable jobs re-seeded.  Gates: the client's map refresh + retry
   RESUMES (never restarts) with a final byte-identical to the
   uninterrupted oracle run, exactly ONE final row per request_id
   across both lives, the zombie owner's writes are rejected typed
   ``stale_epoch``, and the OTHER shards serve throughout with zero
   non-rejected failures.
3. **Fleet-wide tenant quotas** — a greedy tenant's charges on one
   router replicate to every peer via seq-numbered debt deltas: the
   third request is shed typed ``tenant_quota`` by a router that never
   charged this tenant locally (its virgin bucket would have admitted
   it — the shed PROVES fleet consistency).
4. **Router scale curve** — fleets of 1, 2, 3 routers (each fronting
   its OWN pool of 2 fixed-service-rate replicas) drive the identical
   shard-spread workload; one ``lane: "router_scale"`` row per fleet
   size lands in ``evidence/scale_curve.jsonl`` and
   ``perf_gate.py --router-scale`` holds 3-router aggregate RPS >=
   2.4x the 1-router knee with p99 inside the band.

The summary row lands in ``--out`` (``evidence/shard_smoke.json``)
with ``"failures": 0`` iff every gate held; the scale-lane gate report
lands in ``evidence/shard_gate.json``.
"""

from __future__ import annotations

import argparse
import base64
import json
import subprocess
import sys
import threading
import time
from pathlib import Path

import _path  # noqa: F401  (repo root + JAX_PLATFORMS re-apply)

from parallel_convolution_tpu.utils.evidence_io import rewrite_shared_jsonl

SCRIPTS = Path(__file__).resolve().parent


def _pct(vals, q):
    if not vals:
        return None
    vs = sorted(vals)
    return vs[min(len(vs) - 1, int(round(q * (len(vs) - 1))))]


def main() -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--n", type=int, default=12,
                    help="batch requests in the routing phase")
    ap.add_argument("--rows", type=int, default=24)
    ap.add_argument("--cols", type=int, default=32)
    ap.add_argument("--mesh", default="1x2", help="grid per replica")
    ap.add_argument("--service-ms", type=float, default=60.0,
                    help="synthetic per-request device time of each "
                         "scale-lane replica (serialized per replica: "
                         "a fixed service rate, so aggregate RPS is "
                         "bounded by replicas, never the host CPU)")
    ap.add_argument("--scale-threads", type=int, default=9,
                    help="closed-loop client threads per scale step")
    ap.add_argument("--scale-reqs", type=int, default=18,
                    help="timed requests per client thread")
    ap.add_argument("--out", default="evidence/shard_smoke.json")
    ap.add_argument("--curve-out", default="evidence/scale_curve.jsonl")
    ap.add_argument("--gate-out", default="evidence/shard_gate.json")
    ap.add_argument("--history",
                    default="evidence/shard_smoke_history.jsonl",
                    help="the smoke's OWN perf history, seeded fresh "
                         "each run; never the committed "
                         "evidence/perf_history.jsonl")
    args = ap.parse_args()

    import tempfile

    import numpy as np

    from _chaos_common import oracle_converge_final
    from parallel_convolution_tpu.obs import events as obs_events
    from parallel_convolution_tpu.ops import filters, oracle
    from parallel_convolution_tpu.parallel.mesh import mesh_from_spec
    from parallel_convolution_tpu.serving.peers import (
        InProcessPeer, ShardClient, ShardRouter, shard_of,
    )
    from parallel_convolution_tpu.serving.pricing import WorkPricer
    from parallel_convolution_tpu.serving.router import (
        InProcessReplica, TenantQuotas, route_key,
    )
    from parallel_convolution_tpu.serving.service import ConvolutionService
    from parallel_convolution_tpu.utils import imageio

    obs_events.install_from_env()
    failures: list[str] = []
    t0 = time.time()
    img = imageio.generate_test_image(args.rows, args.cols, "grey",
                                      seed=7)
    b64 = base64.b64encode(np.ascontiguousarray(img).tobytes()).decode()
    names = ["rA", "rB", "rC"]
    assign = {"0": "rA", "1": "rB", "2": "rC"}

    def batch_body(iters: int, rid: str) -> dict:
        return {"image_b64": b64, "rows": args.rows, "cols": args.cols,
                "mode": "grey", "filter": "blur3", "iters": iters,
                "request_id": rid}

    def cv_body(rid: str) -> dict:
        return {"image_b64": b64, "rows": args.rows, "cols": args.cols,
                "mode": "grey", "filter": "jacobi3",
                "backend": "shifted", "quantize": False, "tol": 0.0,
                "max_iters": 40, "check_every": 10, "request_id": rid}

    # ---- shard discovery: iters is a route-key field, so scanning it
    # partitions configs across all 3 shards with no other knob moved.
    by_shard: dict[str, list[int]] = {"0": [], "1": [], "2": []}
    for it in range(1, 120):
        s = shard_of(route_key(batch_body(it, "probe")), 3)
        if len(by_shard[s]) < 4:
            by_shard[s].append(it)
        if all(len(v) >= 4 for v in by_shard.values()):
            break
    if not all(len(v) >= 3 for v in by_shard.values()):
        failures.append(f"config scan could not fill 3 shards: "
                        f"{ {s: len(v) for s, v in by_shard.items()} }")
        print(json.dumps({"failures": len(failures),
                          "failure_detail": failures}))
        return 1
    drill_iters = {s: v[0] for s, v in by_shard.items()}
    oracles = {it: oracle.run_serial_u8(img, filters.get_filter("blur3"),
                                        it)
               for v in by_shard.values() for it in v}

    def factory():
        return ConvolutionService(mesh_from_spec(args.mesh), max_batch=1,
                                  max_delay_s=0.001, max_queue=64)

    def mk_fleet(tmp, reps, quotas=None, pricer=None):
        routers = {}
        for nm in names:
            routers[nm] = ShardRouter(
                nm, reps, n_shards=3,
                owned=[s for s, o in assign.items() if o == nm],
                state_dir=tmp, assignments=assign,
                quotas=None if quotas is None else quotas[nm],
                pricer=pricer, start_sync=False, start_health=False,
                breaker_cooldown_s=0.2, wal_fsync=False)
        for nm in names:
            routers[nm].peers = [InProcessPeer(routers[o])
                                 for o in names if o != nm]
        return routers

    def checked(client, it: int, rid: str, attempts: int = 6):
        """One batch request through the shard client, with bounded
        backoff on typed retryable sheds; byte-checks the oracle."""
        delay = 0.01
        for _ in range(attempts):
            status, wire = client.request(batch_body(it, rid))
            if wire.get("ok"):
                got = np.frombuffer(base64.b64decode(wire["image_b64"]),
                                    np.uint8).reshape(img.shape)
                if not np.array_equal(got, oracles[it]):
                    failures.append(f"{rid}: oracle byte mismatch")
                return wire
            if not wire.get("retryable"):
                failures.append(f"{rid}: non-rejected failure "
                                f"{wire.get('rejected')!r}")
                return wire
            time.sleep(delay)
            delay = min(delay * 2, 0.2)
        failures.append(f"{rid}: still shed after {attempts} attempts")
        return {}

    finals_per_rid: dict[str, int] = {}

    def watch_finals(rows):
        out = []
        for r in rows:
            out.append(r)
            if r.get("kind") == "final":
                rid = r.get("request_id", "")
                finals_per_rid[rid] = finals_per_rid.get(rid, 0) + 1
        return out

    tmp = Path(tempfile.mkdtemp(prefix="pctpu-shard-smoke-"))

    # ---- phase 1: 3-shard boot, routing, wrong_shard, map version ---------
    drill_reps = [InProcessReplica(factory, name=f"w{i}")
                  for i in range(3)]
    drill_dir = tmp / "drill"
    drill_dir.mkdir()
    routers = mk_fleet(drill_dir, drill_reps)
    client = ShardClient(list(routers.values()))
    shards_served = set()
    for i in range(args.n):
        shard = str(i % 3)
        it = by_shard[shard][i // 3 % len(by_shard[shard])]
        wire = checked(client, it, f"sb{i}")
        stamp = wire.get("router", {})
        if wire.get("ok"):
            shards_served.add(stamp.get("shard"))
            if stamp.get("shard") != shard:
                failures.append(f"sb{i}: routed to shard "
                                f"{stamp.get('shard')!r}, key says "
                                f"{shard!r}")
            if not stamp.get("epoch") or stamp.get("map_version") is None:
                failures.append(f"sb{i}: router stamp incomplete: "
                                f"{stamp}")
    if shards_served != {"0", "1", "2"}:
        failures.append(f"not every shard served: {shards_served}")

    # Straight to a NON-owner: typed, retryable wrong_shard naming the
    # real owner (the client's refresh-and-retry contract).
    st, wire = routers["rA"].request(
        batch_body(drill_iters["1"], "misroute"))
    if (st != 421 or wire.get("rejected") != "wrong_shard"
            or wire.get("owner") != assign["1"]
            or not wire.get("retryable")):
        failures.append(f"misroute not a typed wrong_shard naming "
                        f"{assign['1']}: {st} {wire}")

    # One anti-entropy round → every router converges on one version.
    for _ in range(2):
        for r in routers.values():
            r.sync_now()
    versions = {nm: r.shardmap_wire()["version"]
                for nm, r in routers.items()}
    if len(set(versions.values())) != 1:
        failures.append(f"map versions did not converge: {versions}")
    if min(versions.values()) < 3:
        failures.append(f"converged version below the 3 live epochs: "
                        f"{versions}")

    # ---- phase 2: kill one active router mid-stream -----------------------
    body = cv_body("shard-kill")
    kill_shard = shard_of(route_key(body), 3)
    victim_name = assign[kill_shard]
    victim = routers[victim_name]
    survivors = [routers[nm] for nm in names if nm != victim_name]
    other_shards = [s for s in ("0", "1", "2") if s != kill_shard]
    oracle_final = oracle_converge_final(
        factory, dict(body, request_id="oracle"))

    st, rows = client.converge(dict(body))
    pre_rows = []
    if st != 200:
        failures.append(f"kill-drill converge admission failed: {st}")
    else:
        # Consume two rows, then the owner "process" dies — the stream
        # is ABANDONED un-closed, exactly what SIGKILL leaves.
        for row in rows:
            pre_rows.extend(watch_finals([row]))
            if len(pre_rows) >= 2:
                break
    if len(pre_rows) < 2 or pre_rows[-1].get("kind") == "final":
        failures.append(f"kill drill got no mid-stream rows: {pre_rows}")
    victim.hard_stop()

    # The surviving shards serve THROUGH the takeover window: traffic
    # interleaved with the anti-entropy rounds that detect the death.
    for i, other in enumerate(other_shards * 2):
        checked(client, drill_iters[other], f"during{i}")
        for r in survivors:
            r.sync_now()
    owners = [r for r in survivors if kill_shard in r._sub]
    if len(owners) != 1:
        failures.append(f"expected exactly one takeover owner of shard "
                        f"{kill_shard}: {[r.name for r in owners]}")
    successor = owners[0] if owners else survivors[0]
    if owners and successor.stats["takeovers"] != 1:
        failures.append(f"successor counted {successor.stats['takeovers']}"
                        " takeovers, expected 1")
    if owners and successor.sub(kill_shard).epoch <= victim.sub(
            kill_shard).epoch:
        failures.append("takeover did not bump the shard epoch: "
                        f"{successor.sub(kill_shard).epoch} vs zombie "
                        f"{victim.sub(kill_shard).epoch}")

    # Zombie: the dead owner's sub-router writes to the taken-over
    # shard → typed stale_epoch; per-shard, never per-process.
    _, zrows = victim.sub(kill_shard).converge(
        dict(body, request_id="zombie"))
    zfirst = next(iter(zrows), {})
    if zfirst.get("rejected") != "stale_epoch":
        failures.append(f"zombie converge not fenced typed stale_epoch: "
                        f"{zfirst.get('rejected')!r}")

    # The client refreshes the map and retries the SAME request_id: it
    # must RESUME from the WAL-recovered token on the successor.
    client.refresh()
    st, rows = client.converge(dict(body))
    got = watch_finals(rows) if st == 200 else []
    final = got[-1] if got else {}
    if final.get("kind") != "final":
        failures.append(f"takeover retry did not finish: status {st}")
    else:
        if got[0].get("iters", 0) <= pre_rows[-1].get("iters", 0):
            failures.append(
                f"retry restarted instead of resuming: first row at "
                f"iters {got[0].get('iters')} after pre-kill "
                f"{pre_rows[-1].get('iters')}")
        stamp = final.get("router", {})
        if stamp.get("resume_count", 0) < 1:
            failures.append(f"takeover final carries no resume stamp: "
                            f"{stamp}")
        if stamp.get("shard") != kill_shard:
            failures.append(f"takeover final mis-stamped shard: {stamp}")
        if final.get("image_b64") != oracle_final.get("image_b64"):
            failures.append("takeover final NOT byte-identical to the "
                            "uninterrupted oracle run")
    dup = {r: n for r, n in finals_per_rid.items() if n != 1}
    if dup:
        failures.append(f"exactly-once final rows violated: {dup}")
    takeover_epoch = (successor.sub(kill_shard).epoch
                      if owners else None)
    for r in routers.values():
        try:
            r.close(close_replicas=False)
        except Exception:  # noqa: BLE001 — victim is already dead
            pass
    for rep in drill_reps:
        rep.close()

    # ---- phase 3: fleet-wide tenant quotas --------------------------------
    # Fresh replicas (the drill fleet ratcheted per-shard fences into
    # its pool; a new fleet at epoch 1 must not inherit them).
    quota_reps = [InProcessReplica(factory, name=f"q{i}")
                  for i in range(2)]
    pricer = WorkPricer(min_units=1e-9)
    prices = {s: pricer.price(batch_body(drill_iters[s], "px"))
              for s in ("0", "1", "2")}
    # Budget: the greedy tenant can afford its first two requests
    # fleet-WIDE, never the third — yet the third lands on a router
    # that never charged it locally (virgin bucket = the full burst >
    # that request's price, so only replicated debt can shed it).
    burst = prices["0"] + prices["1"] + 0.5 * prices["2"]
    quotas = {nm: TenantQuotas(rate=1e-12, burst=burst,
                               clock=lambda: 0.0) for nm in names}
    quota_dir = tmp / "quota"
    quota_dir.mkdir()
    qrouters = mk_fleet(quota_dir, quota_reps, quotas=quotas,
                        pricer=pricer)
    qclient = ShardClient(list(qrouters.values()))
    for idx, shard in enumerate(("0", "1")):
        st, wire = qclient.request(dict(
            batch_body(drill_iters[shard], f"greedy{idx}"),
            tenant="greedy"))
        if not wire.get("ok"):
            failures.append(f"greedy{idx} (affordable) shed: {wire}")
        for r in qrouters.values():
            r.sync_now()
    owner3 = qrouters[assign["2"]]
    if owner3.quotas.bucket("greedy").level() >= prices["2"]:
        failures.append(
            "fleet quota not replicated: the third router's bucket "
            f"still holds {owner3.quotas.bucket('greedy').level():.4g} "
            f">= the request price {prices['2']:.4g}")
    st, wire = qclient.request(dict(
        batch_body(drill_iters["2"], "greedy2"), tenant="greedy"))
    if wire.get("rejected") != "tenant_quota":
        failures.append(f"over-budget request not shed fleet-wide: "
                        f"{st} {wire.get('rejected')!r}")
    absorbed = sum(r.stats["debt_deltas_absorbed"]
                   for r in qrouters.values())
    if not absorbed:
        failures.append("no debt deltas absorbed anywhere in the fleet")
    for r in qrouters.values():
        r.close(close_replicas=False)
    for rep in quota_reps:
        rep.close()

    # ---- phase 4: the router scale curve ----------------------------------
    class TimedReplica(InProcessReplica):
        """A replica with a FIXED service rate: one serialized
        synthetic device-time sleep per request.  On the 1-core CI
        host real compute cannot scale with router count; the lane's
        claim is about the CONTROL plane, so the data plane is pinned
        to `service_ms` per request per replica and aggregate RPS is
        bounded by how many replicas the fleet keeps busy."""

        def __init__(self, fac, name, service_s):
            self.service_s = float(service_s)
            self._svc_gate = threading.Lock()
            super().__init__(fac, name=name)

        def request(self, body, timeout=None, traceparent=None):
            with self._svc_gate:
                time.sleep(self.service_s)
            return super().request(body, timeout=timeout,
                                   traceparent=traceparent)

    scale_owned = {
        1: {"rA": ["0", "1", "2"]},
        2: {"rA": ["0", "1"], "rB": ["2"]},
        3: {"rA": ["0"], "rB": ["1"], "rC": ["2"]},
    }
    workload = [it for s in ("0", "1", "2") for it in by_shard[s][:3]]
    lane_rows = []
    for k, owned_map in scale_owned.items():
        fleet_reps: list[InProcessReplica] = []
        fleet = {}
        sdir = tmp / f"scale{k}"
        sdir.mkdir()
        for nm, owned in owned_map.items():
            # ONE replica per router: the pool capacity is exactly one
            # service rate, so aggregate RPS measures how many routers
            # the fleet keeps busy — ring skew inside a larger pool
            # would couple the curve to placement luck instead.
            pool = [TimedReplica(factory, f"s{k}{nm}0",
                                 args.service_ms / 1000.0)]
            fleet_reps.extend(pool)
            fleet[nm] = ShardRouter(
                nm, pool, n_shards=3, owned=owned, state_dir=sdir,
                assignments={s: n for n, ss in owned_map.items()
                             for s in ss},
                start_sync=False, start_health=False,
                breaker_cooldown_s=0.2, wal_fsync=False)
        for nm in fleet:
            fleet[nm].peers = [InProcessPeer(fleet[o])
                               for o in fleet if o != nm]
        # Warm every (config, replica) executable before the clock
        # starts — compiles are a boot cost, not a routing cost.
        warm = ShardClient(list(fleet.values()))
        for _ in range(2):
            for it in workload:
                warm.request(batch_body(it, "warm"))
        lat_ms: list[float] = []
        completed = [0]
        step_failures = [0]
        lock = threading.Lock()

        def worker(widx: int, fleet=fleet):
            cl = ShardClient(list(fleet.values()))
            for j in range(args.scale_reqs):
                it = workload[(widx + j) % len(workload)]
                t1 = time.perf_counter()
                ok = False
                for _ in range(4):
                    _, w = cl.request(batch_body(it, f"sc{widx}-{j}"))
                    if w.get("ok"):
                        ok = True
                        break
                    if not w.get("retryable"):
                        break
                dt = (time.perf_counter() - t1) * 1000.0
                with lock:
                    if ok:
                        completed[0] += 1
                        lat_ms.append(dt)
                    else:
                        step_failures[0] += 1

        threads = [threading.Thread(target=worker, args=(i,))
                   for i in range(args.scale_threads)]
        t1 = time.perf_counter()
        for th in threads:
            th.start()
        for th in threads:
            th.join()
        wall = time.perf_counter() - t1
        rps = completed[0] / wall if wall else 0.0
        lane_rows.append({
            "lane": "router_scale",
            "workload": f"shard-spread blur3 {args.rows}x{args.cols} "
                        f"{len(workload)} configs, "
                        f"{args.service_ms}ms/replica service",
            "routers": k, "replicas": k,
            "n": args.scale_threads * args.scale_reqs,
            "completed": completed[0],
            "rps": round(rps, 2),
            "p50_ms": round(_pct(lat_ms, 0.50) or 0.0, 2),
            "p99_ms": round(_pct(lat_ms, 0.99) or 0.0, 2),
            "service_ms": args.service_ms,
            "threads": args.scale_threads,
            "failures": step_failures[0],
        })
        if step_failures[0]:
            failures.append(f"scale step {k} routers: "
                            f"{step_failures[0]} non-rejected failures")
        for r in fleet.values():
            r.close(close_replicas=False)
        for rep in fleet_reps:
            rep.close()

    # ---- evidence: the shared curve file (we own ONLY our lane) -----------
    # evidence_io preserves every foreign line (static_check forbids any
    # other open-for-write of shared curve files).
    curve_path = Path(args.curve_out)
    rewrite_shared_jsonl(curve_path, lane_rows, lane="router_scale")

    # The scale-lane gate: 3-router RPS >= 2.4x the 1-router knee, p99
    # in band, zero lane failures — perf_gate owns the thresholds.
    rc_scale = subprocess.run(
        [sys.executable, str(SCRIPTS / "perf_gate.py"),
         "--router-scale", str(curve_path), "--out", args.gate_out,
         "--quiet"], check=False).returncode
    if rc_scale != 0:
        failures.append(f"perf_gate --router-scale exited {rc_scale}")

    wall = time.time() - t0
    rps_by_k = {r["routers"]: r["rps"] for r in lane_rows}
    row = {
        "workload": f"shard-smoke blur3+jacobi3 {args.rows}x"
                    f"{args.cols} 3 routers 3 shards kill-one "
                    "takeover zombie-fence fleet-quota scale-curve",
        "n": args.n,
        "shards_served": sorted(shards_served),
        "map_versions": versions,
        "kill_shard": kill_shard,
        "victim": victim_name,
        "successor": successor.name if owners else None,
        "takeover_epoch": takeover_epoch,
        "resume_count": (final.get("router", {}).get("resume_count")
                         if final else None),
        "finals_per_request": dict(finals_per_rid),
        "quota_burst": round(burst, 6),
        "quota_prices": {s: round(p, 6) for s, p in prices.items()},
        "debt_deltas_absorbed": absorbed,
        "scale_rps": rps_by_k,
        "scale_ratio_3v1": (round(rps_by_k[3] / rps_by_k[1], 3)
                            if rps_by_k.get(1) else None),
        "effective_backend": "shifted",
        "mesh": args.mesh,
        "wall_s": round(wall, 3),
        "gpixels_per_s": round(
            args.rows * args.cols * (args.n + 2 * 40) / wall / 1e9, 6)
        if wall else None,
        "failures": len(failures),
        "failure_detail": failures[:10],
    }

    # ---- perf sentry feed: seed the smoke's own history, then re-gate.
    out = Path(args.out)
    out.parent.mkdir(parents=True, exist_ok=True)
    out.write_text(json.dumps(row, indent=2))
    hist = Path(args.history)
    hist.parent.mkdir(parents=True, exist_ok=True)
    hist.write_text("")   # the smoke's OWN history: truncate per run
    gate = [sys.executable, str(SCRIPTS / "perf_gate.py"),
            "--history", str(hist), "--row", str(out), "--quiet"]
    rc_seed = subprocess.run([*gate, "--update"], check=False).returncode
    rc_pass = subprocess.run(gate, check=False).returncode
    if rc_seed != 0:
        failures.append(f"perf_gate seed run exited {rc_seed}")
    if rc_pass != 0:
        failures.append(f"perf_gate re-gate exited {rc_pass}")
    row["failures"] = len(failures)
    row["failure_detail"] = failures[:12]
    out.write_text(json.dumps(row, indent=2))
    print(json.dumps(row), flush=True)
    return 1 if failures else 0


if __name__ == "__main__":
    sys.exit(main())
