#!/usr/bin/env python
"""BASELINE config 5 both ways: unfused (the r03 status quo) vs the
round-4 fused convergence path (temporal fusion between checks).

VERDICT r03 item 6: "measure config 5 both ways".  Runs the same jacobi
run-to-convergence workload (scaled to the attached hardware like
baseline_configs.py) with (a) shifted/fuse=1 — what every prior round
measured — and (b) temporal fusion between convergence checks: the
Pallas 2D-tap kernel on TPU (jacobi3 has no rank-1 factorization, so
the per-kernel default tile applies — see DEFAULT_TILE), the XLA
shifted path off-TPU (mirroring baseline_configs.py's backend
fallback).  Emits one JSON row per variant plus a ratio row.
"""

from __future__ import annotations

import json
import sys
import time

import _path  # noqa: F401


def main() -> int:
    from parallel_convolution_tpu.utils.platform import (
        apply_platform_env, enable_compile_cache, on_tpu,
    )

    apply_platform_env()
    enable_compile_cache()

    import jax
    import numpy as np

    from parallel_convolution_tpu.ops.filters import get_filter
    from parallel_convolution_tpu.parallel import step
    from parallel_convolution_tpu.parallel.mesh import make_grid_mesh
    from parallel_convolution_tpu.utils import bench

    platform = "tpu" if on_tpu() else jax.default_backend()
    scale = 4 if platform == "tpu" else 16
    size = 32768 // scale
    mesh = make_grid_mesh(jax.devices())
    filt = get_filter("jacobi3")
    x = np.random.default_rng(0).random((1, size, size)).astype(np.float32)

    def run(tag, **kw):
        # warm/compile outside the timed span
        bench.fence(step.sharded_converge(x, filt, tol=1e-3, max_iters=200,
                                          check_every=10, mesh=mesh, **kw)[0])
        t0 = time.perf_counter()
        out, iters = step.sharded_converge(x, filt, tol=1e-3, max_iters=200,
                                           check_every=10, mesh=mesh, **kw)
        bench.fence(out)
        secs = time.perf_counter() - t0
        row = {"variant": tag, "workload": f"jacobi3 {size}x{size} tol=1e-3 "
               "check_every=10", "platform": platform,
               "iters_run": iters, "wall_s": round(secs, 3),
               "iters_per_s": round(iters / secs, 2), **kw}
        print(json.dumps(row), flush=True)
        return row, np.asarray(out)

    fused_backend = "pallas" if platform == "tpu" else "shifted"
    a, out_a = run("unfused (r03 status quo)", backend="shifted")
    b, out_b = run("fused (round 4)", backend=fused_backend, fuse=8)
    identical = bool(np.array_equal(out_a, out_b)) and (
        a["iters_run"] == b["iters_run"])
    print(json.dumps({
        "speedup_fused_vs_unfused": round(
            b["iters_per_s"] / a["iters_per_s"], 2),
        "bit_identical_results": identical,
    }))
    return 0 if identical else 1


if __name__ == "__main__":
    sys.exit(main())
