#!/bin/sh
# SUPERSEDED (resilience PR): express future chip sessions as a JSON legs
# file for scripts/run_supervised.py (completion predicates, classified
# retry, terminal HALT sentinel — all tested in tests/test_resilience.py).
# Kept as the round-5 operational record; do not extend.
#
# Round-5 third-window chip queue, re-armed by tunnel_watch.sh after the
# FOURTH tunnel outage (died ~11:45 UTC 2026-07-31, mid-way through the
# magic-round fuse re-sweep; rows landed so far are preserved in
# evidence/fuse_sweep_magic_r5.jsonl.partial).
#
# The fuse-56 fill-in from r5b is DROPPED deliberately: it wedged a
# 30-minute compile twice and fuse 40-48 is both the measured plateau
# and the practical compile frontier (BASELINE.md round-5b section).
#
# Legs, ordered by value:
#   1. bench.py sanity with the magic-round default -> the row the
#      driver's end-of-round bench should reproduce (~146 u8/fuse32)
#   2. profile_flagship --ab: fresh trace + workload-differencing
#      cross-check of the magic-round kernel (the 8-slot-floor claim)
#      and the interior-split re-ask under the new op mix
#   3. baseline_configs: refresh the five BASELINE config rows under
#      the magic-round default (recorded rows predate the change)
#   4. remaining fuse points (u8 32/40, bf16 32) for the re-sweep record
#   5. silicon soak: the randomized byte-compare campaign (CPU record:
#      1,120/1,120 across the recorded campaigns) run on the real chip —
#      random geometry/filter/storage configs Mosaic-compiled and
#      byte-compared vs the oracle, magic round active (n=20: remote
#      compiles dominate the wall)
set -x
cd "$(dirname "$0")/.."

timeout 60 python -c "import jax; print(jax.devices())" \
  || { echo "tunnel dead; aborting chip session" >&2; exit 1; }

LEG_TIMEOUT="${LEG_TIMEOUT:-1800}"

# Unlike r5b's append-on-failure (whose legs emitted rows exactly once),
# these legs recompute every row per attempt and the watcher refires
# every 4 minutes — appending would duplicate rows in the evidence
# ledger.  Keep whichever single attempt got furthest, and drop the
# stale .partial once the full leg lands.
# keep_best: after a failed/incomplete attempt, keep whichever single
# attempt got furthest as $out.partial (shared by run_to_keep and the
# summary-gated soak leg below so the heuristic cannot diverge).
keep_best() {
  out="$1"
  old=0
  [ -e "$out.partial" ] && old=$(wc -c < "$out.partial")
  if [ -s "$out.tmp" ] && [ "$(wc -c < "$out.tmp")" -gt "$old" ]; then
    mv "$out.tmp" "$out.partial"
    echo "$out incomplete; best attempt kept in $out.partial" >&2
  else
    rm -f "$out.tmp"
    echo "$out incomplete (stderr: /tmp/$(basename "$out").err)" >&2
  fi
}

run_to_keep() {
  out="$1"; shift
  if timeout "$LEG_TIMEOUT" "$@" \
       > "$out.tmp" 2> "/tmp/$(basename "$out").err"; then
    mv "$out.tmp" "$out" && rm -f "$out.partial" && echo "$out OK"
  else
    keep_best "$out"
  fi
}

# Leg 1 gates completion on a RESULT ROW being present, not on exit
# code: bench.py deliberately exits 1 on a magic-guard MISMATCH while
# still printing the full labeled row, and run_to_keep's rc-based gate
# would park that row in .partial and let the watcher refire the whole
# session every 4 minutes forever — an unbounded chip-burning retry on a
# condition retrying cannot heal.  A MISMATCH is TERMINAL: preserve the
# evidence, drop the halt sentinel tunnel_watch.sh checks, and stop.
if [ ! -e evidence/bench_r5c_sanity.json ]; then
  out=evidence/bench_r5c_sanity.json
  timeout "$LEG_TIMEOUT" python bench.py \
    > "$out.tmp" 2> "/tmp/$(basename "$out").err"
  if grep -q '"magic_round_guard": "MISMATCH"' "$out.tmp" 2>/dev/null; then
    mv "$out.tmp" "$out.MISMATCH"
    touch evidence/HALT_r5c
    echo "magic_round_guard=MISMATCH — terminal failure; row preserved" \
         "in $out.MISMATCH, HALT_r5c dropped for the watcher" >&2
    exit 2
  elif grep -q '"best_backend"' "$out.tmp" 2>/dev/null; then
    # "best_backend" only appears in a real result row; the
    # all-backends-failed error row also carries "metric" and must stay
    # retryable (transients heal), not land as final evidence.
    mv "$out.tmp" "$out" && rm -f "$out.partial" && echo "$out OK"
  else
    keep_best "$out"
  fi
fi

# --ab re-asks the interior-split question under the magic round: the
# rint removal changed the per-level op mix (8-slot floor), so the
# round-5 null (1.004x) deserves one re-measure under the new kernel.
[ -e evidence/profile_flagship_magic_r5.jsonl ] || \
  run_to_keep evidence/profile_flagship_magic_r5.jsonl \
    python scripts/profile_flagship.py --size 8192 --fuse 32 --reps 3 --ab

# Refresh the five BASELINE configs under the magic-round default — the
# recorded config rows (evidence/baseline_tpu.json) predate the kernel
# change.  Complete iff the LAST config's row exists (same
# completion-gate pattern as the soak: a timed-out attempt keeps its
# best partial, and the compile cache makes the retry resume warm
# instead of recompiling configs it already passed).
if [ ! -e evidence/baseline_configs_magic_r5.jsonl ]; then
  out=evidence/baseline_configs_magic_r5.jsonl
  timeout "$LEG_TIMEOUT" python scripts/baseline_configs.py \
    > "$out.tmp" 2> "/tmp/$(basename "$out").err"
  if grep -q '"config": "5:' "$out.tmp" 2>/dev/null; then
    mv "$out.tmp" "$out" && rm -f "$out.partial" && echo "$out OK"
  else
    keep_best "$out"
  fi
fi

[ -e evidence/fuse_sweep_magic_r5.jsonl ] || \
  run_to_keep evidence/fuse_sweep_magic_r5.jsonl python - <<'EOF'
from parallel_convolution_tpu.utils.platform import (
    apply_platform_env, enable_compile_cache)
apply_platform_env(); enable_compile_cache()
import json
from parallel_convolution_tpu.ops.filters import get_filter
from parallel_convolution_tpu.parallel.mesh import make_grid_mesh
from parallel_convolution_tpu.utils import bench
mesh = make_grid_mesh(); filt = get_filter("blur3")
for storage, fuse in (("u8", 32), ("u8", 40), ("bf16", 32)):
    row = bench.bench_iterate((8192, 8192), filt, 100, mesh=mesh,
                              backend="pallas_sep", storage=storage,
                              fuse=fuse, reps=3)
    row["round_mode"] = "magic"
    print(json.dumps(row), flush=True)
EOF

# Silicon soak, last (compile-heavy, lowest marginal value).  The soak's
# exit code counts per-config failures, and on silicon a failed config
# is itself a finding (its row records the error) — so the leg is
# complete iff the terminal summary row exists, regardless of rc.
# timeout kills python directly (no wrapper: an interposed shell would
# orphan the workload on timeout); a crash/timeout before the summary
# row keeps the best partial for the next watcher pass.
if [ ! -e evidence/soak_silicon_r5.jsonl ]; then
  out=evidence/soak_silicon_r5.jsonl
  timeout "$LEG_TIMEOUT" python scripts/soak.py --n 20 --seed 21 \
    > "$out.tmp" 2> "/tmp/$(basename "$out").err"
  if grep -q '"summary"' "$out.tmp" 2>/dev/null; then
    mv "$out.tmp" "$out" && rm -f "$out.partial" && echo "$out OK"
  else
    keep_best "$out"
  fi
fi
