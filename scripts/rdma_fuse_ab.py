#!/usr/bin/env python
"""A/B: the RDMA tier vs the ppermute path across temporal-fusion depths,
with an optional overlap on/off column.

VERDICT item 3: "give the RDMA tier a reason to exist, or retire it."
The tier was built for the latency-bound small-block regime, where the
per-iteration cost is dominated by exchange setup — exactly what
temporal fusion amortizes (fuse=T: one T*r-deep exchange, T in-kernel
levels) and what the interior-first overlapped pipeline hides
(``--overlap``: overlap on/off per fuse level — ROADMAP item 1's lever,
measurable in one command at the next tunnel window).  Every cell is
byte-checked against the serial oracle, and every overlap cell is
additionally byte-compared against its serialized twin — the
byte-equality gate the ``--overlap-smoke`` tier-1 leg enforces.

Rows are JSONL for the evidence ledger:

* one row per (path, fuse): the standard bench_iterate row plus
  ``oracle_bytes_ok`` (bit-exactness of a deterministic run),
  ``matches_serialized`` (overlap cells only), and an ``interpret``
  flag (off-TPU rows time the interpreter/XLA:CPU — a mechanism proof,
  NOT a perf claim; the decision row needs silicon);
* on a jax without the DMA-faithful TPU interpreter, multi-device RDMA
  cells are emitted as ``skipped: capability`` rows (they would fail on
  a missing lowering, proving nothing) and the overlap byte proof runs
  on a degenerate 1x1 mesh instead, where every RDMA construct
  statically elides and the overlap REGION-SPLIT compute is still the
  program under test;
* one summary row with per-fuse speedup ratios (rdma/ppermute and
  overlap/serialized), ``failures`` (byte mismatches + unexpected
  errors), and ``bytes_ok_all``.

Round 16 adds the ``--channels`` column: persistent+partitioned per-slab
completion vs the r12 phase-granular overlap vs serialized, crossed with
the {packed, strided} column transport — oracle byte-check every cell,
both kernels, both boundaries (multi-device cells are typed capability
skips on a jax without the faithful interpreter; the degenerate 1x1
proofs always run).

Usage:
  python scripts/rdma_fuse_ab.py                       # CPU mesh (8 virt.)
  python scripts/rdma_fuse_ab.py --overlap --out evidence/overlap_smoke.json
  python scripts/rdma_fuse_ab.py --channels            # round-16 A/B
  python scripts/rdma_fuse_ab.py --size 1024 --iters 64  # silicon regime
"""

from __future__ import annotations

import argparse
import json
import os
import sys

import _path  # noqa: F401  (repo root onto sys.path)


def _byte_check(backend, fuse, mesh, filt, iters, overlap=False,
                size=(64, 64)):
    """Bit-exactness of a deterministic small run vs the serial oracle;
    returns (ok, raw_bytes) so overlap cells can also compare twins."""
    import numpy as np

    from parallel_convolution_tpu.ops import oracle
    from parallel_convolution_tpu.parallel import step
    from parallel_convolution_tpu.utils import imageio

    img = imageio.generate_test_image(*size, "grey", seed=9)
    want = oracle.run_serial_u8(img, filt, iters)
    x = imageio.interleaved_to_planar(img).astype(np.float32)
    out = step.sharded_iterate(x, filt, iters, mesh=mesh, quantize=True,
                               backend=backend, fuse=fuse, overlap=overlap)
    got = imageio.planar_to_interleaved(np.asarray(out).astype(np.uint8))
    return bool(np.array_equal(got, want)), got


def _degenerate_overlap_proofs(filt, fuses):
    """Overlap-vs-serialized byte proofs on a 1x1 mesh — runnable on ANY
    jax (extent-1 axes statically elide every RDMA construct), pinning
    the interior-first REGION-SPLIT compute that is the overlap path's
    only new math when no DMA exists.  Covers both boundaries and both
    kernels (monolithic via the driver; tiled via a forced launch)."""
    import jax
    import numpy as np
    from jax.sharding import PartitionSpec as P

    from parallel_convolution_tpu.ops import oracle, pallas_rdma
    from parallel_convolution_tpu.parallel import step
    from parallel_convolution_tpu.parallel.mesh import AXES, make_grid_mesh
    from parallel_convolution_tpu.utils import imageio, jax_compat

    mesh = make_grid_mesh(jax.devices()[:1], (1, 1))
    rows = []
    for boundary, dims in (("zero", (37, 53)), ("periodic", (24, 36))):
        for fuse in fuses:
            iters = 2 * fuse
            img = imageio.generate_test_image(*dims, "grey", seed=31)
            want = oracle.run_serial_u8(img, filt, iters, boundary=boundary)
            x = imageio.interleaved_to_planar(img).astype(np.float32)
            got = {}
            for ov in (False, True):
                out = step.sharded_iterate(
                    x, filt, iters, mesh=mesh, quantize=True,
                    backend="pallas_rdma", boundary=boundary, fuse=fuse,
                    overlap=ov)
                got[ov] = imageio.planar_to_interleaved(
                    np.asarray(out).astype(np.uint8))
            rows.append({
                "ab": "overlap_degenerate", "boundary": boundary,
                "fuse": fuse, "kernel": "monolithic",
                "oracle_bytes_ok": bool(np.array_equal(got[True], want)),
                "matches_serialized": bool(
                    np.array_equal(got[True], got[False])),
            })
    # Tiled kernel, forced: multi-window grid + the overlap flag.
    img = imageio.generate_test_image(96, 384, "grey", seed=34)
    x = imageio.interleaved_to_planar(img).astype(np.float32)
    want = oracle.run_serial_u8(img, filt, 4)
    got = {}
    for ov in (False, True):
        def body(v, ov=ov):
            import jax.lax as lax

            def one(_, cur):
                return pallas_rdma.fused_rdma_step(
                    cur, filt, (1, 1), "zero", quantize=True, tiled=True,
                    tile=(32, 128), fuse=2, valid_hw=img.shape[:2],
                    overlap=ov)
            return lax.fori_loop(0, 2, one, v)
        out = jax.jit(jax_compat.shard_map(
            body, mesh=mesh, in_specs=P(None, *AXES),
            out_specs=P(None, *AXES), check_vma=False))(x)
        got[ov] = np.asarray(out)[0].astype(np.uint8)
    rows.append({
        "ab": "overlap_degenerate", "boundary": "zero", "fuse": 2,
        "kernel": "tiled",
        "oracle_bytes_ok": bool(np.array_equal(got[True], want)),
        "matches_serialized": bool(np.array_equal(got[True], got[False])),
    })
    return rows


def _kernel_tiers(filt, fuse, mesh_shape, boundary, dims, *, col_mode,
                  tiled=None, tile=None, seed=71):
    """Run the three channel tiers — serialized, r12 phase-granular
    overlap, persistent+partitioned — for one (fuse, col_mode) cell,
    driving ``fused_rdma_step`` directly (the ``partitioned`` knob is a
    kernel-layer A/B reference, deliberately not a dispatch knob).
    Returns ``(oracle_u8, {tier: bytes})``."""
    import jax
    import numpy as np
    from jax.sharding import PartitionSpec as P

    from parallel_convolution_tpu.ops import oracle, pallas_rdma
    from parallel_convolution_tpu.parallel.mesh import AXES, make_grid_mesh
    from parallel_convolution_tpu.utils import imageio, jax_compat

    mesh = make_grid_mesh(
        jax.devices()[: mesh_shape[0] * mesh_shape[1]], mesh_shape)
    img = imageio.generate_test_image(*dims, "grey", seed=seed)
    iters = 2 * fuse
    want = oracle.run_serial_u8(img, filt, iters, boundary=boundary)
    x = imageio.interleaved_to_planar(img).astype(np.float32)
    valid_hw = None if boundary == "periodic" else dims
    got = {}
    for tier, (ov, part) in (("serialized", (False, True)),
                             ("overlap", (True, False)),
                             ("partitioned", (True, True))):
        def body(v, ov=ov, part=part):
            import jax.lax as lax

            def one(_, cur):
                return pallas_rdma.fused_rdma_step(
                    cur, filt, mesh_shape, boundary, quantize=True,
                    tiled=tiled, tile=tile, fuse=fuse, valid_hw=valid_hw,
                    overlap=ov, col_mode=col_mode, partitioned=part)
            return lax.fori_loop(0, 2, one, v)
        out = jax.jit(jax_compat.shard_map(
            body, mesh=mesh, in_specs=P(None, *AXES),
            out_specs=P(None, *AXES), check_vma=False))(x)
        got[tier] = imageio.planar_to_interleaved(
            np.asarray(out).astype(np.uint8))
    return want, got


def channels_proofs(filt, fuses, mesh_shape, rdma_capable):
    """The --channels column: byte-identity of
    {serialized, r12 overlap, persistent+partitioned} x {packed, strided}
    per fuse, both boundaries, both kernels — oracle byte-check every
    cell.  Multi-device cells ride the faithful interpreter (typed
    capability skips without it); the degenerate 1x1 cells ALWAYS run —
    there the channel machinery must statically elide to the serialized
    program verbatim, which the test suite additionally pins at the
    lowered-program level."""
    import numpy as np

    rows = []
    grids = [(1, 1)]
    if rdma_capable and mesh_shape != (1, 1):
        grids.append(mesh_shape)
    elif mesh_shape != (1, 1):
        rows.append({"ab": "channels", "grid": "x".join(
            str(g) for g in mesh_shape), "skipped": "capability",
            "detail": "no DMA-faithful TPU interpreter in this jax; "
                      "multi-device channel cells need current jax or "
                      "silicon — degenerate 1x1 proofs below still run"})
    for grid in grids:
        dims_of = {"zero": (grid[0] * 16 + 5, grid[1] * 16 + 3),
                   "periodic": (grid[0] * 16, grid[1] * 16)}
        for boundary in ("zero", "periodic"):
            for fuse in fuses:
                for cm in ("packed", "strided"):
                    try:
                        want, got = _kernel_tiers(
                            filt, fuse, grid, boundary, dims_of[boundary],
                            col_mode=cm)
                        row = {
                            "ab": "channels", "kernel": "monolithic",
                            "grid": f"{grid[0]}x{grid[1]}",
                            "boundary": boundary, "fuse": fuse,
                            "col_mode": cm,
                            "oracle_bytes_ok": bool(np.array_equal(
                                got["partitioned"], want)),
                            "matches_serialized": bool(
                                np.array_equal(got["partitioned"],
                                               got["serialized"])
                                and np.array_equal(got["overlap"],
                                                   got["serialized"])),
                        }
                    except Exception as e:  # noqa: BLE001 — cell is data
                        row = {"ab": "channels", "kernel": "monolithic",
                               "grid": f"{grid[0]}x{grid[1]}",
                               "boundary": boundary, "fuse": fuse,
                               "col_mode": cm, "error": repr(e)[:200]}
                    rows.append(row)
        # Tiled kernel: one forced cell per col_mode (multi-window grid;
        # dims SCALE with the grid so every per-device block clears the
        # tiled kernel's (sublane, 128) minimum).
        for cm in ("packed", "strided"):
            try:
                want, got = _kernel_tiers(
                    filt, 2, grid, "zero", (grid[0] * 96, grid[1] * 384),
                    col_mode=cm, tiled=True, tile=(32, 128))
                row = {
                    "ab": "channels", "kernel": "tiled",
                    "grid": f"{grid[0]}x{grid[1]}", "boundary": "zero",
                    "fuse": 2, "col_mode": cm,
                    "oracle_bytes_ok": bool(np.array_equal(
                        got["partitioned"], want)),
                    "matches_serialized": bool(
                        np.array_equal(got["partitioned"],
                                       got["serialized"])
                        and np.array_equal(got["overlap"],
                                           got["serialized"])),
                }
            except Exception as e:  # noqa: BLE001
                row = {"ab": "channels", "kernel": "tiled",
                       "grid": f"{grid[0]}x{grid[1]}", "boundary": "zero",
                       "fuse": 2, "col_mode": cm, "error": repr(e)[:200]}
            rows.append(row)
    return rows


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--size", type=int, default=256,
                    help="square image size; small by design — the "
                         "latency-bound regime the RDMA tier targets")
    ap.add_argument("--iters", type=int, default=16)
    ap.add_argument("--reps", type=int, default=2)
    ap.add_argument("--fuse", default="1,2,4,8",
                    help="comma-separated fusion depths")
    ap.add_argument("--mesh", default=None, help="RxC grid (default: all)")
    ap.add_argument("--platform", default=None,
                    help="force jax platform (e.g. cpu)")
    ap.add_argument("--overlap", action="store_true",
                    help="add the overlap on/off A/B column (per fuse: "
                         "serialized RDMA vs interior-first overlapped "
                         "RDMA, byte-checked cell by cell)")
    ap.add_argument("--channels", action="store_true",
                    help="add the channels column (round 16): "
                         "persistent+partitioned per-slab completion vs "
                         "the r12 phase-granular overlap vs serialized, "
                         "x {packed, strided} column transport — oracle "
                         "byte-check every cell, both kernels, both "
                         "boundaries; multi-device cells are typed "
                         "capability skips without the faithful "
                         "interpreter, the degenerate 1x1 proofs always "
                         "run")
    ap.add_argument("--out", default=None,
                    help="also write the summary row to this JSON file "
                         "(the --overlap-smoke leg's done_file)")
    args = ap.parse_args()

    if args.overlap or args.channels:
        # The overlap/channels columns must compile the overlapped
        # PROGRAM even on a CPU mesh (where dispatch force-serializes by
        # default): this harness exists to prove bytes, the env is the
        # documented hatch.
        os.environ.setdefault("PCTPU_OVERLAP_INTERPRET", "1")

    from parallel_convolution_tpu.utils.platform import (
        apply_platform_env, enable_compile_cache, force_platform, on_tpu,
    )

    if args.platform:
        force_platform(args.platform, warn=True)
    else:
        apply_platform_env()
    enable_compile_cache()

    import jax

    from parallel_convolution_tpu.ops.filters import get_filter
    from parallel_convolution_tpu.parallel.mesh import make_grid_mesh
    from parallel_convolution_tpu.utils import bench, jax_compat

    if args.mesh:
        r, c = (int(v) for v in args.mesh.lower().split("x"))
        mesh = make_grid_mesh(jax.devices()[: r * c], (r, c))
    else:
        mesh = make_grid_mesh()
    filt = get_filter("blur3")
    fuses = [int(v) for v in args.fuse.split(",")]
    interp = not on_tpu()
    # Multi-device RDMA needs the DMA-faithful interpreter off-silicon;
    # without it those cells FAIL on a missing lowering — emit typed
    # capability skips instead of error rows that prove nothing.
    rdma_capable = (mesh.size == 1 or not interp
                    or jax_compat.HAS_TPU_INTERPRET)

    # "ppermute" = the standard tier at the same workload: halo.py
    # collective-permute exchange + the Pallas stencil kernel (fused
    # T-level variant for fuse>1) — the path the RDMA kernel must beat.
    paths = [("rdma", "pallas_rdma", False), ("ppermute", "pallas", None)]
    if args.overlap:
        paths.insert(1, ("rdma+overlap", "pallas_rdma", True))

    rows, serial_bytes = [], {}
    for fuse in fuses:
        for label, backend, ov in paths:
            if backend == "pallas_rdma" and not rdma_capable:
                row = {"backend": backend, "fuse": fuse, "path": label,
                       "skipped": "capability",
                       "detail": "no DMA-faithful TPU interpreter in "
                                 "this jax; multi-device RDMA cells "
                                 "need current jax or silicon"}
                row["ab"] = "rdma_fuse"
                rows.append(row)
                print(json.dumps(row), flush=True)
                continue
            try:
                row = bench.bench_iterate(
                    (args.size, args.size), filt, args.iters, mesh=mesh,
                    backend=backend, fuse=fuse, reps=args.reps,
                    overlap=ov)
                ok, raw = _byte_check(
                    backend, fuse, mesh, filt, iters=2 * fuse,
                    overlap=bool(ov))
                row["oracle_bytes_ok"] = ok
                if ov:
                    twin = serial_bytes.get(fuse)
                    if twin is not None:
                        import numpy as np

                        row["matches_serialized"] = bool(
                            np.array_equal(raw, twin))
                elif backend == "pallas_rdma":
                    serial_bytes[fuse] = raw
            except Exception as e:
                row = {"backend": backend, "fuse": fuse,
                       "error": repr(e)[:200]}
            row["ab"] = "rdma_fuse"
            row["path"] = label
            row["interpret"] = interp
            rows.append(row)
            print(json.dumps(row), flush=True)

    # Degenerate-grid overlap proof: ALWAYS runnable (any jax), and the
    # only overlap byte coverage when the full protocol is capability-
    # skipped above.
    proofs = []
    if args.overlap:
        proofs = _degenerate_overlap_proofs(filt, [f for f in fuses
                                                   if f <= 4] or [1])
        for p in proofs:
            rows.append(p)
            print(json.dumps(p), flush=True)
    if args.channels:
        mesh_shape = tuple(int(v) for v in mesh.devices.shape)
        for p in channels_proofs(filt, [f for f in fuses if f <= 4] or [1],
                                 mesh_shape, rdma_capable):
            rows.append(p)
            print(json.dumps(p), flush=True)

    by_fuse = {}
    for r_ in rows:
        if "error" in r_ or "skipped" in r_ or r_["ab"] != "rdma_fuse":
            continue
        by_fuse.setdefault(r_["fuse"], {})[r_["path"]] = r_

    completed = [r_ for r_ in rows
                 if "error" not in r_ and "skipped" not in r_]
    mismatches = [r_ for r_ in completed
                  if not r_.get("oracle_bytes_ok", True)
                  or not r_.get("matches_serialized", True)]
    errors = [r_ for r_ in rows if "error" in r_]
    skipped = [r_ for r_ in rows if "skipped" in r_]
    overlap_proofs = [r_ for r_ in completed
                      if r_.get("ab") == "overlap_degenerate"
                      or r_.get("path") == "rdma+overlap"]
    channel_proofs = [r_ for r_ in completed if r_.get("ab") == "channels"]
    summary = {
        "probe": "rdma_fuse_ab",
        "workload": f"blur3 {args.size}x{args.size} {args.iters} iters, "
                    f"mesh {'x'.join(str(s) for s in mesh.shape.values())}",
        "interpret": interp,
        "overlap_ab": bool(args.overlap),
        # interpret rows prove bytes, never speed — only silicon rows may
        # feed the win/retire decision
        "perf_claim": not interp,
        # False when every configuration errored: an A/B with zero
        # completed rows has proven nothing and must not read as a pass.
        "bytes_ok_all": bool(completed) and not mismatches,
        # The --overlap-smoke gate: byte mismatches + unexpected errors
        # (typed capability skips are not failures — they name the jax
        # feature gap; the degenerate proofs above still ran).
        "failures": len(mismatches) + len(errors),
        "overlap_proofs": len(overlap_proofs),
        "channels_ab": bool(args.channels),
        "channel_proofs": len(channel_proofs),
    }
    for fuse, d in sorted(by_fuse.items()):
        if "rdma" in d and "ppermute" in d and d["rdma"].get("wall_s"):
            summary[f"rdma_vs_ppermute[fuse{fuse}]"] = round(
                d["ppermute"]["wall_s"] / d["rdma"]["wall_s"], 4)
        if ("rdma" in d and "rdma+overlap" in d
                and d["rdma+overlap"].get("wall_s")):
            summary[f"overlap_vs_serialized[fuse{fuse}]"] = round(
                d["rdma"]["wall_s"] / d["rdma+overlap"]["wall_s"], 4)
    if errors:
        summary["error_rows"] = len(errors)
    if skipped:
        summary["skipped_capability"] = len(skipped)
    print(json.dumps(summary), flush=True)
    if args.out:
        os.makedirs(os.path.dirname(os.path.abspath(args.out)),
                    exist_ok=True)
        with open(args.out, "w", encoding="utf-8") as f:
            json.dump(summary, f, indent=1, sort_keys=True)
            f.write("\n")
    ok = summary["bytes_ok_all"] and summary["failures"] == 0
    if args.overlap:
        ok = ok and summary["overlap_proofs"] > 0
    if args.channels:
        ok = ok and summary["channel_proofs"] > 0
    return 0 if ok else 1


if __name__ == "__main__":
    sys.exit(main())
