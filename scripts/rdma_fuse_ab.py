#!/usr/bin/env python
"""A/B: the RDMA tier vs the ppermute path across temporal-fusion depths.

VERDICT item 3: "give the RDMA tier a reason to exist, or retire it."
The tier was built for the latency-bound small-block regime, where the
per-iteration cost is dominated by exchange setup — exactly what
temporal fusion amortizes (fuse=T: one T*r-deep exchange, T in-kernel
levels).  This harness prices both paths on the SAME small-block
workload across fuse ∈ {1,2,4,8} and byte-checks every configuration
against the serial oracle, emitting JSONL rows for the evidence ledger:

* one row per (path, fuse): the standard bench_iterate row plus
  ``oracle_bytes_ok`` (bit-exactness of a deterministic run) and an
  ``interpret`` flag (off-TPU rows time the interpreter/XLA:CPU — a
  mechanism proof, NOT a perf claim; the decision row needs silicon);
* one summary row with the per-fuse rdma/ppermute speedup ratios and
  the win/retire reading DESIGN.md asks for.

Runnable today on the CPU mesh (interpret mode); re-run unchanged on
silicon at the next tunnel window for the decision numbers.

Usage:
  python scripts/rdma_fuse_ab.py                       # CPU mesh (8 virt.)
  python scripts/rdma_fuse_ab.py --size 1024 --iters 64  # silicon regime
"""

from __future__ import annotations

import argparse
import json
import sys

import _path  # noqa: F401  (repo root onto sys.path)


def _byte_check(backend, fuse, mesh, filt, iters):
    """Bit-exactness of a deterministic small run vs the serial oracle."""
    import numpy as np

    from parallel_convolution_tpu.ops import oracle
    from parallel_convolution_tpu.parallel import step
    from parallel_convolution_tpu.utils import imageio

    img = imageio.generate_test_image(64, 64, "grey", seed=9)
    want = oracle.run_serial_u8(img, filt, iters)
    x = imageio.interleaved_to_planar(img).astype(np.float32)
    out = step.sharded_iterate(x, filt, iters, mesh=mesh, quantize=True,
                               backend=backend, fuse=fuse)
    got = imageio.planar_to_interleaved(np.asarray(out).astype(np.uint8))
    return bool(np.array_equal(got, want))


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--size", type=int, default=256,
                    help="square image size; small by design — the "
                         "latency-bound regime the RDMA tier targets")
    ap.add_argument("--iters", type=int, default=16)
    ap.add_argument("--reps", type=int, default=2)
    ap.add_argument("--fuse", default="1,2,4,8",
                    help="comma-separated fusion depths")
    ap.add_argument("--mesh", default=None, help="RxC grid (default: all)")
    ap.add_argument("--platform", default=None,
                    help="force jax platform (e.g. cpu)")
    args = ap.parse_args()

    from parallel_convolution_tpu.utils.platform import (
        apply_platform_env, enable_compile_cache, force_platform, on_tpu,
    )

    if args.platform:
        force_platform(args.platform, warn=True)
    else:
        apply_platform_env()
    enable_compile_cache()

    import jax

    from parallel_convolution_tpu.ops.filters import get_filter
    from parallel_convolution_tpu.parallel.mesh import make_grid_mesh
    from parallel_convolution_tpu.utils import bench

    if args.mesh:
        r, c = (int(v) for v in args.mesh.lower().split("x"))
        mesh = make_grid_mesh(jax.devices()[: r * c], (r, c))
    else:
        mesh = make_grid_mesh()
    filt = get_filter("blur3")
    fuses = [int(v) for v in args.fuse.split(",")]
    interp = not on_tpu()

    # "ppermute" = the standard tier at the same workload: halo.py
    # collective-permute exchange + the Pallas stencil kernel (fused
    # T-level variant for fuse>1) — the path the RDMA kernel must beat.
    rows = []
    for fuse in fuses:
        for label, backend in (("rdma", "pallas_rdma"), ("ppermute", "pallas")):
            try:
                row = bench.bench_iterate(
                    (args.size, args.size), filt, args.iters, mesh=mesh,
                    backend=backend, fuse=fuse, reps=args.reps)
                row["oracle_bytes_ok"] = _byte_check(
                    backend, fuse, mesh, filt, iters=2 * fuse)
            except Exception as e:
                row = {"backend": backend, "fuse": fuse,
                       "error": repr(e)[:200]}
            row["ab"] = "rdma_fuse"
            row["path"] = label
            row["interpret"] = interp
            rows.append(row)
            print(json.dumps(row), flush=True)

    by_fuse = {}
    for r_ in rows:
        if "error" in r_:
            continue
        by_fuse.setdefault(r_["fuse"], {})[r_["path"]] = r_
    summary = {
        "probe": "rdma_fuse_ab",
        "workload": f"blur3 {args.size}x{args.size} {args.iters} iters, "
                    f"mesh {'x'.join(str(s) for s in mesh.shape.values())}",
        "interpret": interp,
        # interpret rows prove bytes, never speed — only silicon rows may
        # feed the win/retire decision
        "perf_claim": not interp,
        # False when every configuration errored: an A/B with zero
        # completed rows has proven nothing and must not read as a pass.
        "bytes_ok_all": bool(by_fuse) and all(
            r_.get("oracle_bytes_ok", False)
            for r_ in rows if "error" not in r_),
    }
    for fuse, d in sorted(by_fuse.items()):
        if "rdma" in d and "ppermute" in d and d["rdma"]["wall_s"]:
            summary[f"rdma_vs_ppermute[fuse{fuse}]"] = round(
                d["ppermute"]["wall_s"] / d["rdma"]["wall_s"], 4)
    errors = [r_ for r_ in rows if "error" in r_]
    if errors:
        summary["error_rows"] = len(errors)
    print(json.dumps(summary), flush=True)
    return 0 if summary["bytes_ok_all"] else 1


if __name__ == "__main__":
    sys.exit(main())
