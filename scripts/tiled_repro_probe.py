#!/usr/bin/env python
"""Isolate which construct of the tiled RDMA kernel kills the compile helper.

`scripts/rdma_on_silicon.py` records that `_rdma_tiled_kernel` is
rejected on silicon with an HTTP 500 (`tpu_compile_helper` subprocess
crash, no Mosaic diagnostic).  The monolithic kernel — which shares the
barrier, remote copies, semaphores, and ANY→VMEM input DMA — compiles
fine, so the suspects are the constructs ONLY the tiled variant uses.

The probes form an additive ladder: each adds EXACTLY ONE construct on
top of the previous probe, so the first failing row's own delta names
the offender:

  a0_any_operands_only   Δ: unblocked (memory_space=ANY) in/out specs —
                            compute goes in→VMEM→out, no HBM scratch.
                            (The round-5 run showed rung a's claim that
                            the monolithic kernel proves this path was
                            wrong: the monolithic call uses DEFAULT
                            blocked VMEM specs, so without this rung the
                            a-failure is ambiguous between ANY operands
                            and the HBM scratch.)
  a_unused_hbm_scratch   Δ: an HBM scratch buffer is allocated (never
                            touched; compute goes in→VMEM→out)
  b_hbm_roundtrip        Δ: DMA into and out of the HBM scratch
  c_hbm_internal_copy    Δ: HBM→HBM copy between two scratch regions
  d_windowed_from_hbm    Δ: gridded pl.ds windowed DMA out of the
                            scratch (refill copy runs EVERY program —
                            wasteful but construct-free)
  e_when_step0           Δ: the refill copy moves under the one-shot
                            @pl.when(step == 0) guard
  f_collective_params    Δ: CompilerParams(collective_id,
                            has_side_effects) as the real kernel passes,
                            plus the step-0-guarded degenerate neighbor
                            barrier (get_barrier_semaphore + zero-count
                            wait) that makes collective_id legal — two
                            constructs in one rung, so a failure here
                            names the pair, not CompilerParams alone

Emits one JSON row per probe (failures are IN the record); exit 0 iff
every probe produced a row.  Off-TPU it exits 1 — the interpreter
accepts every rung, there is nothing to learn from it here.
"""

from __future__ import annotations

import json
import sys

import _path  # noqa: F401


def main() -> int:
    from parallel_convolution_tpu.utils.platform import (
        apply_platform_env, enable_compile_cache, on_tpu,
    )

    apply_platform_env()
    enable_compile_cache()

    import jax
    import jax.numpy as jnp
    import numpy as np
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    if not on_tpu():
        print(json.dumps({"probe": "tiled_repro", "skipped": "no TPU"}))
        return 1

    H, W = 256, 512
    TH, TW = 64, 128
    x = np.arange(H * W, dtype=np.float32).reshape(H, W) % 251.0

    def run(name, fn, want):
        try:
            got = np.asarray(jax.jit(fn)(jnp.asarray(x)))
            row = {"probe": name, "mosaic_compiled": True,
                   "correct": bool(np.array_equal(got, want))}
        except Exception as e:
            msg = repr(e)
            if len(msg) > 3000:
                msg = msg[:1500] + " ...[elided]... " + msg[-1500:]
            row = {"probe": name, "mosaic_compiled": False, "error": msg}
        print(json.dumps(row), flush=True)

    ANY_IO = dict(
        in_specs=[pl.BlockSpec(memory_space=pl.ANY)],
        out_specs=pl.BlockSpec(memory_space=pl.ANY),
        out_shape=jax.ShapeDtypeStruct((H, W), jnp.float32),
    )

    # a0. ANY-space operands alone: in → VMEM → out, no HBM scratch.
    def k_a0(in_ref, out_ref, vmem, sem):
        cp = pltpu.make_async_copy(in_ref, vmem, sem)
        cp.start()
        cp.wait()
        cp2 = pltpu.make_async_copy(vmem, out_ref, sem)
        cp2.start()
        cp2.wait()

    run("a0_any_operands_only", lambda v: pl.pallas_call(
        k_a0, **ANY_IO,
        scratch_shapes=[pltpu.VMEM((H, W), jnp.float32),
                        pltpu.SemaphoreType.DMA(())],
    )(v), x)

    # a. + HBM scratch allocated but never touched; data still moves
    #    via VMEM exactly as in a0.
    def k_a(in_ref, out_ref, hbm, vmem, sem):
        cp = pltpu.make_async_copy(in_ref, vmem, sem)
        cp.start()
        cp.wait()
        cp2 = pltpu.make_async_copy(vmem, out_ref, sem)
        cp2.start()
        cp2.wait()

    run("a_unused_hbm_scratch", lambda v: pl.pallas_call(
        k_a, **ANY_IO,
        scratch_shapes=[pltpu.MemorySpace.HBM((H, W), jnp.float32),
                        pltpu.VMEM((H, W), jnp.float32),
                        pltpu.SemaphoreType.DMA(())],
    )(v), x)

    # b. + DMA into and out of the HBM scratch.
    def k_b(in_ref, out_ref, hbm, sem):
        cp = pltpu.make_async_copy(in_ref, hbm, sem)
        cp.start()
        cp.wait()
        cp2 = pltpu.make_async_copy(hbm, out_ref, sem)
        cp2.start()
        cp2.wait()

    run("b_hbm_roundtrip", lambda v: pl.pallas_call(
        k_b, **ANY_IO,
        scratch_shapes=[pltpu.MemorySpace.HBM((H, W), jnp.float32),
                        pltpu.SemaphoreType.DMA(())],
    )(v), x)

    # c. + HBM→HBM copy between two regions of one scratch.
    def k_c(in_ref, out_ref, hbm, sem):
        cp = pltpu.make_async_copy(in_ref, hbm.at[0], sem)
        cp.start()
        cp.wait()
        cp2 = pltpu.make_async_copy(hbm.at[0], hbm.at[1], sem)
        cp2.start()
        cp2.wait()
        cp3 = pltpu.make_async_copy(hbm.at[1], out_ref, sem)
        cp3.start()
        cp3.wait()

    run("c_hbm_internal_copy", lambda v: pl.pallas_call(
        k_c, **ANY_IO,
        scratch_shapes=[pltpu.MemorySpace.HBM((2, H, W), jnp.float32),
                        pltpu.SemaphoreType.DMA(())],
    )(v), x)

    # d. + gridded pl.ds windowed DMA out of the scratch.  The refill
    #    copy runs unconditionally in EVERY program (grid steps execute
    #    sequentially on the core, so this is waste, not a race) — the
    #    one-shot guard is probe e's delta, not this one's.
    def make_k_win(guarded):
        def k_win(in_ref, out_ref, hbm, win, sems, xsem):
            i, j = pl.program_id(0), pl.program_id(1)

            def refill():
                cp = pltpu.make_async_copy(in_ref, hbm, xsem)
                cp.start()
                cp.wait()

            if guarded:
                pl.when(jnp.logical_and(i == 0, j == 0))(refill)
            else:
                refill()
            cp = pltpu.make_async_copy(
                hbm.at[pl.ds(i * TH, TH), pl.ds(j * TW, TW)], win, sems)
            cp.start()
            cp.wait()
            out_ref[...] = win[...]
        return k_win

    GRID_IO = dict(
        grid=(H // TH, W // TW),
        in_specs=[pl.BlockSpec(memory_space=pl.ANY)],
        out_specs=pl.BlockSpec((TH, TW), lambda i, j: (i, j)),
        out_shape=jax.ShapeDtypeStruct((H, W), jnp.float32),
    )
    SCRATCH = [pltpu.MemorySpace.HBM((H, W), jnp.float32),
               pltpu.VMEM((TH, TW), jnp.float32),
               pltpu.SemaphoreType.DMA(()),
               pltpu.SemaphoreType.DMA(())]

    run("d_windowed_from_hbm", lambda v: pl.pallas_call(
        make_k_win(False), **GRID_IO, scratch_shapes=SCRATCH)(v), x)

    # e. + the @pl.when(step == 0) one-shot refill guard.
    run("e_when_step0", lambda v: pl.pallas_call(
        make_k_win(True), **GRID_IO, scratch_shapes=SCRATCH)(v), x)

    # f. + the collective compiler params the real kernel passes.  The
    #    r5 run showed bare CompilerParams(collective_id) is rejected at
    #    TRACE time ("collective_id has to be unspecified or None when
    #    not using a custom barrier") — the rung never reached the
    #    helper.  Include the degenerate 1x1 form of the real kernel's
    #    neighbor barrier (get_barrier_semaphore + zero-count wait) so
    #    the construct under test is the one the helper actually sees.
    def k_f(in_ref, out_ref, hbm, win, sems, xsem):
        i, j = pl.program_id(0), pl.program_id(1)

        @pl.when(jnp.logical_and(i == 0, j == 0))
        def _barrier():
            # Same placement as the real kernel: the barrier runs inside
            # the one-shot step-0 guard (pallas_rdma._rdma_tiled_kernel).
            bsem = pltpu.get_barrier_semaphore()
            pltpu.semaphore_wait(bsem, jnp.int32(0))

        make_k_win(True)(in_ref, out_ref, hbm, win, sems, xsem)

    run("f_collective_params", lambda v: pl.pallas_call(
        k_f, **GRID_IO, scratch_shapes=SCRATCH,
        compiler_params=pltpu.CompilerParams(collective_id=1,
                                             has_side_effects=True),
    )(v), x)

    return 0


if __name__ == "__main__":
    sys.exit(main())
