#!/usr/bin/env python
"""Perf-regression sentry: gate new bench/loadgen rows against history.

Until round 13 the bench trajectory was write-only: rows landed in
evidence files and nothing ever JUDGED a new number against the old
ones.  This script is the gate:

* **History** — ``evidence/perf_history.jsonl`` (committed), one line
  per accepted measurement, keyed by ``plan_key + backend + grid`` (the
  same tuning identity the plan cache and the drift series use; rows
  without a plan_key fall back to their workload string).  Round 17:
  multi-host/multi-slice rows append ``|hosts=N|topo=...`` (a future
  multi-host row never shares a baseline with a single-host one) and
  sustained-load rows append ``|rps=R`` (a latency point is only
  comparable at the same offered load).
* **Latency gating** (round 17) — rows stamped ``gate_metric:
  "latency"`` (the p50/p95/p99-vs-offered-load curve, where throughput
  equals the offered rate by construction) gate on INVERSE p99: a 2×
  latency regression fails exactly like a 2× throughput loss.  History
  lines carry the gated value under ``metric`` (older lines fall back
  to their ``gpixels_per_s``).
* **Baseline** — the median of the last ``--window`` history entries
  for the row's key.  A key with fewer than ``--min-samples`` entries is
  SEEDED (recorded, gate passes): a fresh machine/config cannot regress
  against nothing.
* **Noise-aware threshold** — a row regresses when its throughput falls
  below ``baseline * (1 - t)`` with ``t = clamp(max(--threshold,
  --noise-mult * rel_stdev), ..., 0.9)``: the floor absorbs run-to-run
  jitter on quiet keys, the stdev term widens the gate automatically on
  keys whose history is itself noisy (CPU CI boxes), and improvements
  are reported but never fail.
* **Plan drift** (ROADMAP 5a's series, recorded since r11 but never
  judged) — ``--drift-metrics snapshot.json`` reads the
  ``pctpu_plan_drift_ratio`` gauge (measured/predicted Gpx/s per plan
  key) and flags any ratio outside ``[1/bound, bound]``
  (``--drift-bound``): a model that mispredicts by that much needs
  recalibration before its rankings can be trusted.

Exit status: 0 = every row within its gate (or seeded) and no drift
flags; 1 = at least one regression or drift flag; 2 = usage error.

  # seed, then gate (the trace-smoke leg does exactly this)
  python scripts/perf_gate.py --history evidence/perf_history.jsonl \\
      --row evidence/serving_smoke.json --update
  python scripts/perf_gate.py --history evidence/perf_history.jsonl \\
      --row evidence/serving_smoke.json

Rows are the established bench/loadgen schema: any JSON object (or
JSONL / list of objects) with ``gpixels_per_s`` and the key fields.
"""

from __future__ import annotations

import argparse
import json
import statistics
import sys
import time
from pathlib import Path

import _path  # noqa: F401  (repo root on sys.path)


def row_key(row: dict) -> str:
    """``plan_key|backend|grid[|solver=S]`` — the history identity of
    one row.

    ``plan_key`` (stamped by bench_iterate and serving responses since
    r13) is the canonical tuning identity; rows that predate it key on
    their workload string.  Backend prefers the EFFECTIVE backend (a
    degraded tier must never be compared against the requested tier's
    baseline); grid prefers the mesh/effective_grid stamp.  Convergence
    rows (r15) additionally key on their ``solver`` — every row that
    carries one gets a ``|solver=S`` suffix — so a multigrid row is
    never judged against a jacobi baseline (the two differ by orders of
    magnitude by design), and a jacobi convergence row never shares
    history with a fixed-count iterate row of the same plan_key.  (A
    plan_key already carrying the suffix is not double-stamped.)
    """
    plan = row.get("plan_key") or row.get("workload") or ""
    if isinstance(plan, (list, tuple)):
        plan = plan[0] if plan else ""
    b = row.get("effective_backend") or row.get("backend") or ""
    if isinstance(b, (list, tuple)):
        b = "+".join(str(x) for x in b)
    grid = (row.get("mesh") or row.get("effective_grid")
            or row.get("grid") or "")
    if isinstance(grid, (list, tuple)):
        grid = grid[0] if grid else ""
    key = f"{plan}|{b}|{grid}"
    solver = row.get("solver")
    if solver and f"solver={solver}" not in key:
        key += f"|solver={solver}"
    # Rank keying (round 23): rank-3 volume rows get their own history
    # lane — a (D,H,W) cells/s number must never be judged against a
    # rank-2 pixels/s baseline for a coincidentally-equal plan_key.
    # Rank-2 rows (and every pre-rank history line) stay unsuffixed, so
    # the committed history remains continuous.
    rank = row.get("rank")
    try:
        if rank is not None and int(rank) != 2:
            key += f"|rank={int(rank)}"
    except (TypeError, ValueError):
        pass
    # Topology keying (r17, ROADMAP item 1 pulled forward): multi-host /
    # multi-slice rows get their own history lane so they are never
    # judged against single-host baselines.  Single-host rows keep their
    # unsuffixed keys — the committed history stays continuous.
    hosts = row.get("hosts")
    topo = str(row.get("slice_topology") or "")
    try:
        multi = (hosts is not None and int(hosts) > 1) or (
            topo and not topo.startswith("1x"))
    except (TypeError, ValueError):
        multi = False
    if multi:
        key += f"|hosts={hosts}|topo={topo}"
    # Load-curve keying (r17): a latency point is only comparable at the
    # SAME offered load — each RPS step is its own lane.
    rps = row.get("offered_rps")
    if rps:
        key += f"|rps={rps:g}"
    return key


def row_metric(row: dict) -> float | None:
    """The gated number, HIGHER-IS-BETTER (None = row carries no
    gateable number, e.g. a zero-completion loadgen run).

    Default: throughput (``gpixels_per_s``).  Rows stamped
    ``gate_metric: "latency"`` — the sustained-load curve, where
    throughput equals the offered rate by construction and latency IS
    the regression surface — gate on inverse p99 (``1000 / p99_ms``),
    so a 2× latency regression halves the metric and fails exactly like
    a 2× throughput loss.
    """
    if row.get("gate_metric") == "latency":
        try:
            p99 = float(row.get("p99_ms"))
        except (TypeError, ValueError):
            return None
        return 1000.0 / p99 if p99 > 0 else None
    v = row.get("gpixels_per_s")
    try:
        v = float(v)
    except (TypeError, ValueError):
        return None
    return v if v > 0 else None


def hist_value(h: dict) -> float | None:
    """One history line's metric: ``metric`` (r17 lines) falling back to
    ``gpixels_per_s`` (every line written before latency gating)."""
    v = h.get("metric", h.get("gpixels_per_s"))
    try:
        v = float(v)
    except (TypeError, ValueError):
        return None
    return v if v > 0 else None


def load_rows(paths: list[str]) -> list[dict]:
    """Each file: a JSON object, a JSON list of objects, or JSONL."""
    rows: list[dict] = []
    for p in paths:
        text = Path(p).read_text().strip()
        if not text:
            continue
        try:
            data = json.loads(text)
            data = data if isinstance(data, list) else [data]
        except ValueError:
            data = [json.loads(line) for line in text.splitlines()
                    if line.strip()]
        for d in data:
            if isinstance(d, dict):
                d = dict(d)
                d["_src"] = p
                rows.append(d)
    return rows


def load_history(path: Path) -> list[dict]:
    if not path.exists():
        return []
    out = []
    for n, line in enumerate(path.read_text().splitlines(), 1):
        if not line.strip():
            continue
        try:
            out.append(json.loads(line))
        except ValueError:
            # A torn tail must not brick the gate forever — skip with a
            # visible note; --update rewrites clean lines only.
            print(f"perf_gate: skipping unparseable history line "
                  f"{path}:{n}", file=sys.stderr)
    return out


def evaluate(row: dict, history: list[dict], *, window: int,
             min_samples: int, threshold: float,
             noise_mult: float) -> dict:
    """One row's verdict against its key's rolling baseline."""
    key = row_key(row)
    gpx = row_metric(row)
    verdict = {"key": key, "metric": gpx,
               "gpixels_per_s": row.get("gpixels_per_s"),
               "src": row.get("_src", "")}
    if gpx is None:
        verdict.update(status="skipped",
                       note="row carries no positive gateable metric")
        return verdict
    hist = [v for v in (hist_value(h) for h in history
                        if h.get("key") == key)
            if v is not None][-window:]
    if len(hist) < min_samples:
        verdict.update(status="seeded", samples=len(hist),
                       note=f"fewer than {min_samples} history samples")
        return verdict
    base = statistics.median(hist)
    rel_sd = (statistics.stdev(hist) / base
              if len(hist) >= 3 and base > 0 else 0.0)
    t = min(0.9, max(threshold, noise_mult * rel_sd))
    ratio = gpx / base if base > 0 else None
    # gpx here is the gated METRIC (inverse p99 for latency rows).
    verdict.update(samples=len(hist), baseline=round(base, 6),
                   rel_stdev=round(rel_sd, 4), threshold=round(t, 4),
                   ratio=round(ratio, 4) if ratio is not None else None)
    if gpx < base * (1 - t):
        verdict["status"] = "regression"
    elif gpx > base * (1 + t):
        verdict["status"] = "improved"
    else:
        verdict["status"] = "ok"
    return verdict


def drift_flags(snapshot: dict, bound: float) -> list[dict]:
    """pctpu_plan_drift_ratio series outside [1/bound, bound]."""
    out = []
    for m in snapshot.get("metrics", []):
        if m.get("name") != "pctpu_plan_drift_ratio":
            continue
        for s in m.get("series", []):
            try:
                r = float(s["value"])
            except (KeyError, TypeError, ValueError):
                continue
            if r <= 0 or r > bound or r < 1.0 / bound:
                out.append({"key": s.get("labels", {}).get("key", ""),
                            "backend": s.get("labels", {}).get(
                                "backend", ""),
                            "drift_ratio": round(r, 4),
                            "bound": bound})
    return out


def wire_ab_flags(rows: list[dict], *, min_bytes: int,
                  knee_ratio: float) -> list[dict]:
    """Gate the wire/batching A/B evidence (``scripts/wire_ab.py`` →
    ``evidence/wire_ab.jsonl``).  Three holds, each a flag on failure:

    * every ``identity`` row must be identical (the binary wire is an
      encoding, never a different answer);
    * every ``codec`` row at ``payload_bytes >= min_bytes`` must show
      frames beating JSON (the crossover must sit BELOW the serving
      payload regime — tiny payloads may tie, big ones may not);
    * the ``batch_ab_summary`` knee ratio (refill/drain) must reach
      ``knee_ratio``, with a nonzero refill counter proving the overlap
      structurally happened.

    Missing evidence is itself a flag: an empty file must not pass.
    """
    out = []
    kinds = {r.get("kind") for r in rows}
    for want in ("codec", "identity", "batch_ab_summary"):
        if want not in kinds:
            out.append({"check": "wire_ab", "why": f"no {want} rows"})
    for r in rows:
        kind = r.get("kind")
        if kind == "identity" and not r.get("identical"):
            out.append({"check": "identity",
                        "endpoint": r.get("endpoint", ""),
                        "why": "arms not byte-identical"})
        elif kind == "codec":
            try:
                pb = float(r.get("payload_bytes", 0))
                jms, fms = float(r["json_ms"]), float(r["frames_ms"])
            except (KeyError, TypeError, ValueError):
                out.append({"check": "codec", "why": f"malformed row {r}"})
                continue
            if pb >= min_bytes and fms >= jms:
                out.append({"check": "codec",
                            "payload_bytes": int(pb),
                            "json_ms": jms, "frames_ms": fms,
                            "why": f"frames not faster at >= {min_bytes}B"})
        elif kind == "batch_ab_summary":
            ratio = r.get("knee_ratio")
            try:
                ok = float(ratio) >= knee_ratio
            except (TypeError, ValueError):
                ok = False
            if not ok:
                out.append({"check": "batch_knee", "knee_ratio": ratio,
                            "required": knee_ratio,
                            "why": "continuous batching did not raise "
                                   "the scale-curve knee"})
            if not r.get("refill_refills"):
                out.append({"check": "batch_refills",
                            "why": "refill arm reported zero mid-flight "
                                   "refills"})
    return out


def router_scale_flags(rows: list[dict], *, min_ratio: float,
                       p99_mult: float) -> list[dict]:
    """Gate the sharded-control-plane scale lane (round 21): the
    ``lane: "router_scale"`` rows ``scripts/shard_smoke.py`` appends to
    ``evidence/scale_curve.jsonl``.  Each row is one fleet size (1, 2,
    3 active routers, each fronting its OWN fixed-service-rate replica
    pool) driven by the identical shard-spread workload.  Holds:

    * the 1-router and 3-router rows both exist (missing evidence is a
      flag, never a pass);
    * no row carries non-rejected failures;
    * 3-router aggregate RPS >= ``min_ratio`` x the 1-router knee — the
      control plane must scale out, not serialize behind one router;
    * 3-router p99 <= ``p99_mult`` x the 1-router p99 — throughput must
      not be bought with tail latency.
    """
    out = []
    lane = [r for r in rows if r.get("lane") == "router_scale"]
    if not lane:
        return [{"check": "router_scale", "why": "no router_scale rows"}]
    by_k: dict[int, dict] = {}
    for r in lane:
        try:
            by_k[int(r["routers"])] = r
        except (KeyError, TypeError, ValueError):
            out.append({"check": "router_scale",
                        "why": f"malformed lane row {r}"})
    for r in lane:
        if r.get("failures"):
            out.append({"check": "scale_failures",
                        "routers": r.get("routers"),
                        "why": f"{r['failures']} non-rejected failures "
                               "in the scale lane"})
    r1, r3 = by_k.get(1), by_k.get(3)
    if r1 is None or r3 is None:
        out.append({"check": "router_scale",
                    "why": f"need 1- and 3-router rows, have "
                           f"{sorted(by_k)}"})
        return out
    try:
        rps1, rps3 = float(r1["rps"]), float(r3["rps"])
        p99_1, p99_3 = float(r1["p99_ms"]), float(r3["p99_ms"])
    except (KeyError, TypeError, ValueError):
        out.append({"check": "router_scale",
                    "why": "lane rows missing rps/p99_ms"})
        return out
    ratio = rps3 / rps1 if rps1 else 0.0
    if ratio < min_ratio:
        out.append({"check": "scale_ratio", "rps_1": rps1,
                    "rps_3": rps3, "ratio": round(ratio, 3),
                    "required": min_ratio,
                    "why": "3-router aggregate RPS did not clear "
                           f"{min_ratio}x the 1-router knee"})
    if p99_1 and p99_3 > p99_mult * p99_1:
        out.append({"check": "scale_p99", "p99_1_ms": p99_1,
                    "p99_3_ms": p99_3, "mult": p99_mult,
                    "why": "3-router p99 blew past the 1-router "
                           "baseline band"})
    return out


def cache_lane_flags(rows: list[dict], *, min_top_hit_rate: float,
                     hit_p99_ratio: float,
                     unique_p99_mult: float) -> list[dict]:
    """Gate the result-cache lane: the ``lane: "cache_skew"`` rows
    ``scripts/cache_smoke.py`` writes into the shared curve file.  The
    lane holds one row per zipf skew S (``mode: "zipf"``) plus an
    all-unique A/B pair (``mode: "unique"``, ``cache: "on" | "off"``).
    Holds:

    * the lane exists, with >= 2 distinct skews (a one-point "curve"
      proves nothing) and the unique on/off pair — missing evidence is
      a flag, never a pass;
    * no row carries non-rejected failures;
    * hit rate RISES with skew and the top-skew row clears
      ``min_top_hit_rate`` — the cache must actually absorb the
      duplicate-heavy head;
    * on the top-skew row, hit p99 <= ``hit_p99_ratio`` x miss p99 —
      served-from-cache must be decisively faster than touching the
      device (the "p99 drops on the zipf lane" gate, measured where
      the effect lives instead of through the mix's miss-dominated
      tail);
    * all-unique p99 with the cache ON <= ``unique_p99_mult`` x OFF —
      digest+lookup overhead must not tax the 0%-hit workload.
    """
    out = []
    lane = [r for r in rows if r.get("lane") == "cache_skew"]
    if not lane:
        return [{"check": "cache_lane", "why": "no cache_skew rows"}]
    for r in lane:
        if r.get("failures"):
            out.append({"check": "cache_failures",
                        "mode": r.get("mode"), "zipf_s": r.get("zipf_s"),
                        "why": f"{r['failures']} non-rejected failures "
                               "in the cache lane"})
    zipf = sorted((r for r in lane if r.get("mode") == "zipf"),
                  key=lambda r: float(r.get("zipf_s") or 0.0))
    uniq = {str(r.get("cache")): r for r in lane
            if r.get("mode") == "unique"}
    if len({r.get("zipf_s") for r in zipf}) < 2:
        out.append({"check": "cache_curve",
                    "why": f"need >= 2 zipf skews, have "
                           f"{[r.get('zipf_s') for r in zipf]}"})
    if zipf:
        try:
            rates = [float(r["cache_hit_rate"]) for r in zipf]
        except (KeyError, TypeError, ValueError):
            out.append({"check": "cache_curve",
                        "why": "zipf rows missing cache_hit_rate"})
            rates = []
        if rates:
            if rates[-1] < min_top_hit_rate:
                out.append({"check": "cache_hit_rate",
                            "zipf_s": zipf[-1].get("zipf_s"),
                            "hit_rate": rates[-1],
                            "required": min_top_hit_rate,
                            "why": "top-skew hit rate below the bar"})
            if len(rates) >= 2 and rates[-1] <= rates[0]:
                out.append({"check": "cache_skew_monotone",
                            "rates": rates,
                            "why": "hit rate did not rise with skew"})
        top = zipf[-1]
        try:
            hp = float(top["hit_p99_ms"])
            mp = float(top["miss_p99_ms"])
        except (KeyError, TypeError, ValueError):
            out.append({"check": "cache_hit_p99",
                        "why": "top-skew row missing hit/miss p99"})
        else:
            if mp and hp > hit_p99_ratio * mp:
                out.append({"check": "cache_hit_p99",
                            "hit_p99_ms": hp, "miss_p99_ms": mp,
                            "ratio": hit_p99_ratio,
                            "why": "cache hits not decisively faster "
                                   "than device misses at p99"})
    on, off = uniq.get("on"), uniq.get("off")
    if on is None or off is None:
        out.append({"check": "cache_unique",
                    "why": f"need unique cache on+off rows, have "
                           f"{sorted(uniq)}"})
        return out
    try:
        p_on, p_off = float(on["p99_ms"]), float(off["p99_ms"])
    except (KeyError, TypeError, ValueError):
        out.append({"check": "cache_unique",
                    "why": "unique rows missing p99_ms"})
        return out
    if p_off and p_on > unique_p99_mult * p_off:
        out.append({"check": "cache_unique_p99",
                    "p99_on_ms": p_on, "p99_off_ms": p_off,
                    "mult": unique_p99_mult,
                    "why": "all-unique p99 regressed with the cache "
                           "enabled (lookup overhead tax)"})
    hr = on.get("cache_hit_rate")
    if hr:
        out.append({"check": "cache_unique_hits", "hit_rate": hr,
                    "why": "all-unique run reported cache hits — the "
                           "digest is colliding or the workload is "
                           "not unique"})
    return out


def storage_smoke_flags(row: dict | None, *, min_modes: int = 4,
                        min_workloads: int = 5) -> list[dict]:
    """Gate the storage-chaos matrix row (``evidence/storage_smoke.json``
    from ``scripts/chaos_matrix.py``).  Holds:

    * the row exists and reports ``failures: 0`` — missing or
      unreadable evidence is a flag, never a pass;
    * the matrix actually covered the advertised surface: >=
      ``min_modes`` fault modes x >= ``min_workloads`` workloads, every
      cell ``ok``, and each non-kill cell's fault actually fired;
    * the ENOSPC degrade drill's acceptance chain held end-to-end:
      a degraded-durability window was OBSERVED (stamped on responses),
      durability re-armed on heal, the degraded-window finalization
      survived into the replay, and zero stale jobs resurrected;
    * both site drills ran: ``events_emit`` dropped lines instead of
      raising, ``evidence_write`` failed typed with the shared curve
      intact.
    """
    if not row:
        return [{"check": "storage_smoke",
                 "why": "no storage-smoke evidence row"}]
    out = []
    if row.get("failures"):
        out.append({"check": "storage_failures",
                    "failures": row["failures"],
                    "detail": row.get("failure_detail", [])[:4],
                    "why": "storage-chaos matrix reported failures"})
    cells = row.get("cells") or []
    modes = {c.get("mode") for c in cells}
    workloads = {c.get("workload") for c in cells}
    if len(modes) < min_modes or len(workloads) < min_workloads:
        out.append({"check": "storage_coverage",
                    "modes": sorted(str(m) for m in modes),
                    "workloads": sorted(str(w) for w in workloads),
                    "why": f"matrix thinner than {min_modes} modes x "
                           f"{min_workloads} workloads"})
    bad = [c["cell"] for c in cells if not c.get("ok")]
    if bad:
        out.append({"check": "storage_cells", "cells": bad[:6],
                    "why": f"{len(bad)} matrix cell(s) failed"})
    dead = [c["cell"] for c in cells
            if c.get("mode") != "kill" and not c.get("injected")]
    if dead:
        out.append({"check": "storage_injection", "cells": dead[:6],
                    "why": "cells whose fault never fired (a dead "
                           "drill proves nothing)"})
    drill = row.get("enospc_drill") or {}
    for field, label in (("degraded_window", "no degraded-durability "
                                             "window observed"),
                         ("rearmed", "durability did not re-arm on "
                                     "heal"),
                         ("finalized_carried", "degraded-window "
                          "finalization lost across replay")):
        if not drill.get(field):
            out.append({"check": "storage_degrade_ladder",
                        "field": field, "why": label})
    if drill.get("stale_live_jobs"):
        out.append({"check": "storage_degrade_ladder",
                    "field": "stale_live_jobs",
                    "count": drill["stale_live_jobs"],
                    "why": "replay after the healed window resurrected "
                           "stale jobs"})
    site = row.get("site_drills") or {}
    ev = site.get("events_emit") or {}
    if not ev.get("dropped"):
        out.append({"check": "storage_site_drills", "site": "events_emit",
                    "why": "events_emit drill dropped nothing"})
    evw = site.get("evidence_write") or {}
    if not (evw.get("typed") and evw.get("curve_intact")):
        out.append({"check": "storage_site_drills",
                    "site": "evidence_write",
                    "why": "evidence_write fault not typed or the "
                           "shared curve was torn"})
    return out


def main() -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--history", default=None,
                    help="the committed JSONL history "
                         "(evidence/perf_history.jsonl; required "
                         "with --row)")
    ap.add_argument("--row", action="append", default=[], metavar="JSON",
                    help="bench/loadgen row file to gate (repeatable; "
                         "JSON object, list, or JSONL)")
    ap.add_argument("--update", action="store_true",
                    help="append gated rows to the history AFTER "
                         "evaluation (so a rerun of the same row "
                         "compares against it)")
    ap.add_argument("--window", type=int, default=8,
                    help="rolling baseline size per key")
    ap.add_argument("--min-samples", type=int, default=1,
                    help="history samples required before gating "
                         "(fewer = seed and pass)")
    ap.add_argument("--threshold", type=float, default=0.3,
                    help="regression floor: fail below "
                         "baseline*(1-threshold)")
    ap.add_argument("--noise-mult", type=float, default=3.0,
                    help="threshold widens to this multiple of the "
                         "history's relative stdev when larger")
    ap.add_argument("--drift-metrics", default=None, metavar="SNAP_JSON",
                    help="metrics snapshot (obs.metrics.dump) to check "
                         "plan-drift ratios from the 5a series")
    ap.add_argument("--drift-bound", type=float, default=10.0,
                    help="flag drift ratios outside [1/bound, bound]")
    ap.add_argument("--wire-ab", default=None, metavar="JSONL",
                    help="wire/batching A/B evidence to gate "
                         "(evidence/wire_ab.jsonl from scripts/"
                         "wire_ab.py): identity must hold, frames must "
                         "beat JSON at >= --wire-min-bytes, the refill "
                         "knee must clear --wire-knee-ratio")
    ap.add_argument("--wire-min-bytes", type=int, default=65536,
                    help="payload size from which frames must beat JSON")
    ap.add_argument("--wire-knee-ratio", type=float, default=1.2,
                    help="required refill/drain scale-curve knee ratio")
    ap.add_argument("--router-scale", default=None, metavar="JSONL",
                    help="scale-curve evidence holding the round-21 "
                         "lane: \"router_scale\" rows "
                         "(evidence/scale_curve.jsonl from scripts/"
                         "shard_smoke.py): 3-router aggregate RPS must "
                         "clear --scale-min-ratio x the 1-router knee "
                         "with p99 inside --scale-p99-mult")
    ap.add_argument("--scale-min-ratio", type=float, default=2.4,
                    help="required 3-router / 1-router aggregate RPS "
                         "ratio")
    ap.add_argument("--scale-p99-mult", type=float, default=1.5,
                    help="3-router p99 must stay within this multiple "
                         "of the 1-router p99")
    ap.add_argument("--cache-lane", default=None, metavar="JSONL",
                    help="curve evidence holding the result-cache "
                         "lane: \"cache_skew\" rows "
                         "(evidence/scale_curve.jsonl from scripts/"
                         "cache_smoke.py): hit rate must rise with "
                         "skew and clear --cache-min-hit-rate at the "
                         "top, hit p99 must beat miss p99 by "
                         "--cache-hit-p99-ratio, and the all-unique "
                         "cache-on arm must stay within "
                         "--cache-unique-p99-mult of cache-off")
    ap.add_argument("--cache-min-hit-rate", type=float, default=0.5,
                    help="required hit rate on the most-skewed zipf "
                         "row")
    ap.add_argument("--cache-hit-p99-ratio", type=float, default=0.5,
                    help="hit p99 must be <= this fraction of miss "
                         "p99 on the top-skew row")
    ap.add_argument("--cache-unique-p99-mult", type=float, default=1.5,
                    help="all-unique p99 with cache on must stay "
                         "within this multiple of cache off")
    ap.add_argument("--storage-smoke", default=None, metavar="JSON",
                    help="storage-chaos matrix evidence to gate "
                         "(evidence/storage_smoke.json from scripts/"
                         "chaos_matrix.py): every cell green, every "
                         "fault fired, the ENOSPC degrade ladder "
                         "(degrade -> serve -> re-arm -> clean replay) "
                         "held, both site drills passed")
    ap.add_argument("--out", default=None,
                    help="also write the JSON report to this path")
    ap.add_argument("--quiet", action="store_true")
    args = ap.parse_args()
    if (not args.row and not args.drift_metrics and not args.wire_ab
            and not args.router_scale and not args.cache_lane
            and not args.storage_smoke):
        print("need --row, --drift-metrics, --wire-ab, "
              "--router-scale, --cache-lane, and/or --storage-smoke",
              file=sys.stderr)
        return 2
    if args.row and not args.history:
        print("--row needs --history", file=sys.stderr)
        return 2

    hist_path = Path(args.history) if args.history else None
    history = load_history(hist_path) if hist_path else []
    try:
        rows = load_rows(args.row)
    except (OSError, ValueError) as e:
        print(f"perf_gate: unreadable row file: {e}", file=sys.stderr)
        return 2

    verdicts = [evaluate(r, history,
                         window=args.window, min_samples=args.min_samples,
                         threshold=args.threshold,
                         noise_mult=args.noise_mult)
                for r in rows]

    flags = []
    if args.drift_metrics:
        try:
            snap = json.loads(Path(args.drift_metrics).read_text())
        except (OSError, ValueError) as e:
            print(f"perf_gate: unreadable metrics snapshot: {e}",
                  file=sys.stderr)
            return 2
        flags = drift_flags(snap, args.drift_bound)

    wflags = []
    if args.wire_ab:
        try:
            wrows = load_rows([args.wire_ab])
        except (OSError, ValueError) as e:
            print(f"perf_gate: unreadable wire-ab file: {e}",
                  file=sys.stderr)
            return 2
        wflags = wire_ab_flags(wrows, min_bytes=args.wire_min_bytes,
                               knee_ratio=args.wire_knee_ratio)

    sflags = []
    if args.router_scale:
        try:
            srows = load_rows([args.router_scale])
        except (OSError, ValueError) as e:
            print(f"perf_gate: unreadable router-scale file: {e}",
                  file=sys.stderr)
            return 2
        sflags = router_scale_flags(srows,
                                    min_ratio=args.scale_min_ratio,
                                    p99_mult=args.scale_p99_mult)

    cflags = []
    if args.cache_lane:
        try:
            crows = load_rows([args.cache_lane])
        except (OSError, ValueError) as e:
            print(f"perf_gate: unreadable cache-lane file: {e}",
                  file=sys.stderr)
            return 2
        cflags = cache_lane_flags(
            crows, min_top_hit_rate=args.cache_min_hit_rate,
            hit_p99_ratio=args.cache_hit_p99_ratio,
            unique_p99_mult=args.cache_unique_p99_mult)

    stflags = []
    if args.storage_smoke:
        try:
            srow = json.loads(Path(args.storage_smoke).read_text())
        except (OSError, ValueError):
            srow = None   # missing/unreadable evidence IS the flag
        stflags = storage_smoke_flags(srow)

    regressions = [v for v in verdicts if v["status"] == "regression"]
    if args.update and hist_path:
        # Append-only, one line per gated row — regressions too: a real
        # slowdown becomes the new reality after it ships; the gate's
        # job is to make it LOUD once, not to pin the baseline forever.
        hist_path.parent.mkdir(parents=True, exist_ok=True)
        with open(hist_path, "a") as f:
            for r, v in zip(rows, verdicts):
                if v["status"] == "skipped":
                    continue
                f.write(json.dumps({
                    "key": v["key"],
                    # The gated metric (throughput, or inverse p99 for
                    # latency-gated rows) — hist_value reads this first.
                    "metric": v["metric"],
                    "gpixels_per_s": v["gpixels_per_s"],
                    "p95_ms": r.get("p95_ms"),
                    "p99_ms": r.get("p99_ms"),
                    "status": v["status"],
                    "ts": round(time.time(), 3),
                    "src": v["src"],
                }) + "\n")

    report = {
        "rows": len(rows),
        "history_lines": len(history),
        "verdicts": verdicts,
        "regressions": len(regressions),
        "drift_flags": flags,
        "wire_ab_flags": wflags,
        "router_scale_flags": sflags,
        "cache_lane_flags": cflags,
        "storage_smoke_flags": stflags,
        "updated": bool(args.update),
    }
    if not args.quiet:
        for v in verdicts:
            line = (f"{v['status']:10s} {v['key']}  "
                    f"metric={v['metric']}")
            if "baseline" in v:
                line += (f"  baseline={v['baseline']} "
                         f"ratio={v['ratio']} thr={v['threshold']}")
            print(line)
        for fl in flags:
            print(f"drift      {fl['key']}|{fl['backend']}  "
                  f"ratio={fl['drift_ratio']} outside "
                  f"[1/{fl['bound']}, {fl['bound']}]")
        for fl in wflags:
            print(f"wire_ab    {fl['check']}: {fl['why']}")
        for fl in sflags:
            print(f"router_scale {fl['check']}: {fl['why']}")
        for fl in cflags:
            print(f"cache_lane {fl['check']}: {fl['why']}")
        for fl in stflags:
            print(f"storage    {fl['check']}: {fl['why']}")
    if args.out:
        p = Path(args.out)
        p.parent.mkdir(parents=True, exist_ok=True)
        p.write_text(json.dumps(report, indent=2))
    else:
        print(json.dumps(report))
    return 1 if (regressions or flags or wflags or sflags
                 or cflags or stflags) else 0


if __name__ == "__main__":
    sys.exit(main())
