#!/usr/bin/env python
"""Labeled multi-chip projection from measured single-chip rows.

SURVEY.md §7 ("single-chip reality"): with one physical chip attached,
multi-chip perf numbers must be CLEARLY-LABELED extrapolations, not
measurements.  This tool is that label made executable: it reads the
measured single-chip rows (`evidence/baseline_tpu.json`) and projects
BASELINE configs 2/4/5 onto their target mesh with an explicit analytic
model — every hardware assumption is a flag, every row carries
``"projection": true`` and echoes the assumptions it used.

Model (per fused chunk of T iterations, per chip, block h×w×C,
storage s bytes/px, filter radius r):

  compute_s = T * h * w * C / measured_gpx_per_chip
  halo_bytes = 2 * (h + w) * r * T * C * s        (both axes, both sides)
  halo_s    = halo_bytes / ici_bytes_s + 2 * phases * latency_s

Two sequential ppermute phases propagate corners (parallel/halo.py), so
latency enters twice per exchange.  Convergence (config 5) adds one
allreduce latency every check_every iterations.  The projection divides
compute by (compute + halo) — i.e. it assumes NO comm/compute overlap,
the conservative end; XLA's async collectives can only do better.

Defaults: ``--ici-gb-s 45`` (per-link-class aggregate for a v5e 2D
torus neighbor exchange; an ASSUMPTION, not a measurement) and
``--latency-us 5`` (per collective phase; an ASSUMPTION in the range of
typical ICI small-message latencies — the CPU-mesh halo proxy is NOT a
bracket for it: it measures XLA:CPU pad/ppermute/stitch cost on host
cores, ~ms for 512² blocks under the round-5 live-differenced
definition, and says nothing about ICI).
Sensitivity: pass different values; rows are cheap.
"""

from __future__ import annotations

import argparse
import json

CONFIGS = [
    # (name, global (H, W, C), mesh (R, Cc), storage bytes, fuse T, radius,
    #  check_every or None)
    ("2: blur3 1920x2520 rgb on 2x2", (1920, 2520, 3), (2, 2), 2, 16, 1, None),
    ("4: blur3 65536^2 rgb on 4x4 (north star)", (65536, 65536, 3), (4, 4),
     2, 16, 1, None),
    ("5: jacobi3 32768^2 f32 on 4x4", (32768, 32768, 1), (4, 4), 4, 1, 1, 10),
]

# Fallback single-chip basis (copied from evidence/baseline_tpu.json as of
# 2026-07-29) — used only if that file is unreadable; the live rows are
# preferred so a re-measure propagates here automatically.  Configs 4/5
# time exactly the target per-chip block; config 2's basis row timed the
# FULL image (4x the 2x2 per-chip block) — per-chip rates usually drop at
# smaller blocks, so that projection leans optimistic and its row says so.
FALLBACK_BASIS = {
    # config-2 basis updated 2026-07-31: the original 266.403 reading did
    # not reproduce (round-5 same-config re-measure: 109.027, cache-
    # residency artifact; BASELINE.md config-2 rows) — carry the
    # reproducible figure.
    "2:": ("blur3 1920x2520x3 100 iters", 109.027),
    "4:": ("blur3 16384x16384x3 5 iters", 86.658),
    "5:": ("jacobi3 8192x8192 tol=1e-3", 22.42),
}


def load_basis() -> dict:
    """{config-prefix: (workload, per-chip rate)} from the evidence rows."""
    import os

    basis = dict(FALLBACK_BASIS)
    path = os.path.join(os.path.dirname(__file__), "..", "evidence",
                        "baseline_tpu.json")
    try:
        with open(path) as f:
            for line in f:
                # Per-line guard: one malformed/blank row (or a matching
                # row missing "workload") must not kill the tool — skip it
                # and let FALLBACK_BASIS cover that config.
                try:
                    row = json.loads(line)
                    pref = row.get("config", " ")[:2]
                    if pref in basis:
                        rate = row.get("gpixels_per_s_per_chip",
                                       row.get("iters_per_s"))
                        if rate:
                            basis[pref] = (row["workload"], float(rate))
                except (ValueError, KeyError, TypeError):
                    continue
    except OSError:
        pass
    return basis


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--ici-gb-s", type=float, default=45.0,
                    help="assumed neighbor-exchange ICI bandwidth, GB/s")
    ap.add_argument("--latency-us", type=float, default=5.0,
                    help="assumed per-collective-phase latency, us")
    args = ap.parse_args()
    ici = args.ici_gb_s * 1e9
    lat = args.latency_us * 1e-6

    basis_map = load_basis()
    for name, (H, W, C), (R, Cc), sbytes, T, r, check_every in CONFIGS:
        basis_workload, basis = basis_map[name[:2]]
        chips = R * Cc
        h, w = H // R, W // Cc
        px_per_iter = h * w * C

        if check_every is None:
            compute_s = T * px_per_iter / (basis * 1e9)
        else:
            # basis is iters/s at this block size; fuse=1 semantics.
            compute_s = T / basis
        halo_bytes = 2 * (h + w) * r * T * C * sbytes
        halo_s = halo_bytes / ici + 2 * 2 * lat  # 2 phases, signal+drain
        if check_every is not None:
            halo_s += lat * T / check_every  # amortized allreduce
        eff = compute_s / (compute_s + halo_s)

        row = {
            "projection": True,
            "config": name,
            "mesh": f"{R}x{Cc}",
            "basis_row": basis_workload,
            "basis_per_chip": basis,
            "assumed_ici_gb_s": args.ici_gb_s,
            "assumed_latency_us": args.latency_us,
            "halo_bytes_per_chunk": halo_bytes,
            "halo_overhead_pct": round((1 - eff) * 100, 2),
            "projected_per_chip": round(basis * eff, 2),
            "unit": "iters/s" if check_every is not None else "Gpx/s",
            "note": "no-overlap analytic projection, NOT a measurement",
        }
        if check_every is None:
            row["projected_fleet"] = round(basis * eff * chips, 2)
        else:
            # A lockstep Jacobi solve advances ONE global iteration at a
            # time: 16 chips don't iterate 16x faster, they carry 16x the
            # area at the same rate — that IS the scaling claim.
            row["projected_solve_iters_per_s"] = round(basis * eff, 2)
            row["area_scaled_x"] = chips
        if name.startswith("2:"):
            row["basis_block_px_ratio"] = 4.0
            row["basis_caveat"] = ("basis row timed the full image, 4x the "
                                   "per-chip block; per-chip rates drop at "
                                   "smaller blocks, so this leans optimistic")
        print(json.dumps(row))
    return 0


if __name__ == "__main__":
    import sys

    sys.exit(main())
