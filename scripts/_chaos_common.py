"""Shared harness pieces for the chaos drills.

``scripts/soak.py --chaos`` (randomized cycles) and
``scripts/chaos_smoke.py`` (the deterministic tier-1 leg) drive the
same pool shape and contract; these helpers keep the two from drifting:
the converge-job wire body, the three-replica chaos pool (one replica
per failure shape), the clean-router oracle run, and the client
retry-with-backoff loop every drill's traffic uses.
"""

from __future__ import annotations

import time

# One replica per failure shape: c0 drops (send + recv), c1 corrupts
# response bodies, c2 injects send latency.
CHAOS_POOL_MODES = (None,
                    {"transport_recv": "corrupt"},
                    {"transport_send": "latency"})


def converge_body(b64: str, rows: int, cols: int, rid: str,
                  tenant: str | None = None, **kw) -> dict:
    """The drills' canonical convergence-job wire body (jacobi3 to a
    fixed 40-iteration budget unless overridden)."""
    body = {"image_b64": b64, "rows": int(rows), "cols": int(cols),
            "mode": "grey", "filter": "jacobi3", "backend": "shifted",
            "quantize": False, "tol": 0.0, "max_iters": 40,
            "check_every": 10, "request_id": rid}
    if tenant is not None:
        body["tenant"] = tenant
    body.update(kw)
    return body


def chaos_pool(factory, seed: int, latency_s: float = 0.02):
    """Three in-process replicas c0/c1/c2, each wrapped in a
    ChaosTransport with its own failure shape (CHAOS_POOL_MODES)."""
    from parallel_convolution_tpu.serving.chaos import ChaosTransport
    from parallel_convolution_tpu.serving.router import InProcessReplica

    return [ChaosTransport(InProcessReplica(factory, name=f"c{i}"),
                           modes=m, seed=seed + i, latency_s=latency_s)
            for i, m in enumerate(CHAOS_POOL_MODES)]


def oracle_converge_final(factory, body: dict) -> dict:
    """The uninterrupted oracle run: one clean replica behind a plain
    router; returns the final row (raises if the job did not finish)."""
    from parallel_convolution_tpu.serving.router import (
        InProcessReplica, ReplicaRouter,
    )

    router = ReplicaRouter([InProcessReplica(factory, name="clean")],
                           start_health=False)
    try:
        _, rows = router.converge(dict(body))
        final = list(rows)[-1]
    finally:
        router.close()
    if final.get("kind") != "final":
        raise RuntimeError(f"oracle converge failed: {final}")
    return final


def request_with_backoff(router, body: dict, attempts: int = 6,
                         cap_s: float = 0.3) -> dict:
    """One batch request through the router, honoring typed RETRYABLE
    rejections with capped backoff (the loadgen client contract)."""
    wire: dict = {}
    for _ in range(attempts):
        _, wire = router.request(dict(body))
        if wire.get("ok") or not wire.get("retryable"):
            break
        time.sleep(min(float(wire.get("retry_after_s") or 0.05), cap_s))
    return wire
