#!/usr/bin/env python
"""Wire-format + continuous-batching A/B evidence generator.

Produces ``evidence/wire_ab.jsonl`` — the committed proof behind the
binary data plane (round 20), three row kinds:

* ``codec`` — the crossover curve: encode+decode wall time of the SAME
  u8 image through the JSON arm (base64 + json.dumps/loads) vs the
  frames arm (``serving.frames`` envelope), swept across payload sizes.
  This is the pure wire tax, no device work — the curve the README
  plots and ``perf_gate.py --wire-ab`` holds (frames must beat JSON at
  >= 64 KB).

* ``identity`` — byte-identity of the two arms end to end: one
  in-process service, each endpoint (``/v1/convolve`` one-shot,
  ``/v1/converge`` streamed) driven through BOTH codecs with the same
  input; every tensor crossing the wire must match byte-for-byte and
  every control field must agree.  A non-identical row is a hard
  failure (exit 1) — the binary wire is an encoding, never a different
  answer.

* ``batch_ab`` — drain vs refill: the same synthetic host/device load
  (``prepare`` burns host milliseconds, ``execute`` burns device
  milliseconds) through a ``pipeline_depth=0`` batcher (the old
  drain-between-flushes barrier) and a ``pipeline_depth=1`` batcher
  (continuous batching), swept across closed-loop worker counts.  Each
  arm's KNEE is its best sustained throughput; the refill arm must
  raise the knee (the flush barrier was the bottleneck) and its
  ``refills`` counter must be nonzero (the overlap actually happened —
  drain mode structurally cannot refill).

stdlib + numpy + the serving package; runs on CPU in seconds
(``--quick`` trims the sweeps for the tier-1 smoke leg).
"""

from __future__ import annotations

import argparse
import base64
import json
import sys
import threading
import time

import _path  # noqa: F401

# Codec sweep: square u8 images, side -> payload_bytes = side*side.
_SIDES = (64, 128, 256, 512, 1024, 2048)
_SIDES_QUICK = (64, 256, 512, 1024)


def _codec_rows(sides, repeat: int):
    """The crossover curve: best-of-``repeat`` encode+decode wall time
    per arm at each payload size, same header shape both arms."""
    import numpy as np

    from parallel_convolution_tpu.serving import frames as frames_mod

    rows = []
    for side in sides:
        img = np.arange(side * side, dtype=np.uint8).reshape(side, side)
        header = {"rows": side, "cols": side, "mode": "grey",
                  "filter": "blur3", "iters": 1}

        def _json_arm():
            doc = json.dumps(dict(header, image_b64=base64.b64encode(
                img.tobytes()).decode("ascii")))
            out = json.loads(doc)
            return np.frombuffer(base64.b64decode(out["image_b64"]),
                                 np.uint8)

        def _frames_arm():
            env = frames_mod.encode_envelope(header, {"image": img})
            _, arrays = frames_mod.decode_envelope(env)
            return arrays["image"]

        # Identity of the round-tripped bytes is part of the curve's
        # validity: a faster codec that loses bits is not a codec.
        assert _json_arm().tobytes() == img.tobytes()
        assert _frames_arm().tobytes() == img.tobytes()
        timed = {}
        for name, fn in (("json", _json_arm), ("frames", _frames_arm)):
            best = float("inf")
            for _ in range(repeat):
                t0 = time.perf_counter()
                fn()
                best = min(best, time.perf_counter() - t0)
            timed[name] = best
        rows.append({
            "kind": "codec",
            "payload_bytes": side * side,
            "json_ms": round(1e3 * timed["json"], 4),
            "frames_ms": round(1e3 * timed["frames"], 4),
            "speedup": round(timed["json"] / timed["frames"], 2)
            if timed["frames"] else None,
        })
    return rows


def _identity_rows(rows_px: int, cols_px: int, seed: int):
    """Drive BOTH endpoints through both codec arms on one in-process
    service; compare every crossing tensor byte-for-byte."""
    import numpy as np

    from parallel_convolution_tpu.serving import frames as frames_mod
    from parallel_convolution_tpu.serving.frontend import InProcessClient
    from parallel_convolution_tpu.serving.service import ConvolutionService
    from parallel_convolution_tpu.utils import imageio

    img = imageio.generate_test_image(rows_px, cols_px, "grey", seed=seed)
    service = ConvolutionService(None, max_batch=4, max_delay_s=0.002,
                                 max_queue=64)
    client = InProcessClient(service)
    out = []
    try:
        # -- /v1/convolve ---------------------------------------------------
        base = {"rows": rows_px, "cols": cols_px, "mode": "grey",
                "filter": "blur3", "iters": 2, "backend": "shifted",
                "storage": "f32", "fuse": 1, "boundary": "zero"}
        jbody = dict(base, image_b64=base64.b64encode(
            np.ascontiguousarray(img).tobytes()).decode("ascii"),
            request_id="ab-json")
        js, jresp = client.request(jbody, timeout=60.0)
        env = frames_mod.encode_envelope(dict(base, request_id="ab-frames"),
                                         {"image": img})
        fs, fraw = client.request_frames(env, timeout=60.0)
        fheader, farrays = frames_mod.decode_envelope(fraw)
        identical = (js == fs == 200 and jresp.get("ok")
                     and fheader.get("ok")
                     and base64.b64decode(jresp["image_b64"])
                     == farrays["image"].tobytes()
                     and jresp.get("effective_backend")
                     == fheader.get("effective_backend"))
        out.append({"kind": "identity", "endpoint": "convolve",
                    "identical": bool(identical),
                    "bytes_compared": int(img.size),
                    "wire_json": jresp.get("wire"),
                    "wire_frames": fheader.get("wire")})

        # -- /v1/converge ---------------------------------------------------
        cbase = {"rows": rows_px, "cols": cols_px, "mode": "grey",
                 "filter": "blur3", "backend": "shifted", "storage": "f32",
                 "fuse": 1, "boundary": "zero", "tol": 5e-3,
                 "max_iters": 40, "check_every": 10, "quantize": False,
                 "solver": "jacobi"}
        jbody = dict(cbase, image_b64=base64.b64encode(
            np.ascontiguousarray(img).tobytes()).decode("ascii"),
            request_id="abc-json")
        js, jrows = client.converge(jbody, timeout=60.0)
        jrows = list(jrows)
        env = frames_mod.encode_envelope(
            dict(cbase, request_id="abc-frames"), {"image": img})
        fs, frows = client.converge_frames(env, timeout=60.0)
        frows = [frames_mod.decode_envelope(r) for r in frows]
        identical = js == fs == 200 and len(jrows) == len(frows)
        compared = 0
        if identical:
            for jr, (fh, fa) in zip(jrows, frows):
                jimg = base64.b64decode(jr.get("image_b64", ""))
                fimg = fa["image"].tobytes() if "image" in fa else b""
                if (jr.get("kind") != fh.get("kind") or jimg != fimg
                        or jr.get("iteration") != fh.get("iteration")
                        or jr.get("converged") != fh.get("converged")):
                    identical = False
                    break
                compared += 1
        out.append({"kind": "identity", "endpoint": "converge",
                    "identical": bool(identical),
                    "rows_compared": compared,
                    "rows_json": len(jrows), "rows_frames": len(frows)})
    finally:
        service.close()
    return out


def _batch_arm(pipeline_depth: int, *, host_ms: float, dev_ms: float,
               max_batch: int, worker_steps, items_per_worker: int):
    """One batching arm: synthetic prepare/execute, closed-loop workers,
    throughput per step; the knee is the best sustained step."""
    from parallel_convolution_tpu.serving.batcher import MicroBatcher

    def prepare(lane, items):
        time.sleep(host_ms / 1e3)     # host half: stack/shed/pad
        return {"n": len(items)}

    def execute(lane, items, prepared=None):
        time.sleep(dev_ms / 1e3)      # device half: the dispatch
        for it in items:
            it.slot.set("ok")

    curve = []
    refills = 0
    for workers in worker_steps:
        mb = MicroBatcher(execute, max_batch=max_batch,
                          max_delay_s=0.001, max_queue=256,
                          prepare=prepare, pipeline_depth=pipeline_depth)
        failures = []

        def run():
            for _ in range(items_per_worker):
                slot = None
                for _ in range(2000):           # bounded admission retry
                    slot = mb.try_submit("lane", {"cost_units": 1.0})
                    if slot is not None:
                        break
                    time.sleep(0.0005)
                if slot is None or slot.result(timeout=30.0) != "ok":
                    failures.append(1)

        t0 = time.perf_counter()
        threads = [threading.Thread(target=run, daemon=True)
                   for _ in range(workers)]
        for t in threads:
            t.start()
        for t in threads:
            t.join(60.0)
        wall = time.perf_counter() - t0
        done = workers * items_per_worker - len(failures)
        refills = int(mb.stats["refills"])
        mb.close()
        curve.append({"workers": workers,
                      "items_per_s": round(done / wall, 1) if wall else 0.0,
                      "failures": len(failures)})
    knee = max((p["items_per_s"] for p in curve), default=0.0)
    return {"kind": "batch_ab",
            "mode": "drain" if pipeline_depth == 0 else "refill",
            "pipeline_depth": pipeline_depth,
            "host_ms": host_ms, "dev_ms": dev_ms, "max_batch": max_batch,
            "knee_items_per_s": knee, "refills": refills, "curve": curve}


def main() -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--out", default="evidence/wire_ab.jsonl")
    ap.add_argument("--quick", action="store_true",
                    help="trimmed sweeps (the tier-1 smoke shape)")
    ap.add_argument("--repeat", type=int, default=5,
                    help="codec timing repeats (best-of)")
    ap.add_argument("--rows", type=int, default=96)
    ap.add_argument("--cols", type=int, default=120,
                    help="identity-check image size (odd on purpose: "
                         "exercises the pad-to-bucket path)")
    ap.add_argument("--host-ms", type=float, default=4.0)
    ap.add_argument("--dev-ms", type=float, default=4.0)
    ap.add_argument("--max-batch", type=int, default=4)
    ap.add_argument("--seed", type=int, default=0)
    args = ap.parse_args()

    rows = []
    sides = _SIDES_QUICK if args.quick else _SIDES
    rows += _codec_rows(sides, max(1, args.repeat))
    rows += _identity_rows(args.rows, args.cols, args.seed)
    worker_steps = (1, 4, 8) if args.quick else (1, 2, 4, 8, 16)
    items = 6 if args.quick else 10
    drain = _batch_arm(0, host_ms=args.host_ms, dev_ms=args.dev_ms,
                       max_batch=args.max_batch, worker_steps=worker_steps,
                       items_per_worker=items)
    refill = _batch_arm(1, host_ms=args.host_ms, dev_ms=args.dev_ms,
                        max_batch=args.max_batch, worker_steps=worker_steps,
                        items_per_worker=items)
    rows += [drain, refill]
    ratio = (refill["knee_items_per_s"] / drain["knee_items_per_s"]
             if drain["knee_items_per_s"] else None)
    rows.append({"kind": "batch_ab_summary",
                 "drain_knee": drain["knee_items_per_s"],
                 "refill_knee": refill["knee_items_per_s"],
                 "knee_ratio": round(ratio, 3) if ratio else None,
                 "refill_refills": refill["refills"],
                 "drain_refills": drain["refills"]})

    from pathlib import Path

    p = Path(args.out)
    p.parent.mkdir(parents=True, exist_ok=True)
    stamp = {"ts": round(time.time(), 3), "quick": bool(args.quick)}
    with open(p, "w") as f:
        for r in rows:
            f.write(json.dumps({**r, **stamp}) + "\n")
    for r in rows:
        print(json.dumps(r), flush=True)

    bad_identity = [r for r in rows
                    if r["kind"] == "identity" and not r["identical"]]
    if bad_identity:
        print(f"IDENTITY FAILURE: {bad_identity}", file=sys.stderr)
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())
