#!/usr/bin/env python
"""Closed/open-loop load generator for the convolution service.

Pushes a stream of identical-config requests at either transport —
``--url`` (the HTTP frontend) or ``--in-process`` (no sockets; builds the
service in this process, the tier-1 smoke path) — and emits ONE summary
row in the established bench-row schema: p50/p95/p99 latency,
Gpixels/s, a queue/compile/device/copy phase breakdown (means across
completed requests, from the serving ``PhaseTimer`` export), the
effective backend(s) that actually produced the bytes, and typed
rejection counts.

  # closed loop: --concurrency workers, each issuing back-to-back
  python scripts/loadgen.py --in-process --n 50 --concurrency 4 \\
      --rows 48 --cols 64 --iters 2

  # open loop: fixed arrival rate (req/s), concurrency unbounded-ish
  python scripts/loadgen.py --url http://127.0.0.1:8080 --n 200 --rate 50

Exit status is 0 iff every request either completed or was shed with a
TYPED rejection — a transport error, HTTP 5xx terminal failure, or
byte-size mismatch is a non-rejected failure and exits 1 (the
``run_t1.sh --serving-smoke`` gate).  ``--check`` additionally
byte-compares every completed response against the NumPy oracle.

Round 14: RETRYABLE rejections (``retryable: true`` in the body —
queue_full / resharding / tenant_quota / replica_unavailable) are
honored with capped backoff (the body's ``retry_after_s``, else
exponential) up to ``--shed-retries`` attempts instead of counting as
final outcomes; the summary row reports ``rejected_retried``.  Multiple
``--target`` URLs round-robin the request stream across a raw replica
set, or point one ``--target`` at ``scripts/router.py`` — responses
carrying a ``router`` stamp feed the row's ``failovers_observed``, and
(round 19) their fencing-epoch stamps feed ``router_restarts_observed``
— the count of router restarts/takeovers this client watched happen
while its run kept completing.  Round 24: the durability stamp feeds
``degraded_served`` — completions answered while the router's WAL was
in a degraded-durability window (served correctly, persisted less).

Round 21: ``--shardmap`` makes multiple ``--target`` URLs a SHARDED
control-plane fleet (scripts/router.py --shards N): the client fetches
the version-stamped map from ``GET /v1/shardmap``, routes every request
straight to its key shard's owner, and on a typed ``wrong_shard`` /
``stale_epoch`` reject refreshes the map and retries at the new owner —
a mid-run takeover shows up in ``router_restarts_observed`` and
``shardmap_refreshes``, never as a failure.
"""

from __future__ import annotations

import argparse
import base64
import json
import statistics
import sys
import threading
import time

import _path  # noqa: F401  (repo root + JAX_PLATFORMS re-apply)


def _percentile(sorted_vals: list[float], q: float) -> float | None:
    if not sorted_vals:
        return None
    i = min(len(sorted_vals) - 1, int(round(q * (len(sorted_vals) - 1))))
    return sorted_vals[i]


def poisson_arrivals(rps: float, fire, *, duration_s: float | None = None,
                     n: int | None = None, seed: int = 0):
    """Open-loop POISSON arrival process (the sustained-load harness's
    one arrival loop — ``scale_smoke.py`` drives its curve steps through
    this same function so the two can never drift).

    Spawns ``fire(i)`` on a daemon thread at exponential inter-arrival
    gaps with mean rate ``rps`` (seeded — a rerun offers the same
    process), until ``duration_s`` wall seconds elapse (when given) else
    ``n`` arrivals.  Arrivals ignore completions, so a saturated server
    shows up as latency growth and typed sheds, never a silently
    reduced offered rate.  Returns ``(issued, threads)`` — the caller
    joins the threads on its own timeout.
    """
    import random

    rng = random.Random(seed)
    threads: list[threading.Thread] = []
    t0 = time.perf_counter()
    deadline = t0 + duration_s if duration_s is not None else None
    target = t0
    i = 0
    while True:
        if deadline is None and i >= (n or 0):
            break
        target += rng.expovariate(rps)
        delay = target - time.perf_counter()
        if delay > 0:
            time.sleep(delay)
        if deadline is not None and time.perf_counter() >= deadline:
            break
        th = threading.Thread(target=fire, args=(i,), daemon=True)
        th.start()
        threads.append(th)
        i += 1
    return i, threads


def _frames_profile(body: dict, img) -> tuple[dict, bytes]:
    """Split a JSON request body into ``(header, raw_frame_bytes)`` for
    the binary wire: the tensor (u8 image, or f32 volume on a
    ``mode: "volume"`` body) crosses as a typed frame, everything else
    stays in the envelope's JSON header.  The split is done ONCE per
    profile — per request the (tiny) header is restamped with its
    request_id and re-joined around the same frame bytes
    (``join_envelope``), which is exactly the zero-copy path the wire
    exists for."""
    from parallel_convolution_tpu.serving import frames as frames_mod

    tensor_key = "volume" if "volume_b64" in body else "image"
    header = {k: v for k, v in body.items()
              if k not in ("image_b64", "volume_b64")}
    env = frames_mod.encode_envelope(dict(header), {tensor_key: img})
    fheader, raw = frames_mod.split_envelope(env)
    return fheader, bytes(raw)


def _frames_resp_dict(data: bytes) -> dict:
    """Decode a framed response/row envelope into the JSON-shaped dict
    the summary accounting already understands (the image frame folds
    back into ``image_b64`` so byte checks stay codec-agnostic)."""
    from parallel_convolution_tpu.serving import frames as frames_mod

    header, arrays = frames_mod.decode_envelope(data)
    img = arrays.get("image")
    if img is not None:
        import numpy as np

        header["image_b64"] = base64.b64encode(
            np.ascontiguousarray(img).tobytes()).decode("ascii")
    return header


def _drain_rows(rows) -> dict:
    """Drain a converge NDJSON stream to its FINAL row (or the typed
    rejection), folding the row count in as ``rows_streamed`` — the one
    place the transports' final-row contract lives."""
    last, n = {"ok": False, "detail": "empty stream"}, 0
    for r in rows:
        last, n = r, n + 1
    last["rows_streamed"] = n
    return last


class _HTTPTransport:
    def __init__(self, url: str, timeout: float):
        self.base = url.rstrip("/")
        self.timeout = timeout

    def request(self, body: dict) -> tuple[int, dict]:
        import urllib.error
        import urllib.request

        data = json.dumps(body).encode()
        req = urllib.request.Request(
            f"{self.base}/v1/convolve", data=data,
            headers={"Content-Type": "application/json"})
        try:
            with urllib.request.urlopen(req, timeout=self.timeout) as resp:
                return resp.status, json.loads(resp.read())
        except urllib.error.HTTPError as e:
            try:
                return e.code, json.loads(e.read())
            except Exception:  # noqa: BLE001
                return e.code, {"ok": False, "detail": f"http {e.code}"}

    def converge(self, body: dict) -> tuple[int, dict]:
        """One progressive convergence job: POST /v1/converge, drain the
        NDJSON stream, return the FINAL row (or the typed rejection)
        with the snapshot count folded in as ``rows_streamed``."""
        import urllib.error
        import urllib.request

        data = json.dumps(body).encode()
        req = urllib.request.Request(
            f"{self.base}/v1/converge", data=data,
            headers={"Content-Type": "application/json"})
        try:
            with urllib.request.urlopen(req, timeout=self.timeout) as resp:
                return resp.status, _drain_rows(
                    json.loads(line) for line in resp if line.strip())
        except urllib.error.HTTPError as e:
            try:
                return e.code, json.loads(e.read())
            except Exception:  # noqa: BLE001
                return e.code, {"ok": False, "detail": f"http {e.code}"}
        except (OSError, ValueError) as e:
            # The stream broke (or corrupted) mid-drain: a typed
            # RETRYABLE outcome, not a client crash — the retry loop
            # re-submits the job and a durable router resumes it from
            # its ledger token instead of iteration 0.
            return 200, {"ok": False, "kind": "rejected",
                         "rejected": "replica_unavailable",
                         "retryable": True,
                         "detail": f"stream broke: {e}"[:300]}

    def request_frames(self, raw: bytes) -> tuple[int, dict]:
        """Binary-wire convolve: envelope bytes up, framed response
        decoded back into the JSON-shaped summary dict."""
        import urllib.error
        import urllib.request

        from parallel_convolution_tpu.serving import frames as frames_mod

        req = urllib.request.Request(
            f"{self.base}/v1/convolve", data=raw,
            headers={"Content-Type": frames_mod.FRAMES_CONTENT_TYPE})
        try:
            with urllib.request.urlopen(req, timeout=self.timeout) as resp:
                return resp.status, _frames_resp_dict(resp.read())
        except urllib.error.HTTPError as e:
            try:
                return e.code, _frames_resp_dict(e.read())
            except Exception:  # noqa: BLE001
                return e.code, {"ok": False, "detail": f"http {e.code}"}

    def converge_frames(self, raw: bytes) -> tuple[int, dict]:
        """Binary-wire converge: drain the length-prefixed framed row
        stream to its final row (the frames twin of :meth:`converge`)."""
        import urllib.error
        import urllib.request

        from parallel_convolution_tpu.serving import frames as frames_mod
        from parallel_convolution_tpu.serving.frontend import (
            iter_framed_rows,
        )

        req = urllib.request.Request(
            f"{self.base}/v1/converge", data=raw,
            headers={"Content-Type": frames_mod.FRAMES_CONTENT_TYPE})
        try:
            with urllib.request.urlopen(req, timeout=self.timeout) as resp:
                return resp.status, _drain_rows(
                    _frames_resp_dict(r) for r in iter_framed_rows(resp))
        except urllib.error.HTTPError as e:
            try:
                return e.code, _frames_resp_dict(e.read())
            except Exception:  # noqa: BLE001
                return e.code, {"ok": False, "detail": f"http {e.code}"}
        except (OSError, ValueError) as e:
            # Same retryable shape as the JSON stream-break path.
            return 200, {"ok": False, "kind": "rejected",
                         "rejected": "replica_unavailable",
                         "retryable": True,
                         "detail": f"stream broke: {e}"[:300]}

    def snapshot(self) -> dict:
        import urllib.request

        with urllib.request.urlopen(f"{self.base}/stats",
                                    timeout=self.timeout) as resp:
            return json.loads(resp.read())


class _ShardedTransport:
    """The shard-aware client half over HTTP (round 21; the in-process
    twin is ``serving.peers.ShardClient``): fetch the version-stamped
    ownership map from any fleet member, compute each request's shard
    from its route key, dial the owner directly, and treat a typed
    ``wrong_shard``/``stale_epoch`` reject as "my map is stale" —
    refresh and retry at the new owner, bounded."""

    _REROUTE = ("wrong_shard", "stale_epoch")

    def __init__(self, urls: list[str], timeout: float,
                 max_redirects: int = 4):
        self._by_addr = {u.rstrip("/"): _HTTPTransport(u, timeout)
                         for u in urls}
        self.timeout = timeout
        self.max_redirects = max_redirects
        self._lock = threading.Lock()
        self._map: dict = {"version": -1, "n_shards": 1, "shards": {}}
        self.refreshes = 0
        self.refresh()

    def refresh(self) -> dict:
        import urllib.request

        last: Exception | None = None
        for base in list(self._by_addr):
            try:
                with urllib.request.urlopen(
                        base + "/v1/shardmap",
                        timeout=self.timeout) as r:
                    smw = json.loads(r.read())
            except Exception as e:  # noqa: BLE001 — try the next member
                last = e
                continue
            with self._lock:
                if smw.get("version", -1) >= self._map.get("version",
                                                           -1):
                    self._map = smw
                self.refreshes += 1
                return dict(self._map)
        raise ConnectionError(
            f"no fleet member answered /v1/shardmap: {last!r}")

    def _transport_for(self, body: dict):
        from parallel_convolution_tpu.serving.peers import shard_of
        from parallel_convolution_tpu.serving.router import route_key

        with self._lock:
            smw = self._map
        shard = shard_of(route_key(dict(body)),
                         smw.get("n_shards", 1) or 1)
        ent = (smw.get("shards") or {}).get(shard) or {}
        addr = (ent.get("addr") or "").rstrip("/")
        with self._lock:
            tr = self._by_addr.get(addr)
            if tr is None and addr:
                # A takeover can publish an owner addr we were never
                # given on the CLI — dial it anyway.
                tr = self._by_addr.setdefault(
                    addr, _HTTPTransport(addr, self.timeout))
        if tr is None:
            tr = next(iter(self._by_addr.values()))
        return tr

    def _call(self, method: str, body: dict):
        status, wire = -1, {"ok": False, "detail": "no attempt"}
        for _ in range(self.max_redirects):
            tr = self._transport_for(body)
            try:
                status, wire = getattr(tr, method)(body)
            except Exception as e:  # noqa: BLE001 — owner unreachable
                # A dead owner is indistinguishable from a stale map:
                # re-fetch from the survivors and retry at whoever the
                # takeover elected.  If it never converges, hand the
                # outer loop a typed RETRYABLE outcome (the same shape
                # the broken-stream path uses) so its capped backoff
                # spans the takeover window.
                status = -1
                wire = {"ok": False, "kind": "rejected",
                        "rejected": "replica_unavailable",
                        "retryable": True,
                        "detail": f"owner unreachable: {e!r}"[:300]}
                try:
                    self.refresh()
                except ConnectionError:
                    pass
                time.sleep(0.05)
                continue
            if (isinstance(wire, dict)
                    and wire.get("rejected") in self._REROUTE):
                # Ownership moved underneath us (redirect or fenced
                # takeover): stale map, not a failed request.
                try:
                    self.refresh()
                except ConnectionError:
                    pass
                continue
            return status, wire
        return status, wire

    def request(self, body: dict):
        return self._call("request", body)

    def converge(self, body: dict):
        return self._call("converge", body)

    def snapshot(self) -> dict:
        return next(iter(self._by_addr.values())).snapshot()


def main() -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    tgt = ap.add_mutually_exclusive_group(required=True)
    tgt.add_argument("--url", default=None,
                     help="HTTP frontend base URL (scripts/serve.py); "
                          "alias for a single --target")
    tgt.add_argument("--target", action="append", default=None,
                     metavar="URL",
                     help="HTTP target base URL (repeatable: requests "
                          "round-robin across a raw replica set, or give "
                          "one router URL)")
    tgt.add_argument("--in-process", action="store_true",
                     help="build the service in this process (no sockets)")
    ap.add_argument("--shardmap", action="store_true",
                    help="treat the --target URLs as a SHARDED router "
                         "fleet (scripts/router.py --shards N): fetch "
                         "GET /v1/shardmap, route each request to its "
                         "key shard's owner, and refresh-and-retry on "
                         "typed wrong_shard / stale_epoch rejects")
    ap.add_argument("--n", type=int, default=50, help="total requests")
    ap.add_argument("--concurrency", type=int, default=4,
                    help="closed-loop worker count (ignored with --rate)")
    ap.add_argument("--rate", type=float, default=None, metavar="RPS",
                    help="open loop: fixed arrival rate in requests/sec")
    ap.add_argument("--rps", type=float, default=None, metavar="RPS",
                    help="open loop with POISSON arrivals at this mean "
                         "rate (exponential inter-arrival gaps — the "
                         "sustained-load harness; pair with "
                         "--duration-s, which then overrides --n)")
    ap.add_argument("--duration-s", type=float, default=None,
                    metavar="SEC",
                    help="run for this long instead of a fixed --n "
                         "(--rps only); the summary row stamps offered "
                         "vs achieved RPS")
    ap.add_argument("--rows", type=int, default=48)
    ap.add_argument("--cols", type=int, default=64)
    ap.add_argument("--mode", default="grey", choices=["grey", "rgb"])
    ap.add_argument("--volume", default=None, metavar="DxHxW",
                    help="rank-3 volume body mode: each request carries "
                         "one seeded (2, D, H, W) float32 volume "
                         "(mode: \"volume\" on the wire) instead of a "
                         "u8 image — pair with a rank-3 --filter "
                         "(fd7/fd25/wave/grayscott); overrides "
                         "--rows/--cols/--mode and excludes "
                         "--mixed-sizes/--zipf/--check")
    ap.add_argument("--filter", default="blur3", dest="filter_name")
    ap.add_argument("--iters", type=int, default=2)
    ap.add_argument("--backend", default="shifted")
    ap.add_argument("--storage", default="f32")
    ap.add_argument("--fuse", type=int, default=1)
    ap.add_argument("--boundary", default="zero")
    ap.add_argument("--converge", type=float, default=None, metavar="TOL",
                    help="drive /v1/converge instead of /v1/convolve: "
                         "each request is one progressive convergence "
                         "job streamed to its final row (--iters is "
                         "ignored; see --max-iters/--solver)")
    ap.add_argument("--max-iters", type=int, default=2000,
                    help="convergence work budget per job (fine-grid "
                         "work units; --converge only)")
    ap.add_argument("--check-every", type=int, default=10,
                    help="snapshot cadence in iterations (--converge "
                         "with the jacobi solver; multigrid streams one "
                         "row per V-cycle)")
    ap.add_argument("--solver", default="jacobi",
                    choices=["jacobi", "multigrid"],
                    help="convergence strategy (--converge only)")
    ap.add_argument("--mg-levels", type=int, default=None,
                    help="multigrid level-count cap (--converge only)")
    ap.add_argument("--wire", default="json",
                    choices=["json", "frames", "mixed"],
                    help="wire codec: 'json' (base64-in-JSON, the "
                         "control arm), 'frames' (the binary tensor-"
                         "frame envelope), or 'mixed' (alternate arms "
                         "per request — the A/B shape)")
    ap.add_argument("--mixed-sizes", action="store_true",
                    help="interleave the --rows/--cols thumbnail with "
                         "full 1920x2520 frames — the mixed-size "
                         "workload the shape-bucketed batcher lanes "
                         "exist for")
    ap.add_argument("--zipf", type=float, default=None, metavar="S",
                    help="duplicate-heavy traffic: draw each request's "
                         "image from a --pool of distinct seeded images "
                         "with Zipf(S)-ranked probabilities (S=0 is "
                         "uniform-unique-ish, S>=1.1 is the classic "
                         "duplicate-heavy head) — deterministic per "
                         "(seed, index), so a rerun offers the same "
                         "stream; the summary row reports the served "
                         "cache hit rate")
    ap.add_argument("--pool", type=int, default=16,
                    help="distinct images in the --zipf pool")
    ap.add_argument("--cache", action="store_true",
                    help="enable the content-addressed result cache on "
                         "the in-process service (no-op with --url: the "
                         "server's own --cache flag decides)")
    ap.add_argument("--deadline-ms", type=float, default=None,
                    help="per-request latency budget (missed -> typed shed)")
    ap.add_argument("--tenant", default=None,
                    help="tenant identity stamped into every request "
                         "(the router's QoS key)")
    ap.add_argument("--shed-retries", type=int, default=4,
                    help="max capped-backoff retries of a RETRYABLE "
                         "rejection before accepting it as the outcome")
    ap.add_argument("--backoff-cap-s", type=float, default=1.0,
                    help="ceiling on one shed-retry backoff sleep")
    ap.add_argument("--timeout", type=float, default=120.0,
                    help="client-side wait per request")
    ap.add_argument("--seed", type=int, default=0, help="image seed")
    ap.add_argument("--check", action="store_true",
                    help="byte-compare completed responses vs the oracle")
    ap.add_argument("--out", default=None,
                    help="also write the summary row JSON to this path")
    ap.add_argument("--trace-out", default=None, metavar="JSONL",
                    help="per-request JSONL trace (request_id, server "
                         "trace_id, latency, phases, outcome) — tail-"
                         "latency spikes become attributable to a specific "
                         "request/phase, and the trace_id joins each row "
                         "to the server-side span tree "
                         "(obs_report.py --client-trace)")
    # In-process service knobs (no-ops with --url):
    ap.add_argument("--mesh", default=None, help="RxC (in-process only)")
    ap.add_argument("--max-batch", type=int, default=8)
    ap.add_argument("--max-delay-ms", type=float, default=5.0)
    ap.add_argument("--max-queue", type=int, default=256)
    ap.add_argument("--warm", action="store_true",
                    help="pre-compile the config before the timed run "
                         "(in-process; separates compile from steady-state)")
    args = ap.parse_args()

    import numpy as np

    from parallel_convolution_tpu.utils import imageio

    vol_shape = None
    if args.volume is not None:
        try:
            vol_shape = tuple(int(v) for v in args.volume.split("x"))
            if len(vol_shape) != 3 or min(vol_shape) < 1:
                raise ValueError
        except ValueError:
            ap.error(f"--volume must be DxHxW positive ints, got "
                     f"{args.volume!r}")
        for flag, name in ((args.mixed_sizes, "--mixed-sizes"),
                           (args.zipf is not None, "--zipf"),
                           (args.check, "--check"),
                           (args.warm, "--warm")):
            if flag:
                ap.error(f"--volume and {name} are exclusive (volumes "
                         "are single-profile f32 bodies)")
    if vol_shape is not None:
        # Bounded [0, 1] fields: safe for every rank-3 form including
        # Gray-Scott's cubic uvv term (unbounded data diverges).
        D, H, W = vol_shape
        rng = np.random.default_rng(args.seed)
        img = np.ascontiguousarray(
            rng.random((2, D, H, W), dtype=np.float32))
        args.rows, args.cols = H, W
        body = {
            "volume_b64": base64.b64encode(img.tobytes()).decode("ascii"),
            "rows": H, "cols": W, "depth": D, "mode": "volume",
            "filter": args.filter_name, "iters": args.iters,
            "backend": args.backend,
            "fuse": args.fuse, "boundary": args.boundary,
        }
    else:
        img = imageio.generate_test_image(args.rows, args.cols, args.mode,
                                          seed=args.seed)
        body = {
            "image_b64": base64.b64encode(
                np.ascontiguousarray(img).tobytes()).decode("ascii"),
            "rows": args.rows, "cols": args.cols, "mode": args.mode,
            "filter": args.filter_name, "iters": args.iters,
            "backend": args.backend, "storage": args.storage,
            "fuse": args.fuse, "boundary": args.boundary,
        }
    if args.deadline_ms is not None:
        body["deadline_ms"] = args.deadline_ms
    if args.tenant:
        body["tenant"] = args.tenant
    if args.converge is not None:
        # Convergence-job wire shape: tol/max_iters/check_every replace
        # iters/deadline; float carries (quantize=False) are the
        # converge default and multigrid's requirement.
        body.pop("iters", None)
        body.pop("deadline_ms", None)
        body.update(tol=args.converge, max_iters=args.max_iters,
                    check_every=args.check_every, quantize=False,
                    solver=args.solver)
        if args.mg_levels is not None:
            body["mg_levels"] = args.mg_levels

    # Request profiles: one fixed config, or (--mixed-sizes) the
    # thumbnail interleaved with a full 1920x2520 frame — near-miss
    # shapes that land in DIFFERENT batcher lanes, the continuous-
    # batching stress shape.  Requests round-robin profiles by index.
    profiles = [(body, img)]
    if args.mixed_sizes:
        big_img = imageio.generate_test_image(1920, 2520, args.mode,
                                              seed=args.seed + 1)
        profiles.append((dict(body, rows=1920, cols=2520,
                              image_b64=base64.b64encode(
                                  np.ascontiguousarray(big_img).tobytes()
                              ).decode("ascii")), big_img))
    if args.zipf is not None and args.mixed_sizes:
        ap.error("--zipf and --mixed-sizes are exclusive (the zipf pool "
                 "is same-shape by design: it isolates content "
                 "duplication from lane mixing)")
    if args.zipf is not None:
        # The duplicate-heavy head: a pool of DISTINCT same-config
        # images, request i drawing pool rank r with probability
        # ∝ 1/r^S — real traffic's shape, and the result cache's
        # reason to exist.  Selection is deterministic per (seed, i):
        # a rerun offers byte-identical traffic.
        import random

        for k in range(1, max(1, args.pool)):
            pimg = imageio.generate_test_image(
                args.rows, args.cols, args.mode, seed=args.seed + k)
            profiles.append((dict(body, image_b64=base64.b64encode(
                np.ascontiguousarray(pimg).tobytes()).decode("ascii")),
                pimg))
        _zw = [1.0 / (r ** args.zipf)
               for r in range(1, len(profiles) + 1)]
        _zcum = []
        _acc = 0.0
        for w in _zw:
            _acc += w
            _zcum.append(_acc)

        def pick(i: int) -> int:
            rng = random.Random((args.seed << 24) ^ (1000003 * (i + 1)))
            return rng.choices(range(len(profiles)),
                               cum_weights=_zcum)[0]
    else:
        def pick(i: int) -> int:
            return i % len(profiles)
    # Binary-wire profiles: header/frames split once, request_id
    # restamped per request around the SAME frame bytes.
    fprofiles = ([_frames_profile(b, im) for b, im in profiles]
                 if args.wire != "json" else [])

    targets = args.target or ([args.url] if args.url else None)
    if args.shardmap and not targets:
        ap.error("--shardmap needs HTTP --target fleet members")
    service = None
    if args.in_process:
        from parallel_convolution_tpu.obs import events as obs_events
        from parallel_convolution_tpu.resilience import diskio, faults
        from parallel_convolution_tpu.serving.frontend import InProcessClient
        from parallel_convolution_tpu.serving.service import (
            ConvolutionService,
        )

        faults.install_from_env()
        diskio.install_from_env()   # PCTPU_DISK_MODES: disk fault shapes
        obs_events.install_from_env()  # PCTPU_OBS_EVENTS: leave a timeline
        mesh = None
        if args.mesh:
            from parallel_convolution_tpu.parallel.mesh import mesh_from_spec

            mesh = mesh_from_spec(args.mesh)
        cache = None
        if args.cache:
            from parallel_convolution_tpu.serving.cache import ResultCache

            cache = ResultCache()
        service = ConvolutionService(
            mesh, max_batch=args.max_batch,
            max_delay_s=args.max_delay_ms / 1e3, max_queue=args.max_queue,
            cache=cache)
        client = InProcessClient(service)
        if args.converge is not None:
            def _converge_inproc(b):
                status, rows = client.converge(b, timeout=args.timeout)
                return status, _drain_rows(rows)

            def _converge_frames_inproc(raw):
                status, rows = client.converge_frames(
                    raw, timeout=args.timeout)
                return status, _drain_rows(
                    _frames_resp_dict(r) for r in rows)

            transports = [_converge_inproc]
            ftransports = [_converge_frames_inproc]
        else:
            def _request_frames_inproc(raw):
                status, data = client.request_frames(
                    raw, timeout=args.timeout)
                return status, _frames_resp_dict(data)

            transports = [lambda b: client.request(b, timeout=args.timeout)]
            ftransports = [_request_frames_inproc]
        transport_snapshot = service.snapshot
    elif args.shardmap:
        if args.wire != "json":
            ap.error("--shardmap routes on the JSON route key; use "
                     "--wire json")
        sharded = _ShardedTransport(targets, args.timeout)
        transports = [sharded.converge if args.converge is not None
                      else sharded.request]
        ftransports = []
        transport_snapshot = sharded.snapshot
    else:
        https = [_HTTPTransport(url, args.timeout) for url in targets]
        transports = [(h.converge if args.converge is not None
                       else h.request) for h in https]
        ftransports = [(h.converge_frames if args.converge is not None
                        else h.request_frames) for h in https]
        transport_snapshot = https[0].snapshot

    if args.warm and service is not None:
        service.warmup([{"rows": b["rows"], "cols": b["cols"],
                         "mode": args.mode, "filter": args.filter_name,
                         "iters": args.iters, "backend": args.backend,
                         "storage": args.storage, "fuse": args.fuse,
                         "boundary": args.boundary}
                        for b, _ in profiles])

    wants = None
    if args.check and args.converge is not None:
        ap.error("--check byte-compares the fixed-count oracle; it does "
                 "not apply to --converge jobs")
    if args.check and args.mixed_sizes:
        ap.error("--check byte-compares the single fixed-size oracle; "
                 "use scripts/wire_ab.py for mixed-size identity proof")
    if args.check:
        from parallel_convolution_tpu.ops import oracle
        from parallel_convolution_tpu.ops.filters import get_filter

        # One oracle per profile image: a --zipf run byte-checks every
        # pool member, so a cache HIT serving stale/wrong bytes can
        # never pass (the hit-vs-miss byte-identity gate).
        filt = get_filter(args.filter_name)
        wants = [oracle.run_serial_u8(im, filt, args.iters,
                                      boundary=args.boundary).tobytes()
                 for _, im in profiles]

    results = []                      # (index, latency_s, status, resp)
    results_lock = threading.Lock()
    retried = [0]                     # capped-backoff shed retries issued

    def one_request(i: int) -> None:
        # Round-robin across targets AND profiles; request_id is stable
        # across shed retries ON PURPOSE (it is the idempotency key — a
        # retry that races a late completion dedups at the replica).
        # --wire mixed alternates codec arms on a stride DECOUPLED from
        # the profile stride, so each size sees both codecs.
        pbody, _ = profiles[pick(i)]
        framed = (args.wire == "frames"
                  or (args.wire == "mixed"
                      and (i // len(profiles)) % 2 == 1))
        if framed:
            from parallel_convolution_tpu.serving import (
                frames as frames_mod,
            )

            fheader, fraw = fprofiles[pick(i)]
            request = ftransports[i % len(ftransports)]
            b = frames_mod.join_envelope(
                {**fheader, "request_id": f"lg{i}"}, fraw)
        else:
            request = transports[i % len(transports)]
            b = dict(pbody, request_id=f"lg{i}")
        t0 = time.perf_counter()
        ts = time.time()
        attempt = 0
        while True:
            try:
                status, resp = request(b)
            except Exception as e:  # noqa: BLE001 — a transport failure row
                status, resp = -1, {"ok": False, "detail": repr(e)[:300]}
            retryable = (not resp.get("ok") and resp.get("retryable")
                         and resp.get("rejected") != "timeout")
            if not retryable or attempt >= args.shed_retries:
                break
            # Honor the server's back-off hint, capped; else exponential.
            attempt += 1
            with results_lock:
                retried[0] += 1
            hint = resp.get("retry_after_s")
            delay = (float(hint) if hint is not None
                     else 0.05 * 2 ** (attempt - 1))
            time.sleep(min(delay, args.backoff_cap_s))
        lat = time.perf_counter() - t0
        with results_lock:
            results.append((i, ts, lat, status, resp))

    if args.rps and args.rate:
        ap.error("--rps (Poisson) and --rate (fixed clock) are exclusive")
    if args.duration_s and not args.rps:
        ap.error("--duration-s needs --rps")

    n_issued = args.n
    t_start = time.perf_counter()
    if args.rps:
        # Open loop, POISSON arrivals (see poisson_arrivals):
        # --duration-s bounds the run by wall time (the sustained-load
        # harness shape), else --n bounds it by count.
        n_issued, threads = poisson_arrivals(
            args.rps, one_request, duration_s=args.duration_s,
            n=None if args.duration_s else args.n, seed=args.seed)
        for th in threads:
            th.join(args.timeout)
    elif args.rate:
        # Open loop: arrivals on a fixed clock regardless of completions —
        # each request gets its own thread so a slow server shows up as
        # latency (and eventually typed queue_full sheds), not as a
        # silently reduced offered rate.
        threads = []
        interval = 1.0 / args.rate
        for i in range(args.n):
            target = t_start + i * interval
            delay = target - time.perf_counter()
            if delay > 0:
                time.sleep(delay)
            th = threading.Thread(target=one_request, args=(i,), daemon=True)
            th.start()
            threads.append(th)
        for th in threads:
            th.join(args.timeout)
    else:
        # Closed loop: --concurrency workers, each back-to-back.
        counter = iter(range(args.n))
        counter_lock = threading.Lock()

        def worker():
            while True:
                with counter_lock:
                    i = next(counter, None)
                if i is None:
                    return
                one_request(i)

        threads = [threading.Thread(target=worker, daemon=True)
                   for _ in range(max(1, args.concurrency))]
        for th in threads:
            th.start()
        for th in threads:
            th.join()
    wall = time.perf_counter() - t_start

    if args.trace_out:
        # The per-request timeline: one JSONL line per issued request, in
        # issue order — a p99 spike is now a grep, not a guess.
        from pathlib import Path

        tp = Path(args.trace_out)
        tp.parent.mkdir(parents=True, exist_ok=True)
        with open(tp, "w") as f:
            for i, ts, lat, s, r in sorted(results):
                line = {
                    "request_id": r.get("request_id") or f"lg{i}",
                    # The SERVER-assigned trace id (round 13): joins this
                    # client-side record to the server-side span tree in
                    # the event log — obs_report.py --client-trace does
                    # the merge offline.
                    "trace_id": r.get("trace_id", ""),
                    "ts": round(ts, 6),
                    "latency_ms": round(1e3 * lat, 3),
                    "status": s,
                    "ok": bool(r.get("ok")),
                }
                if r.get("ok"):
                    line.update(
                        effective_backend=r.get("effective_backend", ""),
                        effective_grid=r.get("effective_grid", ""),
                        batch_size=r.get("batch_size"),
                        plan_source=r.get("plan_source", ""),
                        phases=r.get("phases", {}),
                        # The result-cache stamp every served body
                        # carries (hit|miss|off + input digest).
                        cache=r.get("cache", ""),
                        digest=(r.get("digest") or "")[:16],
                    )
                else:
                    line.update(rejected=r.get("rejected"),
                                detail=(r.get("detail") or "")[:200])
                f.write(json.dumps(line) + "\n")

    completed = [(lat, r) for _, _, lat, s, r in results
                 if s == 200 and r.get("ok")]
    rejected: dict[str, int] = {}
    failures = []
    for _, _, lat, s, r in results:
        if s == 200 and r.get("ok"):
            continue
        reason = r.get("rejected")
        if reason and reason != "timeout":
            rejected[reason] = rejected.get(reason, 0) + 1
        else:
            # No typed reason — or "timeout", the client giving up on an
            # unresponsive service, which is a failure, not load shedding.
            failures.append({"status": s,
                             "detail": r.get("detail", "") or reason or ""})
    channels = 3 if args.mode == "rgb" else 1
    # Per-profile pixel areas: mixed-size runs account each completion
    # at ITS profile's size (selection is deterministic by index).
    # Volume bodies account CELLS (2 fields x D x H x W) and their
    # responses carry f32 (4 bytes/cell), not u8.
    if vol_shape is not None:
        channels = 2 * vol_shape[0]
        elem_bytes = 4
    else:
        elem_bytes = 1
    area_of = [b["rows"] * b["cols"] for b, _ in profiles]
    ok_rows = [(i, r) for i, _, _, s, r in results
               if s == 200 and r.get("ok")]
    mismatches = 0
    if wants is not None:
        for i, r in ok_rows:
            if base64.b64decode(r["image_b64"]) != wants[pick(i)]:
                mismatches += 1
    bad_bytes = sum(
        1 for i, r in ok_rows
        if len(base64.b64decode(r["image_b64"]))
        != area_of[pick(i)] * channels * elem_bytes)
    non_rejected_failures = len(failures) + mismatches + bad_bytes

    lats = sorted(lat for lat, _ in completed)
    if args.converge is not None:
        # Convergence jobs: pixels iterated = the solver-comparable
        # fine-grid work units each final row stamps (iterations for
        # jacobi, the pixel-weighted per-level sum for multigrid).
        px = int(channels * sum(
            area_of[pick(i)] * r.get("work_units", 0.0)
            for i, r in ok_rows))
    else:
        px = channels * args.iters * sum(
            area_of[pick(i)] for i, _ in ok_rows)
    phase_names = ("queue", "compile", "device", "copy_in", "copy_out")
    phases_ms = {
        p: round(1e3 * statistics.mean(
            [r.get("phases", {}).get(p, 0.0) for _, r in completed]), 3)
        for p in phase_names
    } if completed and args.converge is None else {}
    effective = sorted({r.get("effective_backend", "") for _, r in completed})
    grids = sorted({r.get("effective_grid", "") for _, r in completed})
    batch_sizes = [r.get("batch_size", 1) for _, r in completed]
    plan_keys = sorted({r.get("plan_key", "") for _, r in completed} - {""})
    # Router-stamped responses make failovers CLIENT-observable: count
    # requests that completed OFF their consistent-hash home (spilled
    # past a dead/unready replica) or after a failed dispatch.
    failovers_observed = sum(
        1 for _, r in completed
        if r.get("router", {}).get("failovers", 0) > 0
        or (r.get("router", {}).get("replica")
            and r.get("router", {}).get("home")
            and r["router"]["replica"] != r["router"]["home"]))
    replicas_seen = sorted({r.get("router", {}).get("replica", "")
                            for _, r in completed} - {""})
    # Round 24: the router stamps its durability mode on every
    # response.  Completions served while the WAL was in its degraded
    # window are still correct answers — but the client can now COUNT
    # how many of its requests rode on reduced durability, so a smoke
    # can assert both "kept serving" and "window actually closed".
    degraded_served = sum(
        1 for _, r in completed
        if r.get("router", {}).get("durability") == "degraded")
    # Round 21: which control-plane shards served this client's keys —
    # plus how often the shard map had to be re-fetched mid-run (>1
    # means a redirect/takeover was observed and absorbed).
    shards_seen = sorted({r.get("router", {}).get("shard", "")
                          for _, r in completed} - {""})
    # Round 19: the router stamps its fencing epoch on every response;
    # an epoch CHANGE mid-run means the control plane restarted (or a
    # standby took over) underneath this client — and the run kept
    # completing anyway.  distinct-epochs-minus-one is the restart
    # count this client can prove.
    epochs_seen = sorted({r.get("router", {}).get("epoch")
                          for _, r in completed} - {None, 0})

    # Which codec arm(s) the SERVER says actually answered — the
    # client-observable proof the negotiated wire was honored.
    wires_seen = sorted({r.get("wire", "") for _, r in completed} - {""})
    row = {
        "workload": (f"serve {args.filter_name} "
                     + (f"volume {args.volume}" if vol_shape is not None
                        else f"{args.rows}x{args.cols}"
                        + ("+1920x2520" if args.mixed_sizes else "")
                        + f"x{channels}")
                     + " "
                     + (f"converge tol={args.converge}"
                        if args.converge is not None
                        else f"{args.iters} iters")
                     + (f" zipf={args.zipf}" if args.zipf is not None
                        else "")),
        **({"rank": 3} if vol_shape is not None else {}),
        "wire": args.wire,
        **({"wires_seen": wires_seen} if wires_seen else {}),
        "loop": ("open-poisson" if args.rps
                 else ("open" if args.rate else "closed")),
        "n": n_issued,
        **({"offered_rps": args.rps,
            # The arrival process actually realized (scheduling jitter
            # can under-deliver on a loaded host) vs the completion
            # throughput the service sustained — the load-curve row
            # states all three, so "the server kept up" is checkable.
            "issued_rps": (round(n_issued / wall, 3) if wall else None),
            "achieved_rps": (round(len(completed) / wall, 3)
                             if wall else None),
            **({"duration_s": args.duration_s}
               if args.duration_s else {})}
           if args.rps
           else ({"rate_rps": args.rate} if args.rate
                 else {"concurrency": args.concurrency})),
        "backend": args.backend,
        "effective_backend": (effective[0] if len(effective) == 1
                              else effective),
        "effective_grid": grids[0] if len(grids) == 1 else grids,
        # The tuning identity of the served config (perf_gate.py's
        # history key; a list only if mixed keys were somehow served).
        "plan_key": (plan_keys[0] if len(plan_keys) == 1
                     else (plan_keys or "")),
        "completed": len(completed),
        "rejected": rejected,
        "rejected_retried": retried[0],
        "failovers_observed": failovers_observed,
        **({"degraded_served": degraded_served}
           if degraded_served else {}),
        **({"replicas_seen": replicas_seen} if replicas_seen else {}),
        **({"shards_seen": shards_seen} if shards_seen else {}),
        **({"shardmap_refreshes": sharded.refreshes}
           if args.shardmap else {}),
        **({"router_restarts_observed": len(epochs_seen) - 1,
            "router_epochs_seen": epochs_seen} if epochs_seen else {}),
        "non_rejected_failures": non_rejected_failures,
        "wall_s": round(wall, 4),
        "p50_ms": round(1e3 * _percentile(lats, 0.50), 3) if lats else None,
        "p95_ms": round(1e3 * _percentile(lats, 0.95), 3) if lats else None,
        "p99_ms": round(1e3 * _percentile(lats, 0.99), 3) if lats else None,
        "gpixels_per_s": round(px / wall / 1e9, 6) if wall else None,
        "phases_ms": phases_ms,
        "batch_mean": (round(statistics.mean(batch_sizes), 2)
                       if batch_sizes else None),
        "batch_max": max(batch_sizes, default=None),
    }
    # Result-cache accounting (every served body stamps cache: hit|miss
    # when the server runs cached; the hit-rate-vs-skew curve and the
    # perf_gate cache lane read these).
    cache_stamps = {r.get("cache", "") for _, r in completed} - {"", "off"}
    if cache_stamps or args.zipf is not None:
        hits = sum(1 for _, r in completed if r.get("cache") == "hit")
        row["cache_hits"] = hits
        row["cache_misses"] = sum(1 for _, r in completed
                                  if r.get("cache") == "miss")
        row["cache_hit_rate"] = (round(hits / len(completed), 4)
                                 if completed else None)
    if args.zipf is not None:
        row["zipf_s"] = args.zipf
        row["pool"] = len(profiles)
    if args.converge is not None:
        # Solver-shaped convergence accounting (r15), stamped from the
        # final rows the SERVER streamed (post-resolution — mg_levels is
        # the planner's actual schedule, work_units the solver's own
        # bill), never from the request knobs.
        solvers = sorted({r.get("solver", "") for _, r in completed} - {""})
        levels = sorted({r.get("mg_levels") for _, r in completed}
                        - {None})
        wus = sorted(r.get("work_units", 0.0) for _, r in completed)
        # Always a scalar string: perf_gate.row_key interpolates this
        # into the history identity, and a list repr would mint a key no
        # future run ever matches.  A genuinely mixed run gets a stable
        # "a+b" key distinct from either solver's own history.
        row["solver"] = (solvers[0] if len(solvers) == 1
                         else ("+".join(solvers) if solvers
                               else args.solver))
        row["mg_levels"] = (levels[0] if len(levels) == 1
                            else (levels or None))
        row["work_units_to_tol"] = _percentile(wus, 0.50)
        row["tol"] = args.converge
        row["converged"] = sum(1 for _, r in completed
                               if r.get("converged"))
        row["rows_streamed_mean"] = (round(statistics.mean(
            [r.get("rows_streamed", 0) for _, r in completed]), 1)
            if completed else None)
        # Durable-job visibility (round 18): final rows whose router
        # stamp says the job resumed on a surviving replica mid-stream
        # — the client-observable proof that device-seconds already
        # spent were NOT re-run from iteration 0.
        row["resumes_observed"] = sum(
            1 for _, r in completed
            if r.get("router", {}).get("resume_count", 0) > 0)
    if wants is not None:
        row["oracle_mismatches"] = mismatches
    try:
        snap = transport_snapshot()
        row["platform"] = snap.get("platform", "")
        row["mesh"] = snap.get("mesh", "")
        row["engine"] = snap.get("engine", {})
        row["service"] = snap.get("service", {})
        # Topology identity (ROADMAP item 1's keying, pulled forward):
        # the SERVER's hosts/slice layout when it reports one, else this
        # process's own — perf_gate keys multi-host rows separately.
        row["hosts"] = snap.get("hosts")
        row["slice_topology"] = snap.get("slice_topology")
    except Exception as e:  # noqa: BLE001 — the row survives a dead /stats
        row["snapshot_error"] = repr(e)[:200]
    if not row.get("hosts"):
        from parallel_convolution_tpu.utils.platform import topology

        row.update(topology())
    if failures:
        row["failure_sample"] = failures[:3]

    print(json.dumps(row), flush=True)
    if args.out:
        from pathlib import Path

        p = Path(args.out)
        p.parent.mkdir(parents=True, exist_ok=True)
        p.write_text(json.dumps(row, indent=2))
    if service is not None:
        service.close()
    return 1 if non_rejected_failures else 0


if __name__ == "__main__":
    sys.exit(main())
