#!/usr/bin/env python
"""Price VPU ops with an in-VMEM Pallas chain, to steer kernel-ledger work.

Round-5 context: two silicon A/Bs (interior-split 1.004x, fused-path
clamp elision ~0-3%) falsified the uniform-op-cost ledger — removing
"ops" only pays when the removed op sits on the issue-critical path.
DESIGN.md names the credible next levers as cutting *FMA or rint* work,
e.g. integer accumulation folding rint into the u8 store, or the
magic-number rint replacement.  Whether those levers can pay depends on
hardware op prices this probe measures directly:

  - f32 FMA chain         — the kernel's dominant op (baseline price)
  - bf16 FMA chain        — packed-2x issue?
  - int32 / int16 mul-add — the integer-accumulate alternative's price
  - int32 / int16 add     — the blur numerator's actual op mix
  - f32 rint (+add)       — the per-level quantize cost being folded
  - f32 magic-round (+add)— (x + 1.5*2^23) - 1.5*2^23, the candidate
                            2-add replacement for rint (exact
                            half-to-even for |x| < 2^22)
  - f32 clamp (min+max+add) — the already-elided op, for scale
  - f32 add               — chain control (subtract from rint rows)

METHOD.  Each candidate is a Pallas kernel whose grid streams
(1024, 512) blocks through VMEM and runs K dependent elementwise steps
per block via an in-kernel fori_loop — so HBM traffic is one read +
one write per block while compute is K ops/element (~32 f32 ops/byte
at K=128): issue-bound by two orders of magnitude.  This exists
because two cheaper attempts measured something else (artifacts kept
alongside, 2026-07-31):

  - vpu_op_probe_r5_stream.jsonl: jitted fori_loop(unroll=8) chain —
    every dtype landed at ~700 GB/s regardless of op: HBM-bound.
  - vpu_op_probe_r5_xla_chain.jsonl: Python-unrolled 128-op jit chain —
    internally inconsistent (pure f32 add "7x slower" than f32 FMA;
    the slow rows' walls exactly match 128 unfused round trips): it
    measures XLA's fusion grouping, not the VPU.

One JSON row per candidate: {op, dtype, ops_per_step, elems, k,
wall_s, gops_per_s, per_step_vs_f32_fma}.  ``per_step_vs_f32_fma`` is
the price of one step of this op chain in units of one f32-FMA step.
"""

from __future__ import annotations

import json
import sys
from functools import partial

import _path  # noqa: F401

MAGIC = 12582912.0  # 1.5 * 2**23: f32 add forces round-half-even at ulp=1


def main() -> int:
    from parallel_convolution_tpu.utils.platform import (
        apply_platform_env, enable_compile_cache, timing_mode,
    )

    apply_platform_env()
    enable_compile_cache()

    import jax
    import jax.numpy as jnp
    import numpy as np
    from jax.experimental import pallas as pl

    from parallel_convolution_tpu.utils import bench

    H, W = 8192, 512   # 4M elements; streamed as 16 VMEM blocks
    BH = 512           # block rows: 4 refs x 1 MB f32 x 2 slots = 8 MB,
    #                    inside the 16 MB scoped-VMEM budget the
    #                    helper_crash_probe pinned (1024 rows OOM'd at ~22 MB)
    K = 128            # dependent steps per element

    rng = np.random.default_rng(0)
    xf = rng.uniform(10.0, 200.0, (H, W)).astype(np.float32)
    # Multiplier near 1 and a sign-alternating addend keep K chained
    # steps inside float range (no inf/NaN slow paths).
    af = rng.uniform(0.99, 1.01, (H, W)).astype(np.float32)
    bf = rng.uniform(-0.5, 0.5, (H, W)).astype(np.float32)
    xi = rng.integers(0, 255, (H, W)).astype(np.int32)
    ai = rng.integers(1, 4, (H, W)).astype(np.int32)
    bi = rng.integers(-8, 8, (H, W)).astype(np.int32)

    interpret = jax.default_backend() == "cpu"

    def runner(step, a, b, dtype):
        """Chainable x -> x: grid-streamed blocks, K in-VMEM steps each."""
        def kern(x_ref, a_ref, b_ref, o_ref):
            av, bv = a_ref[...], b_ref[...]

            def body(_, y):
                return step(y, av, bv)

            # Full unroll (Mosaic supports only unroll=1 or =num_steps):
            # amortizes per-iteration loop overhead so the wall prices
            # the op, not the loop.
            o_ref[...] = jax.lax.fori_loop(0, K, body, x_ref[...],
                                           unroll=K)

        spec = pl.BlockSpec((BH, W), lambda i: (i, 0))
        call = pl.pallas_call(
            kern,
            grid=(H // BH,),
            in_specs=[spec, spec, spec],
            out_specs=spec,
            out_shape=jax.ShapeDtypeStruct((H, W), dtype),
            interpret=interpret,
        )
        aj = jnp.asarray(a, dtype=dtype)
        bj = jnp.asarray(b, dtype=dtype)
        return jax.jit(lambda x: call(x, aj, bj))

    platform = jax.default_backend()
    candidates = [
        # (op, dtype_name, dtype, ops/step, step(y, a, b), x0)
        ("fma", "f32", jnp.float32, 1, lambda y, a, b: y * a + b, xf),
        ("fma", "bf16", jnp.bfloat16, 1, lambda y, a, b: y * a + b, xf),
        ("muladd", "i32", jnp.int32, 1, lambda y, a, b: y * a + b, xi),
        ("muladd", "i16", jnp.int16, 1, lambda y, a, b: y * a + b, xi),
        ("add", "i32", jnp.int32, 1, lambda y, a, b: y + b, xi),
        ("add", "i16", jnp.int16, 1, lambda y, a, b: y + b, xi),
        ("add", "f32", jnp.float32, 1, lambda y, a, b: y + b, xf),
        # rint/magic rows keep values moving with +b so the chain cannot
        # collapse; subtract the add-f32 row to price the round alone.
        ("rint+add", "f32", jnp.float32, 2,
         lambda y, a, b: jnp.rint(y) + b, xf),
        ("magicround+add", "f32", jnp.float32, 3,
         lambda y, a, b: ((y + MAGIC) - MAGIC) + b, xf),
        ("clamp+add", "f32", jnp.float32, 3,
         lambda y, a, b: jnp.minimum(jnp.maximum(y, 0.0), 255.0) + b, xf),
    ]

    rows = []
    f32_fma_step = None
    for name, dtype_name, dtype, ops, step, x0 in candidates:
        try:
            if dtype_name.startswith("i"):
                a_src, b_src = ai, bi
            else:
                a_src, b_src = af, bf
            run = runner(step, a_src, b_src, dtype)
            x = jnp.asarray(x0, dtype=dtype)
            wall_s = bench.slope_wall(run, x, reps=5)
        except Exception as e:
            msg = repr(e)
            if len(msg) > 600:
                msg = msg[:300] + " ... " + msg[-300:]
            print(json.dumps({"op": name, "dtype": dtype_name,
                              "error": msg}), flush=True)
            continue
        total_ops = H * W * K * ops
        row = {
            "op": name, "dtype": dtype_name, "ops_per_step": ops,
            "elems": H * W, "k": K, "wall_s": round(wall_s, 6),
            "gops_per_s": round(total_ops / wall_s / 1e9, 1),
            "platform": platform, "timing": timing_mode(),
        }
        per_step = wall_s / K
        if name == "fma" and dtype_name == "f32":
            f32_fma_step = per_step
        if f32_fma_step:
            row["per_step_vs_f32_fma"] = round(per_step / f32_fma_step, 3)
        rows.append(row)
        print(json.dumps(row), flush=True)
    return 0 if rows else 1


if __name__ == "__main__":
    sys.exit(main())
