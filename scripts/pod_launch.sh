#!/usr/bin/env bash
# Multi-host TPU pod launch (the reference's mpiexec/PBS tier, C12).
#
# The reference launched `mpiexec -np N ./mpi_conv ...` via qsub; on a TPU
# pod slice each host runs the SAME command and JAX's multi-controller
# runtime plays the role of MPI_Init (see parallel/multihost.py):
#
#   gcloud compute tpus tpu-vm ssh $TPU_NAME --worker=all --command "
#     cd parallel-convolution-tpu &&
#     python -c '
# from parallel_convolution_tpu.parallel import multihost
# multihost.initialize()                      # MPI_Init analog
# import sys
# from parallel_convolution_tpu import cli
# sys.exit(cli.main(sys.argv[1:]))
# ' run big.raw 65536 65536 100 rgb -o out.raw --sharded-io --backend pallas --fuse 8
#   "
#
# Every host reads/writes only its own devices' blocks (utils/sharded_io
# touches addressable_shards only), so the raw file can live on a shared
# filesystem (GCS fuse, NFS) exactly like the reference's cluster scratch.
#
# Single-host smoke version of the same flow:
set -euo pipefail
IMG=${1:-/tmp/pconv_demo.raw}
python -m parallel_convolution_tpu.cli generate "$IMG" 1920 2520 grey
python -m parallel_convolution_tpu.cli run "$IMG" 1920 2520 100 grey \
  -o "${IMG%.raw}_out.raw" --backend pallas --fuse 8 --storage bf16
python -m parallel_convolution_tpu.cli serial "$IMG" 1920 2520 100 grey \
  -o "${IMG%.raw}_serial.raw"
python -m parallel_convolution_tpu.cli compare \
  "${IMG%.raw}_out.raw" "${IMG%.raw}_serial.raw"
