#!/usr/bin/env python
"""Fleet-autoscaling smoke: the ``run_t1.sh --scale-smoke`` leg.

Boot ONE in-process replica behind the router with the autoscaler and
cost-priced admission armed, then drive the whole round-17 control loop
on the CPU mesh:

1. **Load curve** — open-loop POISSON arrivals at fixed offered-RPS
   steps; each step emits one p50/p95/p99 latency row
   (``gate_metric: "latency"``) into ``evidence/scale_curve.jsonl`` —
   the committed latency-vs-offered-load trajectory ``perf_gate.py``
   judges.
2. **Scale-up under saturation** — a closed-loop worker pack pushes
   pressure past the control loop's threshold; gates: the pool GROWS
   (>= 1 new replica), the newcomer PRE-WARMED its ring shard before
   its vnodes joined (``prewarmed_configs >= 1``), and the shard's
   per-key compile ledger stays FLAT through the remapped traffic that
   follows (warm placement: scale-up is not a compile storm).
3. **Scale-down on idle** — traffic stops; the pool shrinks back to the
   boot floor through the ring-remove + drain path.
4. **Cost-priced tenant isolation** — one tenant hammers large converge
   jobs (charged their predicted device-seconds; the bucket sheds the
   excess typed + retryable with the price in the body) while a polite
   tenant's small requests run: the polite tenant sees ZERO quota sheds
   and its p99 stays within the stated bound of its solo baseline.
5. **Perf sentry** — curve + summary rows seed and re-gate against the
   smoke's OWN history (never the committed ``perf_history.jsonl``),
   and a synthetic 2× p99 row must DEMONSTRABLY fail the gate.

Every completed response is byte-compared to the NumPy oracle; any
non-rejected failure anywhere fails the smoke.  The summary row lands
in ``--out`` (``evidence/scale_smoke.json``, the supervisor leg's
done_file) with ``"failures": 0`` iff every gate held.
"""

from __future__ import annotations

import argparse
import base64
import json
import subprocess
import sys
import threading
import time
from pathlib import Path

import _path  # noqa: F401  (repo root + JAX_PLATFORMS re-apply)
from loadgen import poisson_arrivals  # the ONE open-loop arrival loop

from parallel_convolution_tpu.utils.evidence_io import rewrite_shared_jsonl

SCRIPTS = Path(__file__).resolve().parent


def _pct(vals, q):
    if not vals:
        return None
    vs = sorted(vals)
    return vs[min(len(vs) - 1, int(round(q * (len(vs) - 1))))]


def main() -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--rows", type=int, default=48)
    ap.add_argument("--cols", type=int, default=64)
    ap.add_argument("--mesh", default="1x2", help="grid per replica")
    ap.add_argument("--curve-rps", default="5,15,30",
                    help="offered-RPS steps of the committed load curve")
    ap.add_argument("--step-s", type=float, default=4.0,
                    help="wall seconds per curve step")
    ap.add_argument("--out", default="evidence/scale_smoke.json")
    ap.add_argument("--curve-out", default="evidence/scale_curve.jsonl")
    ap.add_argument("--history",
                    default="evidence/scale_smoke_history.jsonl",
                    help="the smoke's OWN perf history, seeded fresh "
                         "each run; never point this at the committed "
                         "evidence/perf_history.jsonl")
    args = ap.parse_args()

    import numpy as np

    from parallel_convolution_tpu.obs import events as obs_events
    from parallel_convolution_tpu.ops import filters, oracle
    from parallel_convolution_tpu.parallel.mesh import mesh_from_spec
    from parallel_convolution_tpu.serving.autoscaler import AutoScaler
    from parallel_convolution_tpu.serving.pricing import WorkPricer
    from parallel_convolution_tpu.serving.router import (
        InProcessReplica, ReplicaRouter, TenantQuotas, route_key,
    )
    from parallel_convolution_tpu.serving.service import ConvolutionService
    from parallel_convolution_tpu.utils import imageio
    from parallel_convolution_tpu.utils.platform import topology

    obs_events.install_from_env()
    failures: list[str] = []
    t0 = time.time()

    img = imageio.generate_test_image(args.rows, args.cols, "grey", seed=7)
    b64 = base64.b64encode(np.ascontiguousarray(img).tobytes()).decode()
    iters_pool = [1, 2, 3]
    oracles = {it: oracle.run_serial_u8(img, filters.get_filter("blur3"),
                                        it) for it in iters_pool}
    grid = tuple(int(v) for v in args.mesh.lower().split("x"))

    def factory():
        # max_batch=1 ON PURPOSE: every executable is the batch-1
        # program, so the warm-placement gate below can demand an
        # EXACTLY flat per-key compile ledger (a co-batched flush would
        # legitimately compile a batch-N twin and muddy the assertion).
        return ConvolutionService(mesh_from_spec(args.mesh), max_batch=1,
                                  max_delay_s=0.001, max_queue=16,
                                  max_progressive=2)

    def transport_factory(name):
        return InProcessReplica(factory, name=name)

    pricer = WorkPricer(grid=grid, platform="cpu")
    big_img = imageio.generate_test_image(256, 256, "grey", seed=3)
    big_job = {"image_b64": base64.b64encode(
        np.ascontiguousarray(big_img).tobytes()).decode("ascii"),
        "rows": 256, "cols": 256, "mode": "grey", "filter": "blur3",
        "solver": "multigrid", "max_iters": 200, "tol": 0.0,
        "quantize": False, "storage": "f32", "backend": "shifted"}
    big_cost = pricer.price(big_job, converge=True)
    small_cost = pricer.price({"rows": args.rows, "cols": args.cols,
                               "mode": "grey", "filter": "blur3",
                               "iters": 2})
    # The greedy tenant's bucket is sized IN WORK UNITS around the big
    # job's own predicted price: one job fits (debt semantics), the
    # refill admits roughly one job per 10 s — the polite tenant's
    # budget is generous in units but would have been IDENTICAL to
    # greedy's under request counting, which is the whole point.
    quotas = TenantQuotas(
        rate=5.0, burst=8.0,
        overrides={"greedy": (big_cost / 10.0, big_cost * 1.2)})
    router = ReplicaRouter(
        [InProcessReplica(factory, name="r0")], quotas=quotas,
        pricer=pricer, poll_interval_s=0.05, breaker_cooldown_s=0.2)
    scaler = AutoScaler(
        router, transport_factory, min_replicas=1, max_replicas=2,
        up_pressure=0.3, down_pressure=0.02, up_ticks=2, down_ticks=10,
        cooldown_s=2.0, interval_s=0.2, drain_s=5.0)

    def body_for(i: int, tenant: str = "polite") -> dict:
        return {"image_b64": b64, "rows": args.rows, "cols": args.cols,
                "mode": "grey", "filter": "blur3",
                "iters": iters_pool[i % len(iters_pool)],
                "request_id": f"sc{tenant}{i}", "tenant": tenant}

    lock = threading.Lock()
    outcomes: list[dict] = []   # every batch request's verdict

    def one(i: int, tenant: str = "polite", retries: int = 5) -> dict:
        body = body_for(i, tenant)
        t_req = time.perf_counter()
        wire = {}
        for attempt in range(retries + 1):
            status, wire = router.request(dict(body))
            if wire.get("ok") or not wire.get("retryable"):
                break
            time.sleep(min(float(wire.get("retry_after_s") or 0.05), 0.25))
        lat = time.perf_counter() - t_req
        it = iters_pool[i % len(iters_pool)]
        byte_ok = None
        if wire.get("ok"):
            got = np.frombuffer(base64.b64decode(wire["image_b64"]),
                                np.uint8).reshape(args.rows, args.cols)
            byte_ok = bool(np.array_equal(got, oracles[it]))
        rec = {"i": i, "tenant": tenant, "ok": bool(wire.get("ok")),
               "byte_ok": byte_ok, "latency_s": lat,
               "rejected": wire.get("rejected"),
               "retryable": wire.get("retryable"),
               "router": wire.get("router", {})}
        with lock:
            outcomes.append(rec)
        return rec

    # ---- phase 0: warm the key space (the observatory sees 3 configs).
    for i in range(len(iters_pool)):
        rec = one(i)
        if not rec["ok"]:
            failures.append(f"warm request {i} failed: {rec}")
    scaler.start()

    # ---- phase 1: the committed load curve (fixed offered-RPS steps).
    curve_rows: list[dict] = []
    rps_steps = [float(v) for v in args.curve_rps.split(",") if v.strip()]
    for step_no, rps in enumerate(rps_steps):
        step_lat: list[float] = []
        step_lock = threading.Lock()

        def fire(i: int) -> None:
            rec = one(i)   # curve traffic is all iters round-robin
            with step_lock:
                if rec["ok"]:
                    step_lat.append(rec["latency_s"])

        t_step = time.perf_counter()
        issued, threads = poisson_arrivals(
            rps, fire, duration_s=args.step_s, seed=step_no)
        for th in threads:
            th.join(60)
        wall = time.perf_counter() - t_step
        lats_ms = [1e3 * v for v in step_lat]
        curve_rows.append({
            "workload": f"scale-curve blur3 {args.rows}x{args.cols}x1",
            "gate_metric": "latency",
            "loop": "open-poisson",
            "offered_rps": rps,
            "issued_rps": round(issued / wall, 3),
            "achieved_rps": round(len(step_lat) / wall, 3),
            "n": issued,
            "completed": len(step_lat),
            "p50_ms": round(_pct(lats_ms, 0.50), 3) if lats_ms else None,
            "p95_ms": round(_pct(lats_ms, 0.95), 3) if lats_ms else None,
            "p99_ms": round(_pct(lats_ms, 0.99), 3) if lats_ms else None,
            "effective_backend": "shifted",
            "mesh": args.mesh,
            "replicas": len(router.ring.members()),
            **topology(),
        })

    # ---- phase 2: saturation -> the control loop must GROW the pool.
    sat_stop = threading.Event()
    counter = [10_000]

    def sat_worker() -> None:
        while not sat_stop.is_set():
            with lock:
                i = counter[0]
                counter[0] += 1
            one(i)

    sat_threads = [threading.Thread(target=sat_worker, daemon=True)
                   for _ in range(24)]
    for th in sat_threads:
        th.start()
    grew_at = None
    t_sat = time.perf_counter()
    while time.perf_counter() - t_sat < 30.0:
        if len(router.ring.members()) >= 2:
            grew_at = time.perf_counter() - t_sat
            break
        time.sleep(0.1)
    # Keep the pressure on briefly AFTER the join so the remapped shard
    # actually serves traffic on the newcomer (the flat-compile gate's
    # evidence window), then stop.
    if grew_at is not None:
        time.sleep(2.0)
    sat_stop.set()
    for th in sat_threads:
        th.join(60)

    members = router.ring.members()
    newcomer = next((m for m in members if m != "r0"), None)
    if grew_at is None or newcomer is None:
        failures.append(
            f"pool never grew under saturation (ring={members}, "
            f"scaler={scaler.snapshot()['stats']})")
    prewarmed = scaler.stats["prewarmed_configs"]
    if newcomer is not None and prewarmed < 1:
        failures.append("newcomer joined with zero pre-warmed configs")

    # Warm-placement gate: every key the newcomer is HOME for must sit
    # at EXACTLY one compile (its pre-warm build) — the remapped
    # traffic above hit warm executables, not a compile storm.  Spilled
    # non-home keys are excluded (a spill compiles cold by design).
    shard_iters: list[int] = []
    if newcomer is not None:
        hub = router.replica(newcomer)
        # Post-join serve pass: drive every key homed on the newcomer
        # once more, serially, to prove warm serving in steady state.
        for i, it in enumerate(iters_pool):
            if router.ring.candidates(
                    route_key(body_for(i)))[0] == newcomer:
                shard_iters.append(it)
                rec = one(i)
                if not rec["ok"]:
                    failures.append(f"post-join shard request failed: {rec}")
        resident = {r["iters"]: r for r in hub.snapshot()["resident"]}
        for it in shard_iters:
            entry = resident.get(it)
            if entry is None:
                failures.append(
                    f"shard key iters={it} not resident on {newcomer}")
            elif entry["compiles"] != 1:
                failures.append(
                    f"shard key iters={it} compiled {entry['compiles']}x "
                    f"on {newcomer} (warm placement broken)")
        if not shard_iters:
            failures.append(
                f"no observed key homes on {newcomer} (vnode anomaly)")

    # ---- phase 3: idle -> the pool must SHRINK back to the floor.
    shrunk_at = None
    t_idle = time.perf_counter()
    while time.perf_counter() - t_idle < 30.0:
        if len(router.ring.members()) == 1:
            shrunk_at = time.perf_counter() - t_idle
            break
        time.sleep(0.1)
    if grew_at is not None and shrunk_at is None:
        failures.append(
            f"pool never shrank on idle (ring={router.ring.members()})")
    scaler.close()

    # ---- phase 4: cost-priced tenant isolation.
    # Pre-compile the big job's level programs OUTSIDE the measured
    # window (a neutral tenant with the default bucket): the isolation
    # bound judges admitted-job CONTENTION, not a one-time compile storm
    # both tenants would pay anyway.
    status, rows = router.converge(dict(
        big_job, max_iters=8, request_id="mgwarm", tenant="warmmg",
        check_every=1))
    warm_final = None
    for warm_final in rows:
        pass
    if status != 200 or not (warm_final or {}).get("ok"):
        failures.append(f"mg pre-compile job failed: {status} "
                        f"{ {k: v for k, v in (warm_final or {}).items() if k != 'image_b64'} }")
    solo = [one(20_000 + i)["latency_s"] for i in range(30)]
    solo_p99 = _pct([v for v in solo if v is not None], 0.99) or 0.0

    greedy_stop = threading.Event()
    greedy_stats = {"admitted": 0, "quota_sheds": 0, "other_sheds": 0,
                    "bad_shape": 0, "max_cost_units": 0.0}

    def _categorize(first: dict | None) -> None:
        with lock:
            if first is None:
                pass
            elif first.get("rejected") == "tenant_quota":
                greedy_stats["quota_sheds"] += 1
                cu = float(first.get("cost_units") or 0.0)
                greedy_stats["max_cost_units"] = max(
                    greedy_stats["max_cost_units"], cu)
                if not first.get("retryable"):
                    greedy_stats["bad_shape"] += 1
            elif first.get("ok"):
                greedy_stats["admitted"] += 1
            else:
                # Replica-side shed (progressive-slot queue_full etc) —
                # charged then refunded, distinct from the quota story.
                greedy_stats["other_sheds"] += 1

    def _drain_bg(rows) -> None:
        try:
            for _ in rows:
                if greedy_stop.is_set():
                    break
        except Exception:  # noqa: BLE001 — drill teardown
            pass
        finally:
            close = getattr(rows, "close", None)
            if close is not None:
                close()

    def greedy_worker() -> None:
        # Job A: the full bucket pays it into debt; it streams in the
        # background for the WHOLE measured window (its duration must
        # not gate the drill — an earlier cut only submitted job B
        # after A finished, so a slow A meant no shed was ever
        # attempted).
        for attempt in range(3):
            status, rows = router.converge(dict(
                big_job, request_id=f"greedyA{attempt}", tenant="greedy",
                check_every=1))
            first = next(iter(rows), None)
            _categorize(first)
            if first is not None and first.get("ok"):
                threading.Thread(target=_drain_bg, args=(rows,),
                                 daemon=True).start()
                break
            _drain_bg(rows)
            time.sleep(0.2)
        # Jobs B…: while A runs, every further submission must be
        # priced out (the bucket is in debt and refills at cost/10 per
        # second — typed retryable tenant_quota carrying the bill).
        i = 0
        while not greedy_stop.is_set():
            status, rows = router.converge(dict(
                big_job, request_id=f"greedyB{i}", tenant="greedy",
                check_every=1))
            first = next(iter(rows), None)
            _categorize(first)
            _drain_bg(rows)
            i += 1
            greedy_stop.wait(0.25)

    gt = threading.Thread(target=greedy_worker, daemon=True)
    gt.start()
    time.sleep(0.5)   # let the first big job start occupying the pool
    contended = [one(30_000 + i)["latency_s"] for i in range(30)]
    greedy_stop.set()
    gt.join(90)
    contended_p99 = _pct([v for v in contended if v is not None],
                         0.99) or 0.0
    # The STATED bound: under one admitted big job + quota-shed
    # pressure, the polite tenant's p99 stays within 10x its solo
    # baseline + 250 ms of absolute slack (CPU smoke boxes are noisy;
    # the mechanism under test is that the OTHER big jobs were priced
    # out, not that contention is free).
    p99_bound = 10.0 * solo_p99 + 0.25
    if contended_p99 > p99_bound:
        failures.append(
            f"polite p99 {contended_p99:.3f}s exceeded the bound "
            f"{p99_bound:.3f}s (solo {solo_p99:.3f}s) under a greedy "
            "converge tenant")
    if greedy_stats["quota_sheds"] < 1:
        failures.append("greedy tenant never hit its work-unit bucket")
    if greedy_stats["admitted"] < 1:
        failures.append("no greedy converge job was ever admitted — the "
                        "isolation phase measured nothing")
    if greedy_stats["bad_shape"]:
        failures.append(f"{greedy_stats['bad_shape']} quota sheds "
                        "missing retryable")
    if greedy_stats["max_cost_units"] <= 10 * small_cost:
        failures.append(
            f"quota shed cost_units {greedy_stats['max_cost_units']} not "
            f"priced above the small-request cost {small_cost} (work-unit "
            "pricing not in effect)")
    polite_quota_sheds = sum(
        1 for r in outcomes
        if r["tenant"] == "polite" and r.get("rejected") == "tenant_quota")
    if polite_quota_sheds:
        failures.append(f"polite tenant saw {polite_quota_sheds} quota "
                        "sheds (bucket isolation broken)")

    # ---- global gates: bytes + typed-only failures.
    byte_fails = [r for r in outcomes if r["ok"] and not r["byte_ok"]]
    non_rejected = [r for r in outcomes
                    if not r["ok"] and not r.get("retryable")]
    if byte_fails:
        failures.append(f"{len(byte_fails)} oracle byte mismatches")
    if non_rejected:
        failures.append(f"{len(non_rejected)} non-rejected failures, "
                        f"e.g. {non_rejected[0]}")

    wall = time.time() - t0
    completed = [r for r in outcomes if r["ok"]]
    px = args.rows * args.cols * sum(
        iters_pool[r["i"] % len(iters_pool)] for r in completed)
    snap = router.snapshot()
    row = {
        "workload": f"scale-smoke blur3 {args.rows}x{args.cols} "
                    "autoscale 1->2->1",
        "n": len(outcomes),
        "completed": len(completed),
        "grew_after_s": round(grew_at, 2) if grew_at is not None else None,
        "shrunk_after_s": (round(shrunk_at, 2)
                           if shrunk_at is not None else None),
        "prewarmed_configs": prewarmed,
        "newcomer_shard_iters": shard_iters,
        "solo_p99_ms": round(1e3 * solo_p99, 3),
        "contended_p99_ms": round(1e3 * contended_p99, 3),
        "p99_bound_ms": round(1e3 * p99_bound, 3),
        "greedy": {k: (round(v, 6) if isinstance(v, float) else v)
                   for k, v in greedy_stats.items()},
        "big_job_cost_units": round(big_cost, 6),
        "small_request_cost_units": round(small_cost, 8),
        "router": snap["router"],
        "scaler": scaler.snapshot()["stats"],
        "effective_backend": "shifted",
        "mesh": args.mesh,
        "wall_s": round(wall, 3),
        "gpixels_per_s": round(px / wall / 1e9, 6) if wall else None,
        **topology(),
        "failures": len(failures),
        "failure_detail": failures[:8],
    }
    router.close()

    # ---- evidence: the committed curve + the smoke's own perf gate.
    # The curve file is SHARED: rows carrying a "lane" field belong to
    # other smokes (shard_smoke's router_scale lane, cache_smoke's
    # cache_skew lane) and must survive our rewrite — we own only the
    # un-laned rows.  evidence_io is the ONE sanctioned writer
    # (static_check forbids direct open-for-write of shared curves).
    curve_path = Path(args.curve_out)
    rewrite_shared_jsonl(curve_path, curve_rows, lane=None)
    out = Path(args.out)
    out.parent.mkdir(parents=True, exist_ok=True)
    out.write_text(json.dumps(row, indent=2))

    hist = Path(args.history)
    hist.parent.mkdir(parents=True, exist_ok=True)
    hist.write_text("")   # the smoke's OWN history: truncate per run
    gate = [sys.executable, str(SCRIPTS / "perf_gate.py"),
            "--history", str(hist), "--row", str(curve_path),
            "--row", str(out), "--quiet"]
    rc_seed = subprocess.run([*gate, "--update"], check=False).returncode
    rc_pass = subprocess.run(gate, check=False).returncode
    if rc_seed != 0:
        failures.append(f"perf_gate seed run exited {rc_seed}")
    if rc_pass != 0:
        failures.append(f"perf_gate re-gate exited {rc_pass}")
    # The sentry must DEMONSTRABLY catch a regression: a synthetic row
    # 2x slower at p99 than the measured first curve step has to fail.
    if curve_rows and curve_rows[0].get("p99_ms"):
        synth = dict(curve_rows[0])
        synth["p99_ms"] = 2.0 * synth["p99_ms"]
        synth_path = out.parent / "scale_smoke_synth_regression.json"
        synth_path.write_text(json.dumps(synth))
        rc_synth = subprocess.run(
            [sys.executable, str(SCRIPTS / "perf_gate.py"),
             "--history", str(hist), "--row", str(synth_path),
             "--quiet"], check=False).returncode
        synth_path.unlink()
        if rc_synth == 0:
            failures.append(
                "perf_gate PASSED a synthetic 2x p99 regression")
    else:
        failures.append("no curve p99 to drive the synthetic regression")

    row["failures"] = len(failures)
    row["failure_detail"] = failures[:10]
    out.write_text(json.dumps(row, indent=2))
    print(json.dumps(row), flush=True)
    return 1 if failures else 0


if __name__ == "__main__":
    sys.exit(main())
