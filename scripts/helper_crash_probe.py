#!/usr/bin/env python
"""Attribute the remote-compile-helper HTTP 500 to a failure CLASS (TPU).

Round-5 finding: the `tpu_compile_helper subprocess exit code 1` /
HTTP 500 rejection first seen on the tiled RDMA kernel
(`evidence/rdma_silicon.json`) is NOT RDMA-specific — the PLAIN fused
stencil kernel (no scratch, no semaphores, no remote copies) hits the
identical rejection at 1536x512 tiles while 1024x512 compiles and runs
(`evidence/tune_convex_r5_recovered.jsonl`).  The obvious difference is
VMEM footprint: the fused kernel double-buffers padded f32 tiles, so
1536-row tiles cross the ~16 MB/core VMEM budget where 1024-row tiles
fit.

Hypothesis: on this tunnel, a Mosaic VMEM-exhaustion diagnostic (which
should surface as a clean RESOURCE_EXHAUSTED) instead kills the remote
compile helper subprocess, and the HTTP 500 is the tunnel's framing of
ANY such compile-stage death.  If true, the six-construct RDMA ladder
(`scripts/tiled_repro_probe.py`) cannot isolate a guilty construct —
the guilt is a resource class plus an infrastructure masking bug.

Test: compile a TRIVIAL kernel (elementwise add of a VMEM scratch it
zeroes itself — no DMA constructs, no windowing, nothing from the RDMA
kernel) at scratch sizes stepping across the VMEM budget, and record
the failure FORM at each step:

  4 MB   well inside        -> expect compile + run
  12 MB  inside             -> expect compile + run
  20 MB  past ~16 MB budget -> failure expected; FORM is the finding
  32 MB  far past           -> same

One JSON row per step.  `error_class` distinguishes a clean Mosaic
resource error (`clean_resource_error`) from the helper crash
(`helper_http500`) by substring, so the evidence row states the
attribution directly.  Exit 0 iff every step produced a row — an
`other` classification is still a complete answer (discovering the
unknown failure form is the probe's purpose), not a failed run.
Off-TPU this exits 1: the interpreter/CPU path has no VMEM budget and
the remote helper does not exist, so there is nothing to learn.
"""

from __future__ import annotations

import json
import sys

import _path  # noqa: F401

# Scratch shapes chosen as (rows, 512) f32 -> bytes = rows*512*4.
STEPS_MB = (4, 12, 20, 32)


def classify(msg: str) -> str:
    if "tpu_compile_helper" in msg or "HTTP 500" in msg:
        return "helper_http500"
    # Trace-time rejections (Pallas refuses the kernel before any
    # compile) must not masquerade as the compile-stage resource error
    # this probe is hunting — the first run mislabeled exactly this.
    if "Cannot store scalars" in msg or "TracerError" in msg:
        return "probe_bug_trace_error"
    if "RESOURCE_EXHAUSTED" in msg or "VMEM" in msg or "vmem" in msg:
        return "clean_resource_error"
    return "other"


def main() -> int:
    from parallel_convolution_tpu.utils.platform import (
        apply_platform_env, enable_compile_cache, on_tpu,
    )

    apply_platform_env()
    enable_compile_cache()

    import jax
    import jax.numpy as jnp
    import numpy as np
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    if not on_tpu():
        print(json.dumps({"error": "not on TPU; helper does not exist"}))
        return 1

    H, W = 256, 512
    x = np.arange(H * W, dtype=np.float32).reshape(H, W) % 251.0
    want = x + 1.0

    for mb in STEPS_MB:
        rows = (mb * 1024 * 1024) // (512 * 4)

        def kernel(in_ref, out_ref, scratch):
            # Touch one row of the scratch so it cannot be elided, but
            # keep the compute trivial: out = in + 1.  (A scalar store
            # like scratch[0, 0] = ... is rejected by Pallas at TRACE
            # time — "Cannot store scalars to VMEM" — which the first
            # run of this probe hit on every rung, so no rung ever
            # reached the compile stage.  Vector-shaped accesses only.)
            scratch[0:1, :] = in_ref[0:1, :]
            out_ref[...] = in_ref[...] + 1.0 + (scratch[0:1, 0:1] * 0.0)

        fn = pl.pallas_call(
            kernel,
            out_shape=jax.ShapeDtypeStruct((H, W), jnp.float32),
            scratch_shapes=[pltpu.VMEM((rows, 512), jnp.float32)],
        )
        row = {"scratch_mb": mb, "scratch_shape": [int(rows), 512]}
        try:
            got = np.asarray(jax.jit(fn)(jnp.asarray(x)))
            row.update(compiled=True, correct=bool(np.array_equal(got, want)))
        except Exception as e:
            msg = repr(e)
            if len(msg) > 3000:
                msg = msg[:1500] + " ...[elided]... " + msg[-1500:]
            row.update(compiled=False, error_class=classify(msg), error=msg)
        print(json.dumps(row), flush=True)
    return 0


if __name__ == "__main__":
    sys.exit(main())
