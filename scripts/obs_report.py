#!/usr/bin/env python
"""Fold an event log + metrics snapshot into one human/JSON summary.

The read side of the round-11 observability spine: given the JSONL event
log (``PCTPU_OBS_EVENTS``) and/or a metrics snapshot JSON
(``obs.metrics.dump``), produce the operator summary the bespoke
telemetry paths never could:

* per-phase latency quantiles (p50/p95/p99) from the serving phase
  histograms;
* exchange-vs-compute fraction and per-direction halo bytes per backend
  (the overlap/topology attribution, ROADMAP items 1 and 3);
* retry / degrade / quarantine / fault totals (the resilience ledger);
* predicted-vs-measured Gpx/s drift per plan key — the cost-model
  recalibration input ROADMAP item 5a consumes;
* event-timeline integrity (counts per kind, seq gaps, invalid lines).

  python scripts/obs_report.py --events evidence/obs_events.jsonl \\
      --metrics evidence/obs_metrics.json --out evidence/obs_report.json

Exit status: 0 on a clean fold; 1 when an input is unreadable or the
event log fails schema validation (invalid lines / seq regressions) —
the ``run_t1.sh --obs-smoke`` gate.
"""

from __future__ import annotations

import argparse
import json
import sys
from pathlib import Path

import _path  # noqa: F401  (repo root on sys.path)

from parallel_convolution_tpu.obs import events as events_lib


def _quantiles(buckets: list[float], counts: list[int],
               qs=(0.5, 0.95, 0.99)) -> dict[float, float | None]:
    """Bucket-interpolated quantiles from a snapshot histogram series
    (same estimate as obs.metrics.Histogram.quantile)."""
    total = sum(counts)
    out: dict[float, float | None] = {}
    for q in qs:
        if total == 0:
            out[q] = None
            continue
        rank = q * total
        cum = 0.0
        val = buckets[-1] if buckets else None
        for i, c in enumerate(counts):
            prev = cum
            cum += c
            if cum >= rank and c > 0:
                if i >= len(buckets):
                    val = buckets[-1]
                else:
                    lo = buckets[i - 1] if i > 0 else 0.0
                    val = lo + (buckets[i] - lo) * (rank - prev) / c
                break
        out[q] = val
    return out


def _metric(snap: dict, name: str) -> list[dict]:
    for m in snap.get("metrics", []):
        if m["name"] == name:
            return m["series"]
    return []


def _counter_by(snap: dict, name: str, label: str) -> dict[str, float]:
    out: dict[str, float] = {}
    for s in _metric(snap, name):
        k = s["labels"].get(label, "")
        out[k] = out.get(k, 0) + s["value"]
    return out


def summarize_metrics(snap: dict) -> dict:
    out: dict = {}
    # Serving latency: p50/p95/p99 per phase (ms), across backends.
    phases: dict[str, dict] = {}
    for s in _metric(snap, "pctpu_request_phase_seconds"):
        ph = s["labels"].get("phase", "")
        agg = phases.setdefault(ph, {"counts": None, "buckets": None,
                                     "count": 0, "sum": 0.0})
        if agg["counts"] is None:
            agg["counts"] = list(s["counts"])
            agg["buckets"] = list(s["buckets"])
        else:
            agg["counts"] = [a + b for a, b in zip(agg["counts"],
                                                   s["counts"])]
        agg["count"] += s["count"]
        agg["sum"] += s["sum"]
    out["phases_ms"] = {
        ph: {
            "count": a["count"],
            "mean": (round(1e3 * a["sum"] / a["count"], 3)
                     if a["count"] else None),
            **{f"p{int(q * 100)}": (round(1e3 * v, 3)
                                    if v is not None else None)
               for q, v in _quantiles(a["buckets"], a["counts"]).items()},
        }
        for ph, a in sorted(phases.items())
    }
    # Exchange vs compute per backend + per-direction halo bytes.
    ex = _counter_by(snap, "pctpu_exchange_seconds_total", "backend")
    comp = _counter_by(snap, "pctpu_compute_seconds_total", "backend")
    rounds = _counter_by(snap, "pctpu_halo_rounds_total", "backend")
    iters = _counter_by(snap, "pctpu_iterations_total", "backend")
    halo: dict[str, dict] = {}
    for s in _metric(snap, "pctpu_halo_bytes_total"):
        b = s["labels"].get("backend", "")
        d = s["labels"].get("direction", "")
        halo.setdefault(b, {})[d] = s["value"]
    out["exchange"] = {
        b: {
            "exchange_s": round(ex.get(b, 0.0), 6),
            "compute_s": round(comp.get(b, 0.0), 6),
            "exchange_fraction": (
                round(ex[b] / (ex[b] + comp.get(b, 0.0)), 4)
                if ex.get(b, 0.0) + comp.get(b, 0.0) > 0 else None),
            "halo_bytes": halo.get(b, {}),
            "rounds": rounds.get(b, 0),
            "iterations": iters.get(b, 0),
        }
        for b in sorted(set(ex) | set(comp) | set(halo))
    }
    # Resilience totals.
    out["totals"] = {
        "retries": sum(_counter_by(
            snap, "pctpu_retries_total", "error").values()),
        "degrades": sum(_counter_by(
            snap, "pctpu_degrades_total", "requested").values()),
        "quarantines": _counter_by(
            snap, "pctpu_quarantines_total", "cause"),
        "faults_fired": _counter_by(
            snap, "pctpu_faults_fired_total", "site"),
        "compiles": sum(_counter_by(
            snap, "pctpu_compiles_total", "builder").values()),
        "admission": _counter_by(snap, "pctpu_admission_total", "outcome"),
    }
    # Predicted-vs-measured drift per plan key (ROADMAP 5a input).
    gpx: dict[tuple[str, str], dict] = {}
    for s in _metric(snap, "pctpu_plan_gpx_per_chip"):
        key = (s["labels"].get("key", ""), s["labels"].get("backend", ""))
        gpx.setdefault(key, {})[s["labels"].get("which", "")] = s["value"]
    drift = {}
    for (key, backend), vals in sorted(gpx.items()):
        pred, meas = vals.get("predicted"), vals.get("measured")
        # Compound report key: the same plan key can carry series for
        # several backends (a degraded fallback, an A/B sweep) — one
        # must never overwrite another in the recalibration input.
        drift[f"{key}|{backend}"] = {
            "backend": backend,
            "predicted_gpx_per_chip": pred,
            "measured_gpx_per_chip": meas,
            "drift_ratio": (round(meas / pred, 4)
                            if pred and meas is not None else None),
        }
    out["drift"] = drift
    return out


def merge_client_trace(rows: list[dict], recs: list[dict]) -> dict:
    """Join loadgen ``--trace-out`` client rows to server-side span trees
    by the server-assigned ``trace_id`` (round 13).

    The client knows wall latency as the user saw it; the server's
    ``request`` root span knows where that time went.  The join reports
    coverage (every client row should find its server trace) and the
    mean client-minus-server delta — the transport/codec overhead
    neither side can see alone.
    """
    from parallel_convolution_tpu.obs import trace as trace_lib

    spans = trace_lib.span_records(recs)
    root_dur: dict[str, float] = {}
    traces: set[str] = set()
    for s in spans:
        tid = s.get("trace_id", "")
        if tid:
            traces.add(tid)
            if s.get("name") == "request" and not s.get("parent_id"):
                root_dur[tid] = float(s.get("dur_s", 0.0))
    with_id = [r for r in rows if r.get("trace_id")]
    joined = [r for r in with_id if r["trace_id"] in traces]
    deltas = [r["latency_ms"] - 1e3 * root_dur[r["trace_id"]]
              for r in joined
              if r["trace_id"] in root_dur
              and isinstance(r.get("latency_ms"), (int, float))]
    return {
        "client_rows": len(rows),
        "with_trace_id": len(with_id),
        "joined": len(joined),
        "unjoined": len(with_id) - len(joined),
        "server_only_traces": len(traces - {r["trace_id"]
                                            for r in with_id}),
        "mean_client_minus_server_ms": (
            round(sum(deltas) / len(deltas), 3) if deltas else None),
    }


def summarize_events(recs: list[dict]) -> dict:
    kinds: dict[str, int] = {}
    invalid = 0
    gaps = 0
    # seq is per-WRITER: supervisor + leg children interleave streams in
    # one file, so continuity is checked within each pid, not globally.
    prev_by_stream: dict[object, int] = {}
    for r in recs:
        if events_lib.validate_event(r):
            invalid += 1
            continue
        kinds[r["kind"]] = kinds.get(r["kind"], 0) + 1
        stream = r.get("pid", 0)
        prev = prev_by_stream.get(stream)
        if prev is not None and r["seq"] != prev + 1:
            gaps += 1
        prev_by_stream[stream] = r["seq"]
    ts = [r.get("ts") for r in recs
          if isinstance(r.get("ts"), (int, float))]
    return {
        "count": len(recs),
        "kinds": dict(sorted(kinds.items())),
        "invalid": invalid,
        "seq_gaps": gaps,
        "first_ts": min(ts) if ts else None,
        "last_ts": max(ts) if ts else None,
        "span_s": round(max(ts) - min(ts), 3) if ts else None,
    }


def _print_human(report: dict) -> None:
    ev = report.get("events")
    if ev:
        print(f"events: {ev['count']} lines, {ev['invalid']} invalid, "
              f"{ev['seq_gaps']} seq gaps, span {ev['span_s']}s")
        for k, n in ev["kinds"].items():
            print(f"  {k:20s} {n}")
    for ph, st in report.get("phases_ms", {}).items():
        print(f"phase {ph:10s} n={st['count']:<6d} "
              f"p50={st['p50']}ms p95={st['p95']}ms p99={st['p99']}ms")
    for b, st in report.get("exchange", {}).items():
        frac = st["exchange_fraction"]
        hb = st["halo_bytes"]
        print(f"backend {b}: exchange_fraction="
              f"{frac if frac is not None else 'n/a'} "
              f"({st['exchange_s']}s vs {st['compute_s']}s) "
              f"halo N/S/E/W="
              f"{[hb.get(d, 0) for d in ('north', 'south', 'east', 'west')]}"
              f" over {st['rounds']} rounds / {st['iterations']} iters")
    tot = report.get("totals")
    if tot:
        print(f"totals: retries={tot['retries']} degrades={tot['degrades']} "
              f"quarantines={tot['quarantines']} "
              f"faults={tot['faults_fired']} compiles={tot['compiles']} "
              f"admission={tot['admission']}")
    cj = report.get("client_join")
    if cj:
        print(f"client join: {cj['joined']}/{cj['with_trace_id']} rows "
              f"matched server traces ({cj['unjoined']} unjoined, "
              f"{cj['server_only_traces']} server-only), "
              f"client-server delta "
              f"{cj['mean_client_minus_server_ms']}ms")
    for key, d in report.get("drift", {}).items():
        print(f"drift {key}: predicted={d['predicted_gpx_per_chip']} "
              f"measured={d['measured_gpx_per_chip']} "
              f"ratio={d['drift_ratio']}")


def main() -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--events", default=None,
                    help="JSONL event log (rotated generations included)")
    ap.add_argument("--metrics", default=None,
                    help="metrics snapshot JSON (obs.metrics.dump)")
    ap.add_argument("--client-trace", default=None, metavar="JSONL",
                    help="loadgen --trace-out rows; joined to the server "
                         "span trees by trace_id (needs --events)")
    ap.add_argument("--out", default=None,
                    help="also write the JSON report to this path")
    ap.add_argument("--quiet", action="store_true",
                    help="suppress the human summary (JSON only)")
    args = ap.parse_args()
    if not args.events and not args.metrics:
        print("need --events and/or --metrics", file=sys.stderr)
        return 2

    report: dict = {}
    rc = 0
    if args.events:
        try:
            recs = events_lib.read_events(args.events)
        except (OSError, ValueError) as e:
            print(f"obs_report: unreadable event log: {e}", file=sys.stderr)
            return 1
        report["events"] = summarize_events(recs)
        if report["events"]["invalid"]:
            print(f"obs_report: {report['events']['invalid']} invalid "
                  "event lines", file=sys.stderr)
            rc = 1
        if report["events"]["seq_gaps"]:
            # Lost lines ARE the integrity failure the seq field exists
            # to detect — a torn timeline must fail the smoke gate.
            print(f"obs_report: {report['events']['seq_gaps']} seq gaps "
                  "(lost event lines)", file=sys.stderr)
            rc = 1
        if args.client_trace:
            try:
                rows = [json.loads(line) for line in Path(
                    args.client_trace).read_text().splitlines()
                    if line.strip()]
            except (OSError, ValueError) as e:
                print(f"obs_report: unreadable client trace: {e}",
                      file=sys.stderr)
                return 1
            report["client_join"] = merge_client_trace(rows, recs)
    elif args.client_trace:
        print("obs_report: --client-trace needs --events", file=sys.stderr)
        return 2
    if args.metrics:
        try:
            snap = json.loads(Path(args.metrics).read_text())
        except (OSError, ValueError) as e:
            print(f"obs_report: unreadable metrics snapshot: {e}",
                  file=sys.stderr)
            return 1
        report.update(summarize_metrics(snap))

    if not args.quiet:
        _print_human(report)
    if args.out:
        p = Path(args.out)
        p.parent.mkdir(parents=True, exist_ok=True)
        p.write_text(json.dumps(report, indent=2))
    else:
        print(json.dumps(report))
    return rc


if __name__ == "__main__":
    sys.exit(main())
