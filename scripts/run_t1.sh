#!/bin/bash
# Tier-1 verify, encoded ONCE — this is the ROADMAP.md "Tier-1 verify"
# command verbatim (keep the two in sync; the ROADMAP line is the spec).
# bash, not sh: the verbatim command needs pipefail + PIPESTATUS.
# Run from anywhere: resolves to the repo root first.
#
#   scripts/run_t1.sh                  the tier-1 pytest gate
#   scripts/run_t1.sh --mg-smoke       multigrid V-cycle + kernel-form
#                                      registry end-to-end on the 2x4 CPU
#                                      mesh: converge a seeded Poisson
#                                      problem both ways (same stopping
#                                      measure), gate the >=10x fine-grid
#                                      work-unit ratio and the oracle
#                                      agreement, prove every backend
#                                      byte-identical through the
#                                      registry with warm compiles flat,
#                                      and fold the convergence rows
#                                      through perf_gate.py against the
#                                      smoke's own history.  Row lands in
#                                      evidence/mg_smoke.json (the
#                                      supervisor leg's done_file).
#   scripts/run_t1.sh --router-smoke   replica-set router end-to-end on the
#                                      CPU mesh: 3 in-process replicas
#                                      (2x2 each) behind the consistent-
#                                      hash router with tenant quotas, 100
#                                      requests across 2 tenants, one KEY-
#                                      HOME replica killed mid-run.  Gates:
#                                      zero non-rejected failures, every
#                                      completed byte-identical to the
#                                      oracle, >= 1 observed failover,
#                                      greedy-tenant quota sheds typed
#                                      retryable while the polite tenant
#                                      sees none, warm caches partitioned
#                                      (each key on exactly one replica
#                                      pre-kill, <= home+1 after), and the
#                                      summary row passes perf_gate.py
#                                      against the smoke's own history.
#                                      Row (failures: 0) lands in
#                                      evidence/router_smoke.json (the
#                                      supervisor leg's done_file).
#   scripts/run_t1.sh --scale-smoke    fleet autoscaling end-to-end on the
#                                      CPU mesh (round 17): 1 replica
#                                      behind the router + autoscaler +
#                                      cost-priced admission; a fixed-RPS
#                                      Poisson load curve lands in
#                                      evidence/scale_curve.jsonl, a
#                                      saturation pack grows the pool
#                                      (the newcomer PRE-WARMS its ring
#                                      shard before its vnodes join —
#                                      per-key compile ledger gated
#                                      flat), idle shrinks it back, and a
#                                      greedy converge tenant is priced
#                                      out (work-unit buckets) while the
#                                      polite tenant's p99 stays within
#                                      its stated bound.  Rows fold
#                                      through perf_gate.py against the
#                                      smoke's own history, incl. a
#                                      synthetic 2x-p99 row that must
#                                      FAIL.  Row (failures: 0) lands in
#                                      evidence/scale_smoke.json (the
#                                      supervisor leg's done_file).
#   scripts/run_t1.sh --chaos-smoke    durable convergence jobs + chaos
#                                      transport (round 18): 3 in-process
#                                      replicas behind the durable router,
#                                      every transport chaos-wrapped;
#                                      mixed batch/converge traffic under
#                                      a seeded transport-fault schedule
#                                      (drops, latency, lost/corrupt
#                                      responses, flapping readiness,
#                                      mid-stream disconnects) plus a
#                                      mid-stream replica kill.  Gates:
#                                      zero non-rejected failures, every
#                                      completion byte-identical to the
#                                      uninterrupted oracle (incl. RESUMED
#                                      converge finals), >= 1 mid-stream
#                                      resume, exactly one final row per
#                                      request_id, resumed jobs charged
#                                      incremental work only, chaos
#                                      counters consistent with the
#                                      injected schedule, and the summary
#                                      row passes perf_gate.py against the
#                                      smoke's own history.  Row
#                                      (failures: 0) lands in
#                                      evidence/chaos_smoke.json (the
#                                      supervisor leg's done_file).
#   scripts/run_t1.sh --wal-smoke      crash-safe control plane (round 19):
#                                      3 in-process replicas behind the
#                                      WAL-backed durable router.  A
#                                      converge stream is interrupted by a
#                                      seeded router_kill crash; a second
#                                      router takes over the SAME WAL
#                                      (fenced: the epoch bumps past every
#                                      replica's own fence) and the
#                                      client's retry RESUMES from the
#                                      recovered token.  Gates: final row
#                                      byte-identical to the uninterrupted
#                                      oracle, exactly one final row per
#                                      request_id across both router
#                                      lives, the zombie router's writes
#                                      rejected typed stale_epoch,
#                                      wal_write faults degrade durability
#                                      loudly but never serving, torn-tail
#                                      WAL damage tolerated while mid-log
#                                      corruption quarantines typed, and
#                                      the die-takeover-resume saga
#                                      charged exactly one uninterrupted
#                                      job (frozen quota clock).  Row
#                                      (failures: 0) lands in
#                                      evidence/wal_smoke.json (the
#                                      supervisor leg's done_file).
#   scripts/run_t1.sh --wire-smoke     binary data plane + continuous
#                                      batching A/B (round 20): the codec
#                                      crossover curve (JSON vs tensor-
#                                      frame envelope encode+decode),
#                                      byte-identity of both arms on
#                                      /v1/convolve and a /v1/converge
#                                      stream, and the drain-vs-refill
#                                      batcher scale curve (same
#                                      synthetic host/device load) land
#                                      in evidence/wire_ab.jsonl; then
#                                      perf_gate.py --wire-ab holds
#                                      identity, frames-beats-JSON at
#                                      >= 64 KB, and the >= 1.2x refill
#                                      knee.  Gate report (wire_ab_flags:
#                                      []) lands in
#                                      evidence/wire_gate.json (the
#                                      supervisor leg's done_file).
#   scripts/run_t1.sh --shard-smoke    sharded control plane (round 21):
#                                      3 active routers over a 3-shard
#                                      partition of the hash ring, each
#                                      owning its own WAL lineage.  A
#                                      shard-aware client routes by the
#                                      version-stamped map; one router
#                                      is SIGKILLed mid-converge-stream
#                                      and the deterministic surviving
#                                      successor performs the fenced
#                                      takeover of the orphaned lineage
#                                      (epoch bump, per-shard fence
#                                      sweep, byte-identical resume,
#                                      exactly one final per request_id,
#                                      zombie writes rejected typed
#                                      stale_epoch) while the OTHER
#                                      shards serve with zero
#                                      non-rejected failures.  Tenant
#                                      debt replicates peer-to-peer so
#                                      quotas shed fleet-wide, and the
#                                      1/2/3-router scale lane
#                                      (lane: router_scale in
#                                      evidence/scale_curve.jsonl) must
#                                      clear perf_gate --router-scale
#                                      (3-router RPS >= 2.4x the
#                                      1-router knee, p99 in band).
#                                      Row (failures: 0) lands in
#                                      evidence/shard_smoke.json (the
#                                      supervisor leg's done_file); the
#                                      lane gate report in
#                                      evidence/shard_gate.json.
#   scripts/run_t1.sh --cache-smoke    content-addressed result cache
#                                      (round 22): a 100%-duplicate tail
#                                      must be served entirely from the
#                                      cache (every response stamped
#                                      cache: hit + digest, byte-identical
#                                      to the oracle, engine compile/
#                                      batch/image counters EXACTLY flat);
#                                      a converge job's final re-streams
#                                      as one cached hit row; a WAL drill
#                                      journals an entry dead, "crashes"
#                                      before the disk bytes drop, and
#                                      the recovered cache must refuse
#                                      them (never-resurrect) while a
#                                      live neighbor IS adopted from
#                                      disk; zipf(S) traffic at several
#                                      skews + an all-unique on/off A/B
#                                      land as lane: cache_skew rows in
#                                      evidence/scale_curve.jsonl and
#                                      must clear perf_gate --cache-lane
#                                      (hit rate rising with skew, hit
#                                      p99 decisively under miss p99,
#                                      the unique arm untaxed) — and a
#                                      synthetic flat-hit-rate lane must
#                                      FAIL it.  Row (failures: 0) lands
#                                      in evidence/cache_smoke.json (the
#                                      supervisor leg's done_file); the
#                                      lane gate report in
#                                      evidence/cache_gate.json.
#   scripts/run_t1.sh --volume-smoke   rank-3 volumetric subsystem (round
#                                      23) end-to-end on the 2x4 CPU
#                                      mesh: every registered rank-3 form
#                                      (fd7/fd25 + _stack twins, wave,
#                                      grayscott) vs the independent
#                                      float64 numpy oracle, the _stack
#                                      twins and the 1x1-vs-2x4 runs
#                                      byte-identical (the decomposition
#                                      invisible); the 8th-order 25-point
#                                      star's equal-accuracy convergence
#                                      win on the periodic manufactured
#                                      Poisson problem (sweep ratio >
#                                      1.5x, measured ~5x); a volume
#                                      served on both wires (JSON +
#                                      binary frames, byte-identical)
#                                      plus a Gray-Scott converge
#                                      stream vs the oracle; and the
#                                      rank-3-stamped throughput rows
#                                      folded through perf_gate.py
#                                      (row_key lanes them via |rank=3)
#                                      against the smoke's own history.
#                                      Row (failures: 0) lands in
#                                      evidence/volume_smoke.json (the
#                                      supervisor leg's done_file).
#   scripts/run_t1.sh --storage-smoke  storage-fault survival (round 24):
#                                      the unified chaos matrix crosses
#                                      every disk fault mode {ENOSPC,
#                                      EIO, torn-write, slow-write,
#                                      process kill} with every workload
#                                      shape {batch JSON, batch frames,
#                                      converge resume, rank-3 volume
#                                      stream, cross-shard takeover,
#                                      cache hit/spill}, one seeded cell
#                                      per pair, gating the standing
#                                      invariants in every cell: zero
#                                      non-typed failures, byte-identical
#                                      or typed-retryable completions,
#                                      exactly-once finals, no stale-byte
#                                      serves, and the fault actually
#                                      fired.  Site drills cover
#                                      events_emit (dropped lines, never
#                                      a raise) and evidence_write (typed
#                                      before any byte moves); the ENOSPC
#                                      degrade drill proves the
#                                      durability ladder: serve through a
#                                      degraded-durability window
#                                      (stamped on every response),
#                                      re-arm on heal with a live-state
#                                      compaction snapshot, and a
#                                      post-heal replay that resurrects
#                                      nothing stale.  Row (failures: 0)
#                                      lands in
#                                      evidence/storage_smoke.json (the
#                                      supervisor leg's done_file); the
#                                      lane gate report in
#                                      evidence/storage_gate.json.
#   scripts/run_t1.sh --static         fast static gate (no jax): every
#                                      .py byte-compiles, no bare
#                                      'except:', every mutation of a
#                                      shared stats dict under serving/
#                                      sits inside a lock-holding 'with',
#                                      and shared evidence curves are
#                                      written only through evidence_io.
#                                      Row (failures: 0) lands in
#                                      evidence/static_check.json.
#   scripts/run_t1.sh --list-legs      print the supervisor leg registry
#                                      (scripts/t1_legs.json) one leg per
#                                      line: name, command, done_file and
#                                      done_pattern.  The registry's
#                                      schema (every leg runs an existing
#                                      script, evidence outputs unique,
#                                      done_pattern iff done_file) is
#                                      enforced by tests/test_t1_legs.py.
#   scripts/run_t1.sh --serving-smoke  boot the in-process serving stack on
#                                      the 8-virtual-device CPU mesh, push
#                                      50 loadgen requests, exit nonzero on
#                                      ANY non-rejected failure (typed load
#                                      sheds are permitted, errors are not).
#                                      Row lands in evidence/serving_smoke.json
#                                      (the supervisor leg's done_file —
#                                      see scripts/t1_legs.json).
#   scripts/run_t1.sh --tuning-smoke   dry-run (model-only) tune on the 2x4
#                                      CPU mesh: emits a plan file, then
#                                      proves backend='auto' resolves FROM
#                                      it (auto_ok in the summary row —
#                                      evidence/tuning_smoke.json, the
#                                      supervisor leg's done_file).
#   scripts/run_t1.sh --obs-smoke      observability end-to-end on the 2x4
#                                      CPU mesh: boot the service with obs
#                                      on, push HTTP traffic, assert
#                                      /metrics parses, the event log
#                                      validates against the obs.events
#                                      schema, and obs_report.py exits 0.
#                                      Row (failures: 0) lands in
#                                      evidence/obs_smoke.json (the
#                                      supervisor leg's done_file).
#   scripts/run_t1.sh --trace-smoke    tracing + perf sentry end-to-end on
#                                      the 2x4 CPU mesh: serve 50 traced
#                                      in-process requests, assert every
#                                      response carries a trace_id, the
#                                      span trees reconstruct complete
#                                      (one root, zero orphans, batch
#                                      spans linking all co-batched
#                                      requests), the client/server
#                                      trace join covers every request,
#                                      and perf_gate.py passes against a
#                                      freshly seeded history while
#                                      flagging a synthetic 2x-slower
#                                      row.  Row (failures: 0) lands in
#                                      evidence/trace_smoke.json (the
#                                      supervisor leg's done_file).
#   scripts/run_t1.sh --overlap-smoke  overlapped-halo A/B on the 2x4 CPU
#                                      mesh: rdma overlap on/off per fuse
#                                      level, oracle byte-checks on every
#                                      cell, plus the degenerate-grid
#                                      overlap-vs-serialized proofs that
#                                      run on any jax (multi-device RDMA
#                                      cells become typed capability
#                                      skips on a jax without the
#                                      DMA-faithful interpreter).
#                                      Summary (failures: 0 = the
#                                      byte-equality gate) lands in
#                                      evidence/overlap_smoke.json (the
#                                      supervisor leg's done_file).
#   scripts/run_t1.sh --channels-smoke persistent/partitioned halo channels
#                                      (round 16) on the 2x4 CPU mesh:
#                                      byte-identity across {serialized,
#                                      r12 overlap, persistent+partitioned}
#                                      x {packed, strided} (degenerate 1x1
#                                      proofs always; multi-device cells
#                                      typed capability skips without the
#                                      faithful interpreter), channel-plan
#                                      build counter flat across a fused
#                                      converge run and a V-cycle level
#                                      schedule (descriptors bound once
#                                      per exchange identity), col_mode
#                                      auto-resolution + bench-row
#                                      stamping, and the summary row
#                                      folded through perf_gate.py against
#                                      the smoke's own history.  Row
#                                      (failures: 0) lands in
#                                      evidence/channels_smoke.json (the
#                                      supervisor leg's done_file).
#   scripts/run_t1.sh --elastic-smoke  reshape round-trip on the CPU mesh:
#                                      crash a checkpointed run on 2x4,
#                                      resume the snapshot on 1x2 / 2x2 /
#                                      1x1 (grid-agnostic reshard), every
#                                      output byte-compared to the oracle.
#                                      Summary row (failures: 0) lands in
#                                      evidence/elastic_smoke.json (the
#                                      supervisor leg's done_file).
cd "$(dirname "$0")/.." || exit 1

if [ "${1:-}" = "--obs-smoke" ]; then
  exec timeout -k 10 600 env JAX_PLATFORMS=cpu \
    XLA_FLAGS="--xla_force_host_platform_device_count=8" \
    PCTPU_OBS=1 \
    python scripts/obs_smoke.py --n 24 --rows 48 --cols 64 --iters 2 \
      --mesh 2x4 --out evidence/obs_smoke.json
fi

if [ "${1:-}" = "--trace-smoke" ]; then
  exec timeout -k 10 600 env JAX_PLATFORMS=cpu \
    XLA_FLAGS="--xla_force_host_platform_device_count=8" \
    PCTPU_OBS=1 \
    python scripts/trace_smoke.py --n 50 --rows 48 --cols 64 --iters 2 \
      --mesh 2x4 --out evidence/trace_smoke.json
fi

if [ "${1:-}" = "--overlap-smoke" ]; then
  exec timeout -k 10 600 env JAX_PLATFORMS=cpu \
    XLA_FLAGS="--xla_force_host_platform_device_count=8" \
    python scripts/rdma_fuse_ab.py --overlap --size 64 --iters 4 \
      --reps 1 --fuse 1,2,4 --mesh 2x4 --out evidence/overlap_smoke.json
fi

if [ "${1:-}" = "--channels-smoke" ]; then
  exec timeout -k 10 600 env JAX_PLATFORMS=cpu \
    XLA_FLAGS="--xla_force_host_platform_device_count=8" \
    python scripts/channels_smoke.py --rows 48 --cols 64 --mesh 2x4 \
      --out evidence/channels_smoke.json
fi

if [ "${1:-}" = "--elastic-smoke" ]; then
  exec timeout -k 10 600 env JAX_PLATFORMS=cpu \
    XLA_FLAGS="--xla_force_host_platform_device_count=8" \
    python scripts/soak.py --reshape 2 --seed 0 \
      --summary-out evidence/elastic_smoke.json
fi

if [ "${1:-}" = "--tuning-smoke" ]; then
  exec timeout -k 10 300 env JAX_PLATFORMS=cpu \
    XLA_FLAGS="--xla_force_host_platform_device_count=8" \
    python scripts/tune.py --rows 48 --cols 64 --mode grey \
      --filter blur3 --iters 2 --mesh 2x4 --dry-run \
      --emit-plans --out evidence/tuning_smoke_plans.json \
      --verify-auto --summary-out evidence/tuning_smoke.json
fi

if [ "${1:-}" = "--mg-smoke" ]; then
  exec timeout -k 10 600 env JAX_PLATFORMS=cpu \
    XLA_FLAGS="--xla_force_host_platform_device_count=8" \
    python scripts/mg_smoke.py --rows 96 --cols 64 --mesh 2x4 \
      --out evidence/mg_smoke.json
fi

if [ "${1:-}" = "--scale-smoke" ]; then
  exec timeout -k 10 600 env JAX_PLATFORMS=cpu \
    XLA_FLAGS="--xla_force_host_platform_device_count=8" \
    PCTPU_OBS=1 \
    python scripts/scale_smoke.py --rows 48 --cols 64 --mesh 1x2 \
      --out evidence/scale_smoke.json
fi

if [ "${1:-}" = "--wal-smoke" ]; then
  exec timeout -k 10 600 env JAX_PLATFORMS=cpu \
    XLA_FLAGS="--xla_force_host_platform_device_count=8" \
    PCTPU_OBS=1 \
    python scripts/wal_smoke.py --n 12 --rows 40 --cols 56 \
      --mesh 1x2 --out evidence/wal_smoke.json
fi

if [ "${1:-}" = "--wire-smoke" ]; then
  timeout -k 10 600 env JAX_PLATFORMS=cpu \
    XLA_FLAGS="--xla_force_host_platform_device_count=8" \
    python scripts/wire_ab.py --quick --out evidence/wire_ab.jsonl \
    || exit 1
  exec timeout -k 10 120 \
    python scripts/perf_gate.py --wire-ab evidence/wire_ab.jsonl \
      --out evidence/wire_gate.json
fi

if [ "${1:-}" = "--shard-smoke" ]; then
  exec timeout -k 10 600 env JAX_PLATFORMS=cpu \
    XLA_FLAGS="--xla_force_host_platform_device_count=8" \
    PCTPU_OBS=1 \
    python scripts/shard_smoke.py --n 12 --rows 24 --cols 32 \
      --mesh 1x2 --out evidence/shard_smoke.json
fi

if [ "${1:-}" = "--cache-smoke" ]; then
  exec timeout -k 10 600 env JAX_PLATFORMS=cpu \
    XLA_FLAGS="--xla_force_host_platform_device_count=8" \
    PCTPU_OBS=1 \
    python scripts/cache_smoke.py --mesh 1x2 \
      --out evidence/cache_smoke.json
fi

if [ "${1:-}" = "--volume-smoke" ]; then
  exec timeout -k 10 600 env JAX_PLATFORMS=cpu \
    XLA_FLAGS="--xla_force_host_platform_device_count=8" \
    python scripts/volume_smoke.py --mesh 2x4 \
      --out evidence/volume_smoke.json
fi

if [ "${1:-}" = "--storage-smoke" ]; then
  exec timeout -k 10 600 env JAX_PLATFORMS=cpu \
    XLA_FLAGS="--xla_force_host_platform_device_count=8" \
    PCTPU_OBS=1 \
    python scripts/chaos_matrix.py --rows 40 --cols 56 --mesh 1x2 \
      --out evidence/storage_smoke.json \
      --gate-out evidence/storage_gate.json
fi

if [ "${1:-}" = "--static" ]; then
  exec timeout -k 10 120 \
    python scripts/static_check.py --out evidence/static_check.json
fi

if [ "${1:-}" = "--list-legs" ]; then
  exec python - scripts/t1_legs.json <<'PYEOF'
import json, sys
for leg in json.load(open(sys.argv[1])):
    done = (f"{leg['done_file']} ~ {leg['done_pattern']}"
            if leg.get("done_file") else "-")
    print(f"{leg['name']:16s} {' '.join(leg['cmd']):44s} {done}")
PYEOF
fi

if [ "${1:-}" = "--chaos-smoke" ]; then
  exec timeout -k 10 600 env JAX_PLATFORMS=cpu \
    XLA_FLAGS="--xla_force_host_platform_device_count=8" \
    PCTPU_OBS=1 \
    python scripts/chaos_smoke.py --n 30 --rows 40 --cols 56 \
      --mesh 1x2 --volume --out evidence/chaos_smoke.json
fi

if [ "${1:-}" = "--router-smoke" ]; then
  exec timeout -k 10 600 env JAX_PLATFORMS=cpu \
    XLA_FLAGS="--xla_force_host_platform_device_count=8" \
    PCTPU_OBS=1 \
    python scripts/router_smoke.py --n 100 --rows 48 --cols 64 \
      --mesh 2x2 --out evidence/router_smoke.json
fi

if [ "${1:-}" = "--serving-smoke" ]; then
  exec timeout -k 10 600 env JAX_PLATFORMS=cpu \
    XLA_FLAGS="--xla_force_host_platform_device_count=8" \
    python scripts/loadgen.py --in-process --n 50 --concurrency 4 \
      --rows 48 --cols 64 --mode grey --filter blur3 --iters 2 \
      --mesh 2x4 --max-batch 8 --max-delay-ms 5 --check \
      --out evidence/serving_smoke.json
fi

set -o pipefail
rm -f /tmp/_t1.log
timeout -k 10 870 env JAX_PLATFORMS=cpu python -m pytest tests/ -q -m 'not slow' --continue-on-collection-errors -p no:cacheprovider -p no:xdist -p no:randomly 2>&1 | tee /tmp/_t1.log
rc=${PIPESTATUS[0]}
echo DOTS_PASSED=$(grep -aE '^[.FEsx]+( *\[ *[0-9]+%\])?$' /tmp/_t1.log | tr -cd . | wc -c)
exit $rc
