#!/usr/bin/env python
"""Tracing + perf-sentry smoke: the ``run_t1.sh --trace-smoke`` leg.

Serve N traced requests through the in-process client on the CPU mesh
with obs ON, then assert the whole round-13 layer held together:

1. every response carries a server-assigned ``trace_id``, and a request
   sent WITH a ``traceparent`` adopts the caller's trace id (context
   propagation);
2. ``/readyz`` (socket-free twin) reports ready on the idle service;
3. ``scripts/trace_report.py`` reconstructs COMPLETE span trees —
   exactly one root per trace, zero orphan spans — and the union of
   batch-span links covers every completed request's trace; the Chrome
   ``trace_event`` export parses as JSON;
4. ``scripts/obs_report.py --client-trace`` joins every client-side row
   to its server-side trace;
5. ``scripts/perf_gate.py``: seeding a FRESH history with this run's
   measured row passes, re-gating the same row against the seeded
   history passes (within noise), and a synthetic 2x-slower row exits
   NONZERO — the sentry demonstrably bites.

One summary row lands in ``--out`` (``evidence/trace_smoke.json``, the
supervisor leg's done_file) with ``"failures": 0`` iff every gate held.
"""

from __future__ import annotations

import argparse
import base64
import json
import subprocess
import sys
import threading
import time
from pathlib import Path

import _path  # noqa: F401  (repo root + JAX_PLATFORMS re-apply)

SCRIPTS = Path(__file__).resolve().parent


def main() -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--n", type=int, default=50, help="requests to push")
    ap.add_argument("--concurrency", type=int, default=4)
    ap.add_argument("--rows", type=int, default=48)
    ap.add_argument("--cols", type=int, default=64)
    ap.add_argument("--iters", type=int, default=2)
    ap.add_argument("--mesh", default="2x4")
    ap.add_argument("--events", default="evidence/trace_events.jsonl")
    ap.add_argument("--client-out", default="evidence/trace_client.jsonl")
    ap.add_argument("--report-out", default="evidence/trace_report.json")
    ap.add_argument("--chrome-out", default="evidence/trace_chrome.json")
    ap.add_argument("--metrics-out", default="evidence/trace_metrics.json")
    ap.add_argument("--history", default="evidence/trace_smoke_history.jsonl",
                    help="the smoke's OWN history file, seeded FRESH each "
                         "run (hermetic gate).  Deliberately NOT "
                         "evidence/perf_history.jsonl — that one is the "
                         "committed append-only baseline real sessions "
                         "accumulate into; a smoke must never truncate it")
    ap.add_argument("--out", default="evidence/trace_smoke.json")
    args = ap.parse_args()

    import numpy as np

    from parallel_convolution_tpu.obs import (
        events as obs_events, metrics, trace as trace_lib,
    )
    from parallel_convolution_tpu.utils import imageio

    if not metrics.enabled():
        metrics.set_enabled(True)  # the smoke TESTS obs: force it on
    ev_path = Path(args.events)
    ev_path.parent.mkdir(parents=True, exist_ok=True)
    for gen in ("", ".1", ".2"):
        p = ev_path.with_name(ev_path.name + gen)
        if p.exists():
            p.unlink()  # a fresh timeline per smoke run
    obs_events.configure(ev_path)

    from parallel_convolution_tpu.parallel.mesh import mesh_from_spec
    from parallel_convolution_tpu.serving.frontend import InProcessClient
    from parallel_convolution_tpu.serving.service import ConvolutionService

    failures: list[str] = []
    service = ConvolutionService(mesh_from_spec(args.mesh), max_batch=8,
                                 max_delay_s=0.005, max_queue=256)
    client = InProcessClient(service)

    img = imageio.generate_test_image(args.rows, args.cols, "grey", seed=0)
    body = {
        "image_b64": base64.b64encode(
            np.ascontiguousarray(img).tobytes()).decode("ascii"),
        "rows": args.rows, "cols": args.cols, "mode": "grey",
        "filter": "blur3", "iters": args.iters, "backend": "shifted",
    }

    # Gate 2 first (idle service): the readiness twin says ready.
    status, ready = client.readyz()
    if status != 200 or not ready.get("ok"):
        failures.append(f"/readyz not ready on idle service: {ready}")

    # One request WITH an upstream traceparent: propagation proof.
    upstream = trace_lib.SpanContext(trace_lib.new_trace_id(),
                                     trace_lib.new_span_id())
    s0, r0 = client.request(
        dict(body, request_id="tp0",
             traceparent=trace_lib.format_traceparent(upstream)),
        timeout=120)
    if s0 != 200 or r0.get("trace_id") != upstream.trace_id:
        failures.append(
            f"traceparent not adopted: status {s0}, "
            f"trace_id {r0.get('trace_id')!r} != {upstream.trace_id!r}")

    results: list[tuple[int, float, int, dict]] = []
    lock = threading.Lock()
    counter = iter(range(args.n))

    def worker():
        while True:
            with lock:
                i = next(counter, None)
            if i is None:
                return
            t0 = time.perf_counter()
            s, r = client.request(dict(body, request_id=f"tr{i}"),
                                  timeout=120)
            lat = time.perf_counter() - t0
            with lock:
                results.append((i, lat, s, r))

    t_start = time.perf_counter()
    threads = [threading.Thread(target=worker, daemon=True)
               for _ in range(max(1, args.concurrency))]
    for th in threads:
        th.start()
    for th in threads:
        th.join()
    wall = time.perf_counter() - t_start

    completed = [(i, lat, r) for i, lat, s, r in results
                 if s == 200 and r.get("ok")]
    if len(completed) != args.n:
        failures.append(f"only {len(completed)}/{args.n} completed")
    missing_tid = [i for i, _, r in completed if not r.get("trace_id")]
    if missing_tid:
        failures.append(
            f"{len(missing_tid)} responses without a trace_id")

    # Client-side rows (the loadgen --trace-out schema) for the join.
    cp = Path(args.client_out)
    cp.parent.mkdir(parents=True, exist_ok=True)
    with open(cp, "w") as f:
        for i, lat, s, r in sorted(results):
            f.write(json.dumps({
                "request_id": r.get("request_id") or f"tr{i}",
                "trace_id": r.get("trace_id", ""),
                "ts": 0.0, "latency_ms": round(1e3 * lat, 3),
                "status": s, "ok": bool(r.get("ok")),
            }) + "\n")

    service.close()
    metrics.dump(args.metrics_out)

    # Gate 3: trace_report reconstructs complete trees.
    report_ok = False
    rc = subprocess.run(
        [sys.executable, str(SCRIPTS / "trace_report.py"),
         "--events", str(ev_path), "--out", args.report_out,
         "--chrome", args.chrome_out, "--quiet"],
        capture_output=True, text=True)
    if rc.returncode != 0:
        failures.append(f"trace_report.py exited {rc.returncode}: "
                        f"{(rc.stderr or '').strip()[:300]}")
    else:
        rep = json.loads(Path(args.report_out).read_text())
        if rep["orphan_spans"] or not rep["roots_per_trace_ok"]:
            failures.append(
                f"span trees incomplete: {rep['orphan_spans']} orphans, "
                f"multi_root={rep['multi_root_traces']}")
        else:
            linked = set()
            for b in rep["batches"]:
                linked.update(b["linked_traces"])
            resp_tids = {r["trace_id"] for _, _, r in completed
                         if r.get("trace_id")}
            if not resp_tids <= linked:
                failures.append(
                    f"{len(resp_tids - linked)} completed traces not "
                    "linked by any batch span")
            else:
                report_ok = True
        try:
            json.loads(Path(args.chrome_out).read_text())["traceEvents"]
        except Exception as e:  # noqa: BLE001
            failures.append(f"chrome export unreadable: {e!r}")

    # Gate 4: the client/server join covers every completed request.
    join_ok = False
    jr = subprocess.run(
        [sys.executable, str(SCRIPTS / "obs_report.py"),
         "--events", str(ev_path), "--client-trace", str(cp),
         "--quiet"],
        capture_output=True, text=True)
    if jr.returncode != 0:
        failures.append(f"obs_report.py --client-trace exited "
                        f"{jr.returncode}")
    else:
        cj = json.loads(jr.stdout.strip().splitlines()[-1]).get(
            "client_join", {})
        if cj.get("joined", 0) < len(completed):
            failures.append(f"client/server join incomplete: {cj}")
        else:
            join_ok = True

    # Gate 5: the perf sentry — seed fresh, re-gate, and prove it bites.
    gate_ok = False
    hist = Path(args.history)
    if hist.exists():
        hist.unlink()  # hermetic: fresh seed per smoke run
    channels = 1
    px = args.rows * args.cols * channels * args.iters * len(completed)
    row = {
        "workload": (f"serve blur3 {args.rows}x{args.cols}x{channels} "
                     f"{args.iters} iters"),
        "backend": "shifted",
        "effective_backend": "shifted",
        "plan_key": next((r.get("plan_key", "")
                          for _, _, r in completed), ""),
        "mesh": args.mesh,
        "completed": len(completed),
        "gpixels_per_s": round(px / wall / 1e9, 6) if wall else 0.0,
    }
    row_path = Path("evidence/trace_smoke_row.json")
    row_path.write_text(json.dumps(row, indent=2))
    slow = dict(row, gpixels_per_s=row["gpixels_per_s"] / 2)
    slow_path = Path("evidence/trace_smoke_row_slow.json")
    slow_path.write_text(json.dumps(slow))

    def gate(*extra):
        return subprocess.run(
            [sys.executable, str(SCRIPTS / "perf_gate.py"),
             "--history", str(hist), "--quiet", *extra],
            capture_output=True, text=True).returncode

    rc_seed = gate("--row", str(row_path), "--update")
    rc_pass = gate("--row", str(row_path))
    rc_slow = gate("--row", str(slow_path))
    slow_path.unlink()
    if rc_seed != 0:
        failures.append(f"perf_gate seed run exited {rc_seed}")
    elif rc_pass != 0:
        failures.append(f"perf_gate within-noise rerun exited {rc_pass}")
    elif rc_slow == 0:
        failures.append("perf_gate did NOT flag the synthetic 2x-slower "
                        "row")
    else:
        gate_ok = True

    summary = {
        "workload": (f"trace smoke blur3 {args.rows}x{args.cols} "
                     f"{args.iters} iters, {args.n} in-process requests"),
        "mesh": args.mesh,
        "completed": len(completed),
        "wall_s": round(wall, 3),
        "gpixels_per_s": row["gpixels_per_s"],
        "traceparent_propagated": s0 == 200
        and r0.get("trace_id") == upstream.trace_id,
        "report_ok": report_ok,
        "join_ok": join_ok,
        "perf_gate_ok": gate_ok,
        "failures": len(failures),
        **({"failure_sample": failures[:5]} if failures else {}),
    }
    out = Path(args.out)
    out.parent.mkdir(parents=True, exist_ok=True)
    out.write_text(json.dumps(summary, indent=2))
    print(json.dumps(summary), flush=True)
    return 1 if failures else 0


if __name__ == "__main__":
    sys.exit(main())
