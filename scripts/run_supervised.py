#!/usr/bin/env python
"""Supervised leg-queue runner — the tested successor to the shell era.

``tunnel_watch.sh`` + ``chip_session_r5*.sh`` (now marked superseded)
encoded retry-on-transient, idempotent leg completion, and the
terminal-failure sentinel in copy-pasted shell nobody could test.  This
CLI drives the same workflow through
``parallel_convolution_tpu.resilience.supervisor``: one JSON legs file
in, a JSON status ledger + per-leg stdout/stderr captures + (on terminal
failure) a ``HALT`` sentinel out.

Legs file: a JSON list of objects with fields
  name              unique leg name (required)
  cmd               argv list (required)
  done_file         completion artifact path (optional; else rc==0)
  done_pattern      regex the artifact must contain (optional)
  terminal_pattern  regex in stdout+stderr marking an unretryable failure
                    (e.g. '"magic_round_guard": "MISMATCH"')
  timeout           per-attempt seconds (optional)
  env               extra environment vars (optional)

Example — the round-5 chip session, as data instead of shell::

  [
    {"name": "bench_sanity",
     "cmd": ["python", "bench.py"],
     "done_file": "evidence/bench_sanity.json",
     "done_pattern": "\\"best_backend\\"",
     "terminal_pattern": "\\"magic_round_guard\\": \\"MISMATCH\\"",
     "timeout": 1800},
    {"name": "soak",
     "cmd": ["python", "scripts/soak.py", "--n", "20"],
     "done_file": "evidence/soak.jsonl",
     "done_pattern": "\\"summary\\"",
     "timeout": 1800}
  ]

Exit codes: 0 all legs complete; 1 some leg exhausted its retries;
2 terminal halt (sentinel written — remove it only after fixing the
cause).  Re-running is always safe: completed legs are skipped and an
existing sentinel refuses to run.
"""

from __future__ import annotations

import argparse
import json
import shutil
import sys
from pathlib import Path

import _path  # noqa: F401  (repo root + JAX_PLATFORMS re-apply)

from parallel_convolution_tpu.obs import events as obs_events
from parallel_convolution_tpu.resilience.retry import RetryPolicy
from parallel_convolution_tpu.resilience.supervisor import (
    Supervisor, legs_from_json,
)


def main() -> int:
    obs_events.install_from_env()  # PCTPU_OBS_EVENTS: leg/heartbeat timeline
    ap = argparse.ArgumentParser(
        description=__doc__, formatter_class=argparse.RawDescriptionHelpFormatter)
    ap.add_argument("--legs", required=True,
                    help="JSON legs file (see module docstring)")
    ap.add_argument("--state-dir", default="supervised_state",
                    help="ledger + captures + HALT sentinel directory")
    ap.add_argument("--max-attempts", type=int, default=5)
    ap.add_argument("--base-delay", type=float, default=10.0,
                    help="first backoff (seconds); doubles per attempt")
    ap.add_argument("--max-delay", type=float, default=240.0,
                    help="backoff cap — the old watcher's 4-minute probe")
    ap.add_argument("--seed", type=int, default=0,
                    help="jitter seed (schedules are deterministic)")
    ap.add_argument("--status", action="store_true",
                    help="print the current ledger and exit")
    ap.add_argument("--clear-halt", action="store_true",
                    help="remove the HALT sentinel (after fixing the cause)")
    args = ap.parse_args()

    state = Path(args.state_dir)
    if args.status:
        ledger = state / "status.json"
        print(ledger.read_text() if ledger.exists()
              else json.dumps({"legs": {}, "halt": None}))
        return 0
    if args.clear_halt:
        halt = state / "HALT"
        if halt.exists():
            shutil.copy(halt, halt.with_suffix(".cleared"))
            halt.unlink()
            print(f"removed {halt} (copy kept at {halt}.cleared)")
        return 0

    legs = legs_from_json(Path(args.legs).read_text())
    sup = Supervisor(
        legs, state,
        policy=RetryPolicy(max_attempts=args.max_attempts,
                           base_delay=args.base_delay,
                           max_delay=args.max_delay, seed=args.seed),
    )
    return sup.run()


if __name__ == "__main__":
    sys.exit(main())
