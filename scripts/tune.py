#!/usr/bin/env python
"""Tune a workload and (optionally) persist the plan file.

The autotuning front door (``parallel_convolution_tpu/tuning/``):
enumerate the legal candidate space, rank it with the roofline cost
model, optionally refine with on-device measurement, and emit the
winning plan — which ``backend="auto"`` (CLI runs, ``ConvolutionModel``,
``utils.bench`` rows, ``scripts/serve.py --plans`` warmup) then resolves
through.

  # model-only (any machine, zero device work), merged into plans.json
  python scripts/tune.py --rows 4096 --cols 4096 --iters 20 \\
      --dry-run --emit-plans --out plans.json

  # measured on the real mesh (O(dozens) of compiles, model-pruned)
  python scripts/tune.py --rows 8192 --cols 8192 --storage bf16 \\
      --iters 20 --emit-plans --out plans.json

  # boot the service already tuned
  python scripts/serve.py --plans plans.json \\
      --warm '{"rows": 8192, "cols": 8192, "iters": 20, "backend": "auto"}'

One summary JSON row goes to stdout (and ``--summary-out``); with
``--verify-auto`` the row additionally proves the emitted file round-
trips — ``backend="auto"`` re-resolved against it must return the
just-written plan with its provenance (``auto_ok``), which is the
``run_t1.sh --tuning-smoke`` gate.
"""

from __future__ import annotations

import argparse
import json
import sys

import _path  # noqa: F401  (repo root + JAX_PLATFORMS re-apply)


def main() -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--rows", type=int, required=True)
    ap.add_argument("--cols", type=int, required=True)
    ap.add_argument("--mode", default="grey", choices=["grey", "rgb"])
    ap.add_argument("--filter", default="blur3", dest="filter_name")
    ap.add_argument("--iters", type=int, default=8,
                    help="iterations per measured rep")
    ap.add_argument("--storage", default="f32",
                    choices=["f32", "bf16", "u8"])
    ap.add_argument("--boundary", default="zero",
                    choices=["zero", "periodic"])
    ap.add_argument("--no-quantize", action="store_true")
    ap.add_argument("--check-every", type=int, default=None,
                    help="tune the CONVERGENCE-path program with this "
                         "check cadence: the cadence joins the plan key "
                         "and caps legal fusion at check_every-1 (the "
                         "chunk's final iteration forms the convergence "
                         "pair unfused)")
    ap.add_argument("--mesh", default=None,
                    help="RxC grid (default: all devices, near-square)")
    ap.add_argument("--backends", default=None,
                    help="comma list restricting the candidate backends")
    ap.add_argument("--fuses", default=None,
                    help="comma list restricting fusion depths")
    ap.add_argument("--tiles", default=None,
                    help="comma list of HxW tiles restricting the menu")
    ap.add_argument("--overlap", default="auto",
                    choices=["auto", "on", "off"],
                    help="overlapped halo pipeline dimension of the "
                         "candidate space: auto = enumerate both where "
                         "legal (RDMA tier, real collective, non-empty "
                         "interior), on/off = clamped request; the "
                         "winning plan persists its overlap verdict")
    ap.add_argument("--dry-run", action="store_true",
                    help="cost model only — no compiles, no device work; "
                         "the emitted plan carries source='predicted'")
    ap.add_argument("--reps", type=int, default=2)
    ap.add_argument("--max-measure", type=int, default=8,
                    help="measured-refinement budget (model-pruned)")
    ap.add_argument("--emit-plans", action="store_true",
                    help="write/merge the winning plan into --out (atomic; "
                         "existing other-key plans are preserved)")
    ap.add_argument("--out", default="plans.json",
                    help="plan-cache file for --emit-plans")
    ap.add_argument("--verify-auto", action="store_true",
                    help="after emitting, resolve backend='auto' against "
                         "the plan file and record auto_ok in the summary "
                         "(requires --emit-plans)")
    ap.add_argument("--summary-out", default=None,
                    help="also write the summary row to this path")
    args = ap.parse_args()
    if args.verify_auto and not args.emit_plans:
        ap.error("--verify-auto requires --emit-plans")

    from parallel_convolution_tpu.ops.filters import get_filter
    from parallel_convolution_tpu.parallel.mesh import mesh_from_spec
    from parallel_convolution_tpu.tuning import (
        PlanCache, Workload, resolve, search,
    )
    from parallel_convolution_tpu.utils.platform import enable_compile_cache

    if not args.dry_run:
        enable_compile_cache()
    mesh = mesh_from_spec(args.mesh)
    filt = get_filter(args.filter_name)
    channels = 3 if args.mode == "rgb" else 1
    shape = (channels, args.rows, args.cols)
    quantize = not args.no_quantize
    w = Workload.from_mesh(mesh, filt, shape, storage=args.storage,
                           quantize=quantize, boundary=args.boundary,
                           check_every=args.check_every)

    backends = args.backends.split(",") if args.backends else None
    fuses = ([int(v) for v in args.fuses.split(",")]
             if args.fuses else None)
    tiles = ([tuple(int(x) for x in t.split("x"))
              for t in args.tiles.split(",")] if args.tiles else None)

    overlap = {"auto": None, "on": True, "off": False}[args.overlap]
    result = search.tune(
        w, mesh=mesh, dry_run=args.dry_run, backends=backends,
        fuses=fuses, tiles=tiles, overlap=overlap, iters=args.iters,
        reps=args.reps, max_measure=args.max_measure)
    for row in result.rows:
        print(json.dumps(row), file=sys.stderr, flush=True)

    plan = result.plan
    summary = {
        "workload": {"shape": list(shape), "filter": filt.name,
                     "storage": args.storage, "quantize": quantize,
                     "boundary": args.boundary,
                     "check_every": args.check_every,
                     "mesh": f"{w.grid[0]}x{w.grid[1]}",
                     "platform": w.platform,
                     "device_kind": w.device_kind},
        "plan": plan.to_record(),
        "plan_key": w.key(),
        "measured_points": sum(1 for r in result.rows if "error" not in r),
        "errors": sum(1 for r in result.rows if "error" in r),
    }

    if args.emit_plans:
        cache = PlanCache()
        cache.put(w, plan)
        summary["plan_file"] = cache.merge_save(args.out)
        summary["plans_in_file"] = len(cache)

    if args.verify_auto:
        # Round-trip proof: auto against the just-written file must hand
        # back this plan, provenance intact — the tuning-smoke gate.
        res = resolve(mesh, filt, shape, storage=args.storage,
                      quantize=quantize, boundary=args.boundary,
                      check_every=args.check_every,
                      plans=PlanCache.load(args.out))
        summary["auto_resolved"] = {
            "backend": res.backend, "fuse": res.fuse,
            "tile": list(res.tile) if res.tile else None,
            "overlap": res.overlap,
            "plan_source": res.source,
        }
        summary["auto_ok"] = bool(
            res.backend == plan.backend and res.source == plan.source)

    line = json.dumps(summary)
    print(line, flush=True)
    if args.summary_out:
        import os

        os.makedirs(os.path.dirname(os.path.abspath(args.summary_out)),
                    exist_ok=True)
        with open(args.summary_out, "w", encoding="utf-8") as f:
            f.write(line + "\n")
    if args.verify_auto and not summary["auto_ok"]:
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())
