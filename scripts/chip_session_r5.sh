#!/bin/sh
# SUPERSEDED (resilience PR): express future chip sessions as a JSON legs
# file for scripts/run_supervised.py (tested retry/terminal logic in
# parallel_convolution_tpu/resilience/).  Kept as the round-5 record.
#
# Round-5 chip session: everything still waiting on TPU silicon, ordered
# by value so another tunnel outage costs the least.  Supersedes
# chip_session_r4b.sh (same legs 1-5, plus the round-5 additions).
#
#   1. flagship tile/fuse re-tune with the convex-clamp elision (the
#      headline number; +39% preliminary on pallas/f32/fuse1)
#   2. profiler trace + interior-split A/B (VERDICT r4 item 5: confirm or
#      correct the 1.47 TF/s VPU-ceiling claim, then one measured attempt
#      past it — the generalized split is that attempt)
#   3. u8-carry re-tune
#   4. rdma_on_silicon + tiled_repro_probe (VERDICT item 2: attribute the
#      tiled-kernel compile-helper crash to a construct)
#   5. validate_walls rerun (lost to the round-4 file-swap accident)
#   6. config-2 working-set-matched re-measure (VERDICT item 7: the
#      266.4 Gpx/s/chip row is a cache-resident artifact; measure the
#      same config at a working set matching the 8192^2 flagship)
#   7. bench.py sanity (isplit row now valid on any grid)
#
set -x
cd "$(dirname "$0")/.."

# Dead-tunnel guard: a dead tunnel makes jax HANG on backend init, which
# would eat the whole session window; fail fast instead.
timeout 60 python -c "import jax; print(jax.devices())" \
  || { echo "tunnel dead; aborting chip session" >&2; exit 1; }

# Per-leg timeout: the tunnel dies transiently MID-session too, and a
# dead tunnel makes the next leg's fresh python HANG in backend init —
# the start-of-session guard above only protects the first process.
LEG_TIMEOUT="${LEG_TIMEOUT:-2400}"

run_to() {
  out="$1"; shift
  if timeout "$LEG_TIMEOUT" "$@" \
       > "$out.tmp" 2> "/tmp/$(basename "$out").err"; then
    mv "$out.tmp" "$out" && echo "$out OK"
  else
    # Never leave a stale .tmp in evidence/ — it reads like a record.
    rm -f "$out.tmp"
    echo "$out FAILED (stderr: /tmp/$(basename "$out").err)" >&2
  fi
}

# 1. Flagship re-tune (bf16 carries, elision active since round 4).
run_to evidence/tune_convex_r5.jsonl \
  python scripts/tune_pallas.py --backend pallas_sep --storage bf16 \
    --iters 100 --tiles 1024x512,1536x512,2048x512,1024x768 --fuses 24,32,40

# 2. Trace + interior-split A/B at the flagship point.
run_to evidence/profile_flagship_r5.jsonl \
  python scripts/profile_flagship.py --size 8192 --fuse 32 --reps 3 --ab

# 3. u8 carries.
run_to evidence/tune_convex_r5_u8.jsonl \
  python scripts/tune_pallas.py --backend pallas_sep --storage u8 \
    --iters 100 --tiles 1024x512,2048x512 --fuses 32,40

# 4. RDMA: monolithic re-proof + tiled-construct attribution ladder.
run_to evidence/rdma_silicon_r5.json python scripts/rdma_on_silicon.py
run_to evidence/tiled_repro_r5.jsonl python scripts/tiled_repro_probe.py

# 5. Wall cross-validation rerun.
run_to evidence/validate_walls_r5.json python scripts/validate_walls.py

# 6. Config-2 at its true size vs a working-set-matched size (same
#    backend/fuse): the gap quantifies the cache-residency inflation.
#    Matched means matched in BYTES to the 8192^2 grayscale bf16
#    flagship (8192^2 x 2 B = 134 MB): config 2 is RGB, so
#    4736^2 x 3ch x 2 B = 134.6 MB (4736 = 37 x 128, tile-friendly).
run_to evidence/config2_matched_r5.jsonl python - <<'EOF'
import json
import jax
from parallel_convolution_tpu.ops.filters import get_filter
from parallel_convolution_tpu.parallel.mesh import make_grid_mesh
from parallel_convolution_tpu.utils import bench
mesh = make_grid_mesh(jax.devices()[:1], (1, 1))
filt = get_filter("blur3")
for shape, tag in (((1920, 2520), "config2-true-size"),
                   ((4736, 4736), "config2-working-set-matched")):
    row = bench.bench_iterate(shape, filt, 100, mesh=mesh, channels=3,
                              backend="pallas_sep", storage="bf16",
                              fuse=16, reps=3)
    row["tag"] = tag
    print(json.dumps(row), flush=True)
EOF

# 7. Driver-bench sanity.
timeout "$LEG_TIMEOUT" python bench.py \
    > /tmp/bench_r5_sanity.json 2> /tmp/bench_r5_sanity.err \
  && tail -c 500 /tmp/bench_r5_sanity.json
