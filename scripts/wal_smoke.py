#!/usr/bin/env python
"""Crash-safe control-plane smoke: the ``run_t1.sh --wal-smoke`` leg
(round 19).

Boot a WAL-backed durable router over three in-process replicas and
prove the control plane itself can die and come back:

1. **Batch sanity under the WAL** — every request completes (or sheds
   typed retryable), byte-identical to the oracle, and every response
   carries the router's fencing-epoch stamp.
2. **The kill-the-router drill** — a converge stream is interrupted by
   a seeded ``router_kill`` fault (``serving.chaos.router_kill_due``
   polled per row: the stream is ABANDONED un-closed, exactly what a
   crashed process leaves).  A second router constructed over the SAME
   WAL is the fenced takeover: the client's retry of the same
   ``request_id`` resumes from the newest durable token, the final row
   is byte-identical to the uninterrupted oracle run, and exactly ONE
   final row per request_id was delivered across both lives.
3. **Zombie fencing** — the dead router's object (epoch E) submits a
   request after the takeover (epoch E+1): every replica rejects it
   typed, non-retryable ``stale_epoch`` — a zombie active can never
   double-deliver.
4. **Durability degrades loudly, never serving** — a converge run under
   injected ``wal_write`` faults still completes byte-identical; the
   router's ``wal_write_errors`` counter says durability was hit.
5. **Torn tail vs corruption** — a half-written record appended to a
   copy of the WAL replays losslessly (torn tail tolerated, reported);
   a mid-log byte flip is a typed ``WALCorrupt`` quarantine — never a
   silent partial replay.
6. **Incremental charging across the restart** — with the pricer armed
   and a frozen quota clock, the whole die-takeover-resume-complete
   saga costs ONE uninterrupted job's units.

The summary row lands in ``--out`` (``evidence/wal_smoke.json``) with
``"failures": 0`` iff every gate held, then feeds ``perf_gate.py``
against the smoke's OWN history file.
"""

from __future__ import annotations

import argparse
import base64
import json
import subprocess
import sys
import time
from pathlib import Path

import _path  # noqa: F401  (repo root + JAX_PLATFORMS re-apply)

SCRIPTS = Path(__file__).resolve().parent


def main() -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--n", type=int, default=12,
                    help="batch requests in the sanity phase")
    ap.add_argument("--rows", type=int, default=40)
    ap.add_argument("--cols", type=int, default=56)
    ap.add_argument("--mesh", default="1x2", help="grid per replica")
    ap.add_argument("--seed", type=int, default=0)
    ap.add_argument("--out", default="evidence/wal_smoke.json")
    ap.add_argument("--history",
                    default="evidence/wal_smoke_history.jsonl",
                    help="the smoke's OWN perf history, seeded fresh "
                         "each run; never the committed "
                         "evidence/perf_history.jsonl")
    args = ap.parse_args()

    import tempfile

    import numpy as np

    from _chaos_common import (
        converge_body as _cbody, oracle_converge_final,
        request_with_backoff,
    )
    from parallel_convolution_tpu.obs import events as obs_events
    from parallel_convolution_tpu.ops import filters, oracle
    from parallel_convolution_tpu.parallel.mesh import mesh_from_spec
    from parallel_convolution_tpu.resilience import faults
    from parallel_convolution_tpu.serving.chaos import router_kill_due
    from parallel_convolution_tpu.serving.pricing import WorkPricer
    from parallel_convolution_tpu.serving.router import (
        InProcessReplica, ReplicaRouter, TenantQuotas,
    )
    from parallel_convolution_tpu.serving.service import ConvolutionService
    from parallel_convolution_tpu.serving.wal import (
        RouterWAL, WALCorrupt, read_wal,
    )
    from parallel_convolution_tpu.utils import imageio

    obs_events.install_from_env()
    failures: list[str] = []
    t0 = time.time()
    img = imageio.generate_test_image(args.rows, args.cols, "grey",
                                      seed=7)
    b64 = base64.b64encode(np.ascontiguousarray(img).tobytes()).decode()
    iters_pool = [1, 2, 3]
    oracles = {it: oracle.run_serial_u8(
        img, filters.get_filter("blur3"), it) for it in iters_pool}

    def batch_body(i: int) -> dict:
        return {"image_b64": b64, "rows": args.rows, "cols": args.cols,
                "mode": "grey", "filter": "blur3",
                "iters": iters_pool[i % len(iters_pool)],
                "request_id": f"wb{i}", "tenant": "drill"}

    def converge_body(rid: str) -> dict:
        return _cbody(b64, args.rows, args.cols, rid, tenant="drill")

    def factory():
        return ConvolutionService(mesh_from_spec(args.mesh),
                                  max_delay_s=0.002, max_queue=256)

    # ---- the uninterrupted ORACLE converge run (clean router, no WAL)
    try:
        oracle_final = oracle_converge_final(factory,
                                             converge_body("oracle"))
    except RuntimeError as e:
        failures.append(str(e))
        oracle_final = {}

    tmp = Path(tempfile.mkdtemp(prefix="pctpu-wal-smoke-"))
    wal_path = tmp / "router.wal"
    reps = [InProcessReplica(factory, name=f"w{i}") for i in range(3)]
    clock = [0.0]   # frozen quota clock: exact charge arithmetic
    one_job_pricer = WorkPricer(min_units=1e-9)

    def mk_router():
        return ReplicaRouter(
            reps, wal=str(wal_path),
            quotas=TenantQuotas(rate=1.0, burst=1e6,
                                clock=lambda: clock[0]),
            pricer=WorkPricer(min_units=1e-9),
            breaker_threshold=3, breaker_cooldown_s=0.2,
            poll_interval_s=0.05)

    finals_per_rid: dict[str, int] = {}

    def count_finals(rows) -> list[dict]:
        out = []
        for r in rows:
            out.append(r)
            if r.get("kind") == "final":
                rid = r.get("request_id", "")
                finals_per_rid[rid] = finals_per_rid.get(rid, 0) + 1
        return out

    # ---- phase 1: batch sanity + epoch stamps -----------------------------
    router1 = mk_router()
    epoch1 = router1.epoch
    if epoch1 < 1:
        failures.append(f"fresh WAL router booted with epoch {epoch1}")
    completed = 0
    for i in range(args.n):
        wire = request_with_backoff(router1, batch_body(i))
        if wire.get("ok"):
            completed += 1
            got = np.frombuffer(base64.b64decode(wire["image_b64"]),
                                np.uint8).reshape(img.shape)
            if not np.array_equal(
                    got, oracles[iters_pool[i % len(iters_pool)]]):
                failures.append(f"batch {i}: oracle byte mismatch")
            if wire.get("router", {}).get("epoch") != epoch1:
                failures.append(
                    f"batch {i}: missing/wrong epoch stamp "
                    f"{wire.get('router', {}).get('epoch')}")
        elif not wire.get("retryable"):
            failures.append(
                f"batch {i}: non-rejected failure {wire.get('rejected')}")
    if completed < args.n:
        failures.append(f"only {completed}/{args.n} batch completed")

    # ---- phase 2: kill the router mid-stream ------------------------------
    # The seeded router_kill site picks the crash row: after 2 snapshot
    # rows have reached the client, the stream is ABANDONED (no close —
    # a crashed process closes nothing) and a new router takes over.
    level0 = router1.quotas.bucket("drill").level()
    rows_before_kill = 0
    killed = False
    with faults.injected("router_kill:3", seed=args.seed):
        st, rows = router1.converge(converge_body("wal-kill"))
        if st != 200:
            failures.append(f"kill-drill admission failed: {st}")
        else:
            # Consume INCREMENTALLY (the crash happens mid-stream; a
            # drained list would let the job finish first) and abandon
            # the iterator un-closed — a crashed process closes nothing.
            for row in rows:
                count_finals([row])
                rows_before_kill += 1
                if router_kill_due():
                    killed = True
                    break   # the router "process" dies here
            if not killed:
                failures.append("router_kill never fired — the drill "
                                "completed uninterrupted")
    charged_life1 = level0 - router1.quotas.bucket("drill").level()

    # ---- phase 3: fenced takeover -----------------------------------------
    router2 = mk_router()
    if router2.epoch <= epoch1:
        failures.append(
            f"takeover epoch {router2.epoch} did not bump past {epoch1}")
    rec = router2.recovery
    if rec.get("jobs_restored", 0) < 1:
        failures.append(f"no jobs restored from the WAL: {rec}")
    if rec.get("records", 0) < 1:
        failures.append(f"takeover replayed no WAL records: {rec}")

    # Zombie: the dead router's object still holds epoch1 — every
    # replica must reject its writes typed, non-retryably.
    stz, wz = router1.request(dict(batch_body(0), request_id="zombie"))
    if wz.get("rejected") != "stale_epoch" or wz.get("retryable"):
        failures.append(
            f"zombie not fenced: status {stz}, rejected "
            f"{wz.get('rejected')!r}, retryable {wz.get('retryable')}")
    stz2, zrows = router1.converge(converge_body("zombie-cv"))
    zfirst = next(iter(zrows), {})
    if zfirst.get("rejected") != "stale_epoch":
        failures.append(
            f"zombie converge not fenced: {zfirst.get('rejected')!r}")
    router1.close(close_replicas=False)

    # The client retries the SAME request_id against the new router: it
    # must resume from the WAL-recovered token, not iteration 0.
    st, rows = router2.converge(converge_body("wal-kill"))
    got = count_finals(rows) if st == 200 else []
    final3 = got[-1] if got else {}
    if final3.get("kind") != "final":
        failures.append(f"takeover retry did not finish: "
                        f"{ {k: v for k, v in final3.items() if k != 'image_b64'} }")
    else:
        if got[0].get("iters", 0) <= 10 * (rows_before_kill - 1):
            failures.append(
                f"retry restarted instead of resuming: first row at "
                f"iters {got[0].get('iters')} after {rows_before_kill} "
                "pre-crash rows")
        if final3.get("router", {}).get("resume_count", 0) < 1:
            failures.append("takeover final carries no resume stamp: "
                            f"{final3.get('router')}")
        if final3.get("router", {}).get("epoch") != router2.epoch:
            failures.append("takeover rows not stamped with the new "
                            f"epoch: {final3.get('router')}")
        if final3.get("image_b64") != oracle_final.get("image_b64"):
            failures.append("takeover final row is NOT byte-identical "
                            "to the uninterrupted oracle run")
    dup = {r: n for r, n in finals_per_rid.items() if n != 1}
    if dup:
        failures.append(f"exactly-once final rows violated: {dup}")

    # Incremental charge across the restart: the WAL's debt records
    # make the two routers' buckets ONE ledger (router2 restored to
    # router1's journaled post-charge level, then recovery refunded
    # the interrupted job's unexecuted fraction), so comparing levels
    # ACROSS the routers prices the whole saga — which must cost one
    # uninterrupted job (frozen clock: no refill slack).
    level2 = router2.quotas.bucket("drill").level()
    one_job = one_job_pricer.price(converge_body("price-ref"),
                                   converge=True)
    charged_total = level0 - level2
    if not (0.85 * one_job <= charged_total <= 1.15 * one_job):
        failures.append(
            f"die-takeover-resume saga charged {charged_total:.4g} "
            f"units, expected one job's {one_job:.4g} (incremental "
            "rule across the restart)")

    # ---- phase 4: wal_write faults degrade durability, never serving ------
    wal_errs0 = router2.stats["wal_write_errors"]
    with faults.injected("wal_write:2+", seed=args.seed):
        st, rows = router2.converge(converge_body("wal-degraded"))
        got = count_finals(rows) if st == 200 else []
    final = got[-1] if got else {}
    if final.get("kind") != "final":
        failures.append("converge under wal_write faults did not finish")
    elif final.get("image_b64") != oracle_final.get("image_b64"):
        failures.append("wal_write-fault final not byte-identical")
    if router2.stats["wal_write_errors"] <= wal_errs0:
        failures.append("wal_write faults injected but "
                        "wal_write_errors counter flat")

    # ---- phase 5: torn tail vs mid-log corruption -------------------------
    # Isolated copies of the LIVE file only (the real lineage has a
    # rotated .1 generation next to it; a copy in a fresh dir replays
    # standalone — its head is the takeover's compaction snapshot).
    clean_dir = tmp / "clean"
    clean_dir.mkdir()
    clean_copy = clean_dir / "w.wal"
    clean_copy.write_bytes(wal_path.read_bytes())
    torn_dir = tmp / "torn"
    torn_dir.mkdir()
    torn_copy = torn_dir / "w.wal"
    torn_copy.write_bytes(wal_path.read_bytes())
    with open(torn_copy, "a", encoding="utf-8") as fh:
        fh.write('deadbeef {"seq": 99999, "kind": "final", "lid"')
    try:
        recs_ok, _ = read_wal(clean_copy)
        recs_torn, torn = read_wal(torn_copy)
    except WALCorrupt as e:
        failures.append(f"torn tail mis-classified as corruption: {e}")
    else:
        if torn is None:
            failures.append("torn tail not reported")
        if len(recs_torn) != len(recs_ok):
            failures.append(
                f"torn-tail replay lost records: {len(recs_torn)} != "
                f"{len(recs_ok)}")
    corrupt_dir = tmp / "corrupt"
    corrupt_dir.mkdir()
    corrupt_copy = corrupt_dir / "w.wal"
    data = clean_copy.read_bytes()
    mid = len(data) // 2
    corrupt_copy.write_bytes(
        data[:mid] + bytes([data[mid] ^ 0xFF]) + data[mid + 1:])
    try:
        read_wal(corrupt_copy)
        failures.append("mid-log byte flip replayed silently")
    except WALCorrupt as e:
        if e.cause not in ("crc", "json", "format", "seq_gap",
                           "unknown_kind"):
            failures.append(f"corruption cause untyped: {e.cause!r}")

    snap = router2.snapshot()
    router2.close()

    wall = time.time() - t0
    px = args.rows * args.cols * (
        sum(iters_pool[i % len(iters_pool)] for i in range(args.n))
        + 2 * 40)   # two 40-iteration converge jobs
    row = {
        "workload": f"wal-smoke blur3+jacobi3 {args.rows}x{args.cols} "
                    "3 replicas router-kill takeover zombie-fence",
        "n": args.n + 2,
        "batch_completed": completed,
        "epoch_life1": epoch1,
        "epoch_life2": snap["epoch"],
        "rows_before_kill": rows_before_kill,
        "jobs_restored": rec.get("jobs_restored"),
        "wal_records_replayed": rec.get("records"),
        "resume_count": (final3.get("router", {}).get("resume_count")
                         if final3 else None),
        "finals_per_request": dict(finals_per_rid),
        "charged_units": round(charged_total, 6),
        "charged_life1": round(charged_life1, 6),
        "one_job_units": round(one_job, 6),
        "wal_write_errors": snap["router"]["wal_write_errors"],
        "ledger_evicted": snap["jobs"].get("ledger_evicted"),
        "stale_epoch_rejected": wz.get("rejected") == "stale_epoch",
        "effective_backend": "shifted",
        "mesh": args.mesh,
        "wall_s": round(wall, 3),
        "gpixels_per_s": round(px / wall / 1e9, 6) if wall else None,
        "failures": len(failures),
        "failure_detail": failures[:8],
    }

    # ---- perf sentry feed: seed the smoke's own history, then re-gate.
    out = Path(args.out)
    out.parent.mkdir(parents=True, exist_ok=True)
    out.write_text(json.dumps(row, indent=2))
    hist = Path(args.history)
    hist.parent.mkdir(parents=True, exist_ok=True)
    hist.write_text("")   # the smoke's OWN history: truncate per run
    gate = [sys.executable, str(SCRIPTS / "perf_gate.py"),
            "--history", str(hist), "--row", str(out), "--quiet"]
    rc_seed = subprocess.run([*gate, "--update"], check=False).returncode
    rc_pass = subprocess.run(gate, check=False).returncode
    if rc_seed != 0:
        failures.append(f"perf_gate seed run exited {rc_seed}")
    if rc_pass != 0:
        failures.append(f"perf_gate re-gate exited {rc_pass}")
    row["failures"] = len(failures)
    row["failure_detail"] = failures[:10]
    out.write_text(json.dumps(row, indent=2))
    print(json.dumps(row), flush=True)
    return 1 if failures else 0


if __name__ == "__main__":
    sys.exit(main())
