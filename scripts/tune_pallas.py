#!/usr/bin/env python
"""Tile/fuse sweep for the Pallas stencil kernels (run on a real TPU).

Since round 9 this is a thin CLI over ``tuning.search`` — the sweep
loop, candidate legality, and the winner pick live there (shared with
``backend="auto"`` and ``scripts/tune.py``), not here.  Flags are
unchanged from the round-1 tool.  Prints a JSON row per measured point
(resolved tile/fuse stamped by ``utils.bench``) and the winner; to
persist the winner as a plan file use ``scripts/tune.py --emit-plans``.

  python scripts/tune_pallas.py --size 8192 --iters 20
"""

from __future__ import annotations

import argparse
import json
import sys

import _path  # noqa: F401  (repo root onto sys.path)


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--size", type=int, default=8192)
    ap.add_argument("--iters", type=int, default=20)
    ap.add_argument("--storage", default="bf16")
    ap.add_argument("--backend", default="pallas",
                    choices=["pallas", "pallas_sep"])
    ap.add_argument("--tiles", default=None,
                    help="comma list of HxW tiles, e.g. 1024x512,128x512 "
                         "(default: the tuning.search menu, legality-"
                         "filtered)")
    ap.add_argument("--fuses", default=None,
                    help="comma list of fusion depths, e.g. 16,32,64")
    ap.add_argument("--isplit", action="store_true",
                    help="bench the unmasked-interior launch split "
                         "(any grid; rows carry isplit:true)")
    args = ap.parse_args()

    import jax

    from parallel_convolution_tpu.ops.filters import get_filter
    from parallel_convolution_tpu.parallel.mesh import make_grid_mesh
    from parallel_convolution_tpu.tuning import Workload, search

    mesh = make_grid_mesh(jax.devices()[:1], (1, 1))
    filt = get_filter("blur3")
    w = Workload.from_mesh(mesh, filt, (1, args.size, args.size),
                           storage=args.storage)

    tiles = None
    if args.tiles:
        tiles = [tuple(int(v) for v in t.split("x"))
                 for t in args.tiles.split(",")]
    fuses = None
    if args.fuses:
        fuses = tuple(int(v) for v in args.fuses.split(","))
    if args.isplit:
        # The split only exists on the fused (fuse > 1) kernel path; a
        # fuse=1 row stamped isplit:true would record a fabricated no-op
        # "measurement" in the evidence file.
        fuses = fuses if fuses is not None else search.FUSE_MENU
        dropped = [f for f in fuses if f <= 1]
        fuses = tuple(f for f in fuses if f > 1)
        if dropped:
            print(f"# --isplit: dropped fuse{dropped} (split needs fuse>1)",
                  file=sys.stderr)

    candidates = search.enumerate_candidates(
        w, backends=[args.backend], fuses=fuses, tiles=tiles)
    # A requested point the legality filter dropped must leave a row —
    # the pre-round-9 tool benched it and recorded the compile error;
    # silently incomplete evidence is worse than either.
    legal_tiles = {c.tile for c in candidates}
    legal_fuses = {c.fuse for c in candidates}
    for t in (tiles or []):
        if tuple(t) not in legal_tiles:
            print(json.dumps({"tile": f"{t[0]}x{t[1]}", "error":
                              "dropped: fails (sublane,128) alignment, "
                              "scoped-VMEM budget, or block-size legality"}),
                  flush=True)
    for f in (fuses or []):
        if f not in legal_fuses:
            print(json.dumps({"fuse": f, "error":
                              "dropped: fails block>=r*T (or the tiled-"
                              "RDMA r*T<=sublane bound)"}), flush=True)
    results = []
    # tile/fuse thread through as explicit static jit arguments inside
    # search.measure -> bench_iterate — monkeypatching module defaults
    # does NOT reach already-traced kernels.
    for _, c in search.rank(w, candidates):
        try:
            row = search.measure(w, c, mesh, iters=args.iters, reps=2,
                                 interior_split=args.isplit)
            if args.isplit:
                row.update(isplit=True)
            results.append(row)
            print(json.dumps(row), flush=True)
        except Exception as e:  # noqa: BLE001 — an illegal point is data
            print(json.dumps({
                "tile": f"{c.tile[0]}x{c.tile[1]}" if c.tile else None,
                "fuse": c.fuse, "error": repr(e)[:150]}), flush=True)

    if results:
        best = max(results, key=lambda r: r["gpixels_per_s_per_chip"])
        print(f"# BEST: {json.dumps(best)}", file=sys.stderr)
    return 0


if __name__ == "__main__":
    sys.exit(main())
