#!/usr/bin/env python
"""Tile-size tuner for the Pallas stencil kernels (run on a real TPU).

Sweeps (tile_h, tile_w) for the one-step kernel and fusion depth T for the
fused kernel on a fixed workload, printing a JSON row per point and the
winner. Use the winner to update ``ops/pallas_stencil.DEFAULT_TILE`` /
bench fuse depth.

  python scripts/tune_pallas.py --size 8192 --iters 20
"""

from __future__ import annotations

import argparse
import json
import sys
import time


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--size", type=int, default=8192)
    ap.add_argument("--iters", type=int, default=20)
    ap.add_argument("--storage", default="bf16")
    args = ap.parse_args()

    import jax
    import numpy as np

    from parallel_convolution_tpu.ops import pallas_stencil
    from parallel_convolution_tpu.ops.filters import get_filter
    from parallel_convolution_tpu.parallel import step
    from parallel_convolution_tpu.parallel.mesh import make_grid_mesh
    from parallel_convolution_tpu.utils import bench

    mesh = make_grid_mesh(jax.devices()[:1], (1, 1))
    filt = get_filter("blur3")
    H = W = args.size
    results = []

    for tile in [(128, 512), (256, 256), (256, 512), (256, 1024),
                 (512, 512), (512, 1024), (1024, 512)]:
        for fuse in (1, 2, 4, 8, 16):
            old = pallas_stencil.DEFAULT_TILE
            pallas_stencil.DEFAULT_TILE = tile
            # new compile per tile: drop the runner cache
            step._build_iterate.cache_clear()
            try:
                row = bench.bench_iterate(
                    (H, W), filt, args.iters, mesh=mesh, backend="pallas",
                    storage=args.storage, fuse=fuse, reps=2,
                )
                row.update(tile=f"{tile[0]}x{tile[1]}")
                results.append(row)
                print(json.dumps(row), flush=True)
            except Exception as e:
                print(json.dumps({"tile": f"{tile[0]}x{tile[1]}",
                                  "fuse": fuse, "error": repr(e)[:150]}),
                      flush=True)
            finally:
                pallas_stencil.DEFAULT_TILE = old

    if results:
        best = max(results, key=lambda r: r["gpixels_per_s_per_chip"])
        print(f"# BEST: {json.dumps(best)}", file=sys.stderr)
    return 0


if __name__ == "__main__":
    sys.exit(main())
