#!/usr/bin/env python
"""Tile-size tuner for the Pallas stencil kernels (run on a real TPU).

Sweeps (tile_h, tile_w) and fusion depth T on a fixed workload, printing a
JSON row per point and the winner. Use the winner to update
``ops/pallas_stencil.DEFAULT_TILE`` / ``SEP_TILE`` and the bench fuse depth.

  python scripts/tune_pallas.py --size 8192 --iters 20
"""

from __future__ import annotations

import argparse
import json
import sys

import _path  # noqa: F401  (repo root onto sys.path)


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--size", type=int, default=8192)
    ap.add_argument("--iters", type=int, default=20)
    ap.add_argument("--storage", default="bf16")
    ap.add_argument("--backend", default="pallas",
                    choices=["pallas", "pallas_sep"])
    ap.add_argument("--tiles", default=None,
                    help="comma list of HxW tiles, e.g. 1024x512,128x512")
    ap.add_argument("--fuses", default=None,
                    help="comma list of fusion depths, e.g. 16,32,64")
    ap.add_argument("--isplit", action="store_true",
                    help="bench the unmasked-interior launch split "
                         "(any grid; rows carry isplit:true)")
    args = ap.parse_args()

    import jax
    import numpy as np

    from parallel_convolution_tpu.ops.filters import get_filter
    from parallel_convolution_tpu.parallel.mesh import make_grid_mesh
    from parallel_convolution_tpu.utils import bench

    mesh = make_grid_mesh(jax.devices()[:1], (1, 1))
    filt = get_filter("blur3")
    H = W = args.size
    results = []

    tiles = [(128, 512), (256, 256), (256, 512), (256, 1024),
             (512, 512), (512, 1024), (1024, 512)]
    if args.tiles:
        tiles = [tuple(int(v) for v in t.split("x"))
                 for t in args.tiles.split(",")]
    fuses = (1, 2, 4, 8, 16)
    if args.fuses:
        fuses = tuple(int(v) for v in args.fuses.split(","))
    if args.isplit:
        # The split only exists on the fused (fuse > 1) kernel path; a
        # fuse=1 row stamped isplit:true would record a fabricated no-op
        # "measurement" in the evidence file.
        dropped = [f for f in fuses if f <= 1]
        fuses = tuple(f for f in fuses if f > 1)
        if dropped:
            print(f"# --isplit: dropped fuse{dropped} (split needs fuse>1)",
                  file=sys.stderr)
    for tile in tiles:
        for fuse in fuses:
            # tile is threaded through as an explicit static jit argument —
            # monkeypatching the module defaults does NOT reach
            # already-traced kernels (each (tile, fuse) point gets its own
            # compile this way).
            try:
                row = bench.bench_iterate(
                    (H, W), filt, args.iters, mesh=mesh, backend=args.backend,
                    storage=args.storage, fuse=fuse, reps=2, tile=tile,
                    interior_split=args.isplit,
                )
                row.update(tile=f"{tile[0]}x{tile[1]}")
                if args.isplit:
                    row.update(isplit=True)
                results.append(row)
                print(json.dumps(row), flush=True)
            except Exception as e:
                print(json.dumps({"tile": f"{tile[0]}x{tile[1]}",
                                  "fuse": fuse, "error": repr(e)[:150]}),
                      flush=True)

    if results:
        best = max(results, key=lambda r: r["gpixels_per_s_per_chip"])
        print(f"# BEST: {json.dumps(best)}", file=sys.stderr)
    return 0


if __name__ == "__main__":
    sys.exit(main())
