#!/usr/bin/env python
"""Boot the convolution service behind the stdlib HTTP frontend.

The long-lived counterpart of the one-shot CLI: compile-once warm
executables, micro-batching, admission control, and per-request latency
tracing (parallel_convolution_tpu/serving/).  stdlib only — deployment
is this script, nothing else.

  # CPU smoke on 8 virtual devices
  XLA_FLAGS=--xla_force_host_platform_device_count=8 JAX_PLATFORMS=cpu \\
    python scripts/serve.py --port 8080 --mesh 2x4 \\
      --warm '{"rows": 48, "cols": 64, "filter": "blur3", "iters": 2}'

  curl -s localhost:8080/healthz | python -m json.tool   # liveness
  curl -s localhost:8080/readyz  | python -m json.tool   # readiness:
  #   503 during reshape / queue-full; degrade tier in the payload
  python scripts/loadgen.py --url http://127.0.0.1:8080 --n 100 ...

``PCTPU_FAULTS`` is honored (resilience.faults), so injected-fault
drills run end-to-end through the real server; transient compile faults
degrade the backend per key (the /stats `resident` table shows the
effective tier) instead of killing the process.
"""

from __future__ import annotations

import argparse
import json
import signal
import sys

import _path  # noqa: F401  (repo root + JAX_PLATFORMS re-apply)


def main() -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--host", default="127.0.0.1")
    ap.add_argument("--port", type=int, default=8080,
                    help="0 = pick a free port (printed on boot)")
    ap.add_argument("--mesh", default=None, help="RxC grid (default: all "
                                                 "devices, near-square)")
    ap.add_argument("--platform", default=None,
                    help="force a jax platform (e.g. cpu) before init")
    ap.add_argument("--capacity", type=int, default=16,
                    help="warm-executable cache size (LRU-evicted keys)")
    ap.add_argument("--max-batch", type=int, default=8)
    ap.add_argument("--max-delay-ms", type=float, default=5.0,
                    help="micro-batch flush deadline")
    ap.add_argument("--max-queue", type=int, default=64,
                    help="admission bound: deeper queues shed load")
    ap.add_argument("--no-fallback", action="store_true",
                    help="disable the per-key backend degradation ladder")
    ap.add_argument("--plans", default=None, metavar="PLANS_JSON",
                    help="tuner-emitted plan file (scripts/tune.py "
                         "--emit-plans): backend='auto' warm configs and "
                         "requests resolve through it, so the service "
                         "boots already tuned")
    ap.add_argument("--warm", action="append", default=[],
                    metavar="JSON", help="config to pre-compile at startup "
                    '(repeatable), e.g. \'{"rows": 512, "cols": 512, '
                    '"mode": "rgb", "filter": "blur3", "iters": 10, '
                    '"backend": "pallas_sep"}\'')
    ap.add_argument("--cache", action="store_true",
                    help="enable the content-addressed result cache "
                         "(serving/cache.py): byte-identical duplicates "
                         "are served without touching the device")
    ap.add_argument("--cache-dir", default=None, metavar="DIR",
                    help="disk spill tier for the result cache "
                         "(implies --cache)")
    args = ap.parse_args()

    if args.platform:
        from parallel_convolution_tpu.utils.platform import force_platform

        force_platform(args.platform, warn=True)

    from parallel_convolution_tpu.obs import events as obs_events
    from parallel_convolution_tpu.resilience import diskio, faults
    from parallel_convolution_tpu.serving.frontend import make_http_server
    from parallel_convolution_tpu.serving.service import ConvolutionService
    from parallel_convolution_tpu.utils.platform import enable_compile_cache

    faults.install_from_env()
    diskio.install_from_env()   # PCTPU_DISK_MODES: storage fault shapes
    obs_events.install_from_env()  # PCTPU_OBS_EVENTS: the event timeline
    enable_compile_cache()

    mesh = None
    if args.mesh:
        from parallel_convolution_tpu.parallel.mesh import mesh_from_spec

        mesh = mesh_from_spec(args.mesh)

    cache = None
    if args.cache or args.cache_dir:
        from parallel_convolution_tpu.serving.cache import ResultCache

        cache = ResultCache(disk_dir=args.cache_dir)
    service = ConvolutionService(
        mesh, capacity=args.capacity, max_batch=args.max_batch,
        max_delay_s=args.max_delay_ms / 1e3, max_queue=args.max_queue,
        fallback=not args.no_fallback, plans=args.plans, cache=cache)
    warm_cfgs = [json.loads(w) for w in args.warm]
    if warm_cfgs:
        # The engine's plan cache was already armed by the constructor
        # (plans=args.plans) — no plan_file here, or it would be parsed
        # twice with two code paths to keep consistent.
        effective = service.warmup(warm_cfgs)
        for cfg, eff in zip(warm_cfgs, effective):
            print(json.dumps({"warmed": cfg, "effective_backend": eff}),
                  flush=True)

    server = make_http_server(service, args.host, args.port)
    host, port = server.server_address[:2]
    obs_events.emit("serve", state="boot", url=f"http://{host}:{port}",
                    mesh=service.snapshot().get("mesh", ""))
    print(json.dumps({"serving": f"http://{host}:{port}",
                      **{k: v for k, v in service.snapshot().items()
                         if k in ("mesh", "platform", "device_kind")}}),
          flush=True)

    stopping = []

    def _stop(signum, frame):
        import threading

        if stopping:   # timeout(1) + shell job control can double-signal
            return
        stopping.append(signum)
        print(json.dumps({"stopping": signum,
                          "final": service.snapshot()}), flush=True)
        # shutdown() must not run on the thread inside serve_forever (it
        # would deadlock waiting for the suspended loop to acknowledge).
        threading.Thread(target=server.shutdown, daemon=True).start()

    signal.signal(signal.SIGINT, _stop)
    signal.signal(signal.SIGTERM, _stop)
    try:
        server.serve_forever()
    finally:
        server.server_close()
        service.close()
    return 0


if __name__ == "__main__":
    sys.exit(main())
