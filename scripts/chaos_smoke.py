#!/usr/bin/env python
"""Chaos-transport smoke: the ``run_t1.sh --chaos-smoke`` leg (round 18).

Boot THREE in-process replicas behind the durable router, every
transport wrapped in :class:`serving.chaos.ChaosTransport`, and drive
mixed batch/converge traffic under a SEEDED transport-fault schedule
(``PCTPU_FAULTS`` transport sites: send drops, latency, lost responses,
corrupt bodies, flapping readiness, mid-stream disconnects) plus a
mid-stream replica KILL.  Gates, in order of importance:

1. **zero non-rejected failures** — every request/job either completed
   or ended in a typed RETRYABLE rejection (client backoff honored);
2. every completed batch response and every completed converge FINAL row
   **byte-identical to the uninterrupted oracle run**;
3. **>= 1 observed mid-stream resume** — a converge job continued on a
   surviving replica from its ledger token after its stream died
   (including the killed-replica drill), with the ``router:
   {resumed_from, resume_count}`` stamp client-visible;
4. **exactly one final row per request_id** (the exactly-once ledger
   gate, asserted across every stream this smoke consumed);
5. **resumed jobs' tenant charge equals incremental work only** — with
   the pricer armed and a frozen quota clock, the whole
   die-resume-complete saga costs ONE uninterrupted job's units;
6. **counters consistent with the injected schedule** — corrupt
   responses, mid-stream failovers and resumes in ``/stats`` match what
   the chaos wrappers report injecting.

``--volume`` (round 24) adds a rank-3 drill: a (D,H,W) volume converge
stream through a mid-stream replica kill must resume from its ledger
token on a survivor and finish byte-identical to the uninterrupted
volume oracle.

The summary row lands in ``--out`` (``evidence/chaos_smoke.json``) with
``"failures": 0`` iff every gate held, then feeds ``perf_gate.py``
against the smoke's OWN history file (seed + re-gate — never the
committed ``evidence/perf_history.jsonl``).
"""

from __future__ import annotations

import argparse
import base64
import json
import subprocess
import sys
import time
from pathlib import Path

import _path  # noqa: F401  (repo root + JAX_PLATFORMS re-apply)

SCRIPTS = Path(__file__).resolve().parent


def main() -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--n", type=int, default=30,
                    help="batch requests under chaos")
    ap.add_argument("--rows", type=int, default=40)
    ap.add_argument("--cols", type=int, default=56)
    ap.add_argument("--mesh", default="1x2", help="grid per replica")
    ap.add_argument("--seed", type=int, default=0)
    ap.add_argument("--volume", action="store_true",
                    help="also drill a rank-3 volume converge stream "
                         "through a mid-stream replica kill: resume "
                         "from the ledger token, finish byte-identical "
                         "to the uninterrupted volume oracle (round 24)")
    ap.add_argument("--out", default="evidence/chaos_smoke.json")
    ap.add_argument("--history",
                    default="evidence/chaos_smoke_history.jsonl",
                    help="the smoke's OWN perf history, seeded fresh "
                         "each run; never the committed "
                         "evidence/perf_history.jsonl")
    args = ap.parse_args()

    import numpy as np

    from _chaos_common import (
        chaos_pool, converge_body as _cbody, oracle_converge_final,
        request_with_backoff,
    )
    from parallel_convolution_tpu.obs import events as obs_events
    from parallel_convolution_tpu.ops import filters, oracle
    from parallel_convolution_tpu.parallel.mesh import mesh_from_spec
    from parallel_convolution_tpu.resilience import faults
    from parallel_convolution_tpu.serving.pricing import WorkPricer
    from parallel_convolution_tpu.serving.router import ReplicaRouter, TenantQuotas
    from parallel_convolution_tpu.serving.service import ConvolutionService
    from parallel_convolution_tpu.utils import imageio

    obs_events.install_from_env()
    failures: list[str] = []
    t0 = time.time()
    img = imageio.generate_test_image(args.rows, args.cols, "grey",
                                      seed=7)
    b64 = base64.b64encode(np.ascontiguousarray(img).tobytes()).decode()
    iters_pool = [1, 2, 3]
    oracles = {it: oracle.run_serial_u8(
        img, filters.get_filter("blur3"), it) for it in iters_pool}

    def batch_body(i: int) -> dict:
        return {"image_b64": b64, "rows": args.rows, "cols": args.cols,
                "mode": "grey", "filter": "blur3",
                "iters": iters_pool[i % len(iters_pool)],
                "request_id": f"cb{i}", "tenant": "drill"}

    def converge_body(rid: str) -> dict:
        return _cbody(b64, args.rows, args.cols, rid, tenant="drill")

    def factory():
        return ConvolutionService(mesh_from_spec(args.mesh),
                                  max_delay_s=0.002, max_queue=256)

    # ---- the uninterrupted ORACLE converge run (clean router, no chaos)
    try:
        oracle_final = oracle_converge_final(factory,
                                             converge_body("oracle"))
    except RuntimeError as e:
        failures.append(str(e))
        oracle_final = {}

    # ---- the chaos pool: per-replica failure shapes over one seeded
    # schedule (hit-indexed — replayable bit-for-bit).
    reps = chaos_pool(factory, args.seed)
    clock = [0.0]   # frozen quota clock: exact charge arithmetic
    quotas = TenantQuotas(rate=1.0, burst=1e6, clock=lambda: clock[0])
    pricer = WorkPricer(min_units=1e-9)
    router = ReplicaRouter(reps, quotas=quotas, pricer=pricer,
                           breaker_threshold=3, breaker_cooldown_s=0.2,
                           poll_interval_s=0.05)
    finals_per_rid: dict[str, int] = {}

    def drain(rows):
        out = []
        for r in rows:
            out.append(r)
            if r.get("kind") == "final":
                rid = r.get("request_id", "")
                finals_per_rid[rid] = finals_per_rid.get(rid, 0) + 1
        return out

    # ---- phase 1: batch traffic under the seeded schedule -----------------
    plan = faults.plan_from_spec(
        "transport_send:2,transport_recv:4,readyz_probe:3",
        seed=args.seed)
    batch_completed = batch_failovers = 0
    non_rejected: list[dict] = []
    byte_fails = 0
    with faults.injected(plan):
        for i in range(args.n):
            wire = request_with_backoff(router, batch_body(i))
            if wire.get("ok"):
                batch_completed += 1
                if wire["router"].get("failovers", 0) > 0:
                    batch_failovers += 1
                got = np.frombuffer(base64.b64decode(wire["image_b64"]),
                                    np.uint8).reshape(img.shape)
                it = iters_pool[i % len(iters_pool)]
                if not np.array_equal(got, oracles[it]):
                    byte_fails += 1
            elif not wire.get("retryable"):
                non_rejected.append({"i": i, "wire": {
                    k: v for k, v in wire.items() if k != "image_b64"}})
    if byte_fails:
        failures.append(f"{byte_fails} batch oracle byte mismatches")
    if non_rejected:
        failures.append(f"{len(non_rejected)} non-rejected batch "
                        f"failures, e.g. {non_rejected[0]}")
    if batch_completed < args.n - 2:
        failures.append(
            f"only {batch_completed}/{args.n} batch requests completed")

    # ---- phase 1b: a corrupt body, deterministically ----------------------
    # Route a request whose consistent-hash HOME is the corrupt-mode
    # replica (c1) and fire its recv site: the router must classify the
    # garbage typed (breaker food + failover), count it, and still
    # complete the request on a survivor.
    from parallel_convolution_tpu.serving.router import route_key

    corrupt_body = None
    for j in range(1, 65):   # iters is a route-key field: 64 ring points
        cand = dict(batch_body(0), request_id=f"corrupt{j}", iters=j)
        if router.ring.candidates(route_key(cand))[0] == "c1":
            corrupt_body = cand
            break
    if corrupt_body is None:
        failures.append("could not find a key homed on c1")
    else:
        with faults.injected("transport_recv:1", seed=args.seed):
            status, wire = router.request(corrupt_body)
        if not wire.get("ok"):
            failures.append(f"corrupt-leg request failed: {wire}")
        elif wire["router"].get("failovers", 0) < 1:
            failures.append("corrupt body caused no failover walk")
        else:
            got = np.frombuffer(base64.b64decode(wire["image_b64"]),
                                np.uint8).reshape(img.shape)
            want = oracle.run_serial_u8(
                img, filters.get_filter("blur3"),
                corrupt_body["iters"])
            if not np.array_equal(got, want):
                failures.append(
                    "corrupt-leg completion not byte-identical")

    # ---- phase 2: converge under mid-stream disconnects -------------------
    level0 = quotas.bucket("drill").level()
    resumed_jobs = 0
    with faults.injected("transport_stream:3", seed=args.seed):
        st, rows = router.converge(converge_body("cv-chaos"))
        got = drain(rows)
    final = got[-1]
    if final.get("kind") != "final":
        failures.append(f"chaos converge did not finish: {final}")
    else:
        if final.get("router", {}).get("resume_count", 0) < 1:
            failures.append("chaos converge never resumed "
                            f"(router stamp: {final.get('router')})")
        else:
            resumed_jobs += 1
        if final.get("image_b64") != oracle_final.get("image_b64"):
            failures.append("resumed converge final row is NOT "
                            "byte-identical to the oracle run")
    # Incremental-charge gate: the die-resume-complete saga must cost
    # exactly ONE uninterrupted job (frozen clock: no refill slack).
    charged = level0 - quotas.bucket("drill").level()
    one_job = pricer.price(converge_body("price-ref"), converge=True)
    if not (0.85 * one_job <= charged <= 1.15 * one_job):
        failures.append(
            f"resumed job charged {charged:.3g} units, expected one "
            f"uninterrupted job's {one_job:.3g} (incremental rule)")

    # ---- phase 3: the mid-stream replica KILL drill -----------------------
    st, rows = router.converge(converge_body("cv-kill"))
    it = iter(rows)
    first = next(it)
    victim = first.get("router", {}).get("replica", "")
    router.replica(victim).kill()
    obs_events.emit("router", event="kill", replica=victim)
    got = drain([first, *it])
    final = got[-1]
    if final.get("kind") != "final":
        failures.append(f"kill-drill converge did not finish: {final}")
    else:
        stamp = final.get("router", {})
        if stamp.get("resume_count", 0) < 1 or victim not in stamp.get(
                "resumed_from", []):
            failures.append(
                f"kill drill: no resume off {victim!r} ({stamp})")
        else:
            resumed_jobs += 1
        if final.get("image_b64") != oracle_final.get("image_b64"):
            failures.append("kill-drill final row is NOT byte-identical "
                            "to the oracle run")
    router.replica(victim).revive()

    # ---- phase 3b (--volume): rank-3 volume stream through a kill ---------
    # r23 only drilled the volume reshape shed in-process; this is the
    # cross-replica saga: kill the replica serving a (D,H,W) converge
    # stream mid-flight, resume from the job-ledger token on a
    # survivor, land byte-identical to the uninterrupted volume oracle.
    vol_drill = None
    if args.volume:
        vol = np.random.default_rng(11).random((2, 4, 16, 16),
                                               dtype=np.float32)
        vbody = {"rows": 16, "cols": 16, "depth": 4, "mode": "volume",
                 "volume_b64": base64.b64encode(vol.tobytes()).decode(),
                 "filter": "wave", "boundary": "periodic", "tol": 0.0,
                 "max_iters": 12, "check_every": 4,
                 "request_id": "cv-vol", "tenant": "drill"}
        try:
            vol_oracle = oracle_converge_final(factory, dict(vbody))
        except RuntimeError as e:
            failures.append(f"volume oracle run failed: {e}")
            vol_oracle = {}
        st, rows = router.converge(dict(vbody))
        it = iter(rows)
        first = next(it)
        vvictim = first.get("router", {}).get("replica", "")
        router.replica(vvictim).kill()
        obs_events.emit("router", event="kill", replica=vvictim)
        got = drain([first, *it])
        final = got[-1]
        if final.get("kind") != "final":
            failures.append(
                f"volume kill drill did not finish: {final}")
        else:
            stamp = final.get("router", {})
            if (stamp.get("resume_count", 0) < 1
                    or vvictim not in stamp.get("resumed_from", [])):
                failures.append(
                    f"volume kill drill: no resume off {vvictim!r} "
                    f"({stamp})")
            else:
                resumed_jobs += 1
            if final.get("image_b64") != vol_oracle.get("image_b64"):
                failures.append(
                    "volume kill-drill final row is NOT byte-identical "
                    "to the volume oracle run")
        router.replica(vvictim).revive()
        vol_drill = {"killed": vvictim,
                     "resume_count": final.get("router", {}).get(
                         "resume_count", 0),
                     "iters": final.get("iters")}

    # ---- gates over the whole run -----------------------------------------
    dup_finals = {rid: n for rid, n in finals_per_rid.items() if n != 1}
    if dup_finals:
        failures.append(
            f"exactly-once final rows violated: {dup_finals}")
    snap = router.snapshot()
    injected = {}
    for rep in reps:
        for site, n in rep.injected.items():
            injected[site] = injected.get(site, 0) + n
    corrupt_counted = sum(p["corrupt_responses"]
                          for p in snap["replicas"].values())
    if corrupt_counted < 1:
        # Phase 1b injected a corrupt body at c1 deterministically: the
        # router MUST have counted it.
        failures.append(
            "corrupt body injected but corrupt_responses counter flat")
    if snap["router"]["resumes"] < resumed_jobs:
        failures.append(
            f"router resumes counter {snap['router']['resumes']} < "
            f"observed resumed jobs {resumed_jobs}")
    if snap["router"]["mid_stream_failovers"] < resumed_jobs:
        failures.append("mid_stream_failovers counter inconsistent "
                        f"({snap['router']['mid_stream_failovers']} < "
                        f"{resumed_jobs})")
    if resumed_jobs < 1:
        failures.append("no mid-stream resume observed anywhere")
    if not injected:
        failures.append("the chaos schedule injected nothing "
                        "(dead drill proves nothing)")

    wall = time.time() - t0
    px = args.rows * args.cols * (
        sum(iters_pool[i % len(iters_pool)] for i in range(args.n))
        + 2 * 40)   # two 40-iteration converge jobs
    row = {
        "workload": f"chaos-smoke blur3+jacobi3 {args.rows}x{args.cols} "
                    "3 replicas seeded-transport-faults kill-1",
        "n": args.n + 2,
        "batch_completed": batch_completed,
        "batch_failovers": batch_failovers,
        "resumes_observed": resumed_jobs,
        "router_resumes": snap["router"]["resumes"],
        "mid_stream_failovers": snap["router"]["mid_stream_failovers"],
        "corrupt_responses": corrupt_counted,
        "chaos_injected": injected,
        "finals_per_request": {k: v for k, v in finals_per_rid.items()},
        "charged_units": round(charged, 6),
        "one_job_units": round(one_job, 6),
        "jobs_ledger": snap["jobs"],
        "killed": victim,
        **({"volume_drill": vol_drill} if vol_drill else {}),
        "effective_backend": "shifted",
        "mesh": args.mesh,
        "wall_s": round(wall, 3),
        "gpixels_per_s": round(px / wall / 1e9, 6) if wall else None,
        "failures": len(failures),
        "failure_detail": failures[:8],
    }
    router.close()

    # ---- perf sentry feed: seed the smoke's own history, then re-gate.
    out = Path(args.out)
    out.parent.mkdir(parents=True, exist_ok=True)
    out.write_text(json.dumps(row, indent=2))
    hist = Path(args.history)
    hist.parent.mkdir(parents=True, exist_ok=True)
    hist.write_text("")   # the smoke's OWN history: truncate per run
    gate = [sys.executable, str(SCRIPTS / "perf_gate.py"),
            "--history", str(hist), "--row", str(out), "--quiet"]
    rc_seed = subprocess.run([*gate, "--update"], check=False).returncode
    rc_pass = subprocess.run(gate, check=False).returncode
    if rc_seed != 0:
        failures.append(f"perf_gate seed run exited {rc_seed}")
    if rc_pass != 0:
        failures.append(f"perf_gate re-gate exited {rc_pass}")
    row["failures"] = len(failures)
    row["failure_detail"] = failures[:10]
    out.write_text(json.dumps(row, indent=2))
    print(json.dumps(row), flush=True)
    return 1 if failures else 0


if __name__ == "__main__":
    sys.exit(main())
