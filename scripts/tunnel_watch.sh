#!/bin/sh
# Probe the TPU tunnel every 4 minutes; whenever it answers, fire
# chip_session_r5b.sh (idempotent: [ -e ] guards skip landed legs).
# Keeps looping until every guarded output exists — a mid-session
# tunnel death (the recurring failure mode) re-arms instead of
# abandoning the remaining legs.  Log: /tmp/tunnel_status.log.
cd "$(dirname "$0")/.."

all_landed() {
  [ -e evidence/tiled_repro_r5b.jsonl ] \
    && [ -e evidence/rdma_silicon_r5b.json ] \
    && [ -e evidence/helper_crash_probe_r5.jsonl ] \
    && [ -e evidence/tune_convex_r5b_fill.jsonl ]
}

while :; do
  if all_landed; then
    echo "$(date -u) all r5b outputs landed — watcher exiting" >> /tmp/tunnel_status.log
    exit 0
  fi
  if timeout 60 python -c "import jax; print(jax.devices())" \
       >> /tmp/tunnel_status.log 2>&1; then
    echo "$(date -u) tunnel UP — firing chip_session_r5b" >> /tmp/tunnel_status.log
    sh scripts/chip_session_r5b.sh > /tmp/chip_session_r5b.log 2>&1
    echo "$(date -u) chip_session_r5b pass finished" >> /tmp/tunnel_status.log
  else
    echo "$(date -u) tunnel down" >> /tmp/tunnel_status.log
  fi
  sleep 240
done
