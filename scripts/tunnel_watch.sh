#!/bin/sh
# SUPERSEDED (resilience PR): use scripts/run_supervised.py — the same
# probe/retry/sentinel workflow as a tested library
# (parallel_convolution_tpu/resilience/), with a JSON status ledger.
# Kept as the round-5 operational record; do not extend.
#
# Probe the TPU tunnel every 4 minutes; whenever it answers, fire the
# current chip-session queue (idempotent: [ -e ] guards skip landed
# legs).  Keeps looping until every guarded output exists — a
# mid-session tunnel death (the recurring failure mode) re-arms instead
# of abandoning the remaining legs.  Log: /tmp/tunnel_status.log.
#
# Round-5 third window: points at chip_session_r5c.sh (r5b's own legs
# all landed 2026-07-31 ~10:13-10:45 UTC except the fuse-56 fill-in,
# which wedged its compile twice and is dropped for cause).
cd "$(dirname "$0")/.."

all_landed() {
  [ -e evidence/bench_r5c_sanity.json ] \
    && [ -e evidence/profile_flagship_magic_r5.jsonl ] \
    && [ -e evidence/baseline_configs_magic_r5.jsonl ] \
    && [ -e evidence/soak_silicon_r5.jsonl ] \
    && [ -e evidence/fuse_sweep_magic_r5.jsonl ]
}

while :; do
  if [ -e evidence/HALT_r5c ]; then
    # Terminal failure (e.g. magic_round_guard=MISMATCH): retrying cannot
    # heal it — stop instead of refiring the session every 4 minutes.
    echo "$(date -u) HALT_r5c present (terminal failure) — watcher exiting" >> /tmp/tunnel_status.log
    exit 1
  fi
  if all_landed; then
    echo "$(date -u) all r5c outputs landed — watcher exiting" >> /tmp/tunnel_status.log
    exit 0
  fi
  if timeout 60 python -c "import jax; print(jax.devices())" \
       >> /tmp/tunnel_status.log 2>&1; then
    echo "$(date -u) tunnel UP — firing chip_session_r5c" >> /tmp/tunnel_status.log
    sh scripts/chip_session_r5c.sh > /tmp/chip_session_r5c.log 2>&1
    echo "$(date -u) chip_session_r5c pass finished rc=$?" >> /tmp/tunnel_status.log
  else
    echo "$(date -u) tunnel down" >> /tmp/tunnel_status.log
  fi
  sleep 240
done
