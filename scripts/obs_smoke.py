#!/usr/bin/env python
"""Observability smoke: serving + obs end-to-end on the CPU mesh.

The ``run_t1.sh --obs-smoke`` leg: boot the in-process convolution
service on the 2x4 virtual-device mesh with obs ON, push loadgen-style
traffic through the REAL HTTP frontend, then assert the whole telemetry
spine held together:

1. ``GET /metrics`` parses as Prometheus text exposition and carries the
   serving/step/attribution metric families;
2. the event log (``PCTPU_OBS_EVENTS``) validates line-by-line against
   the obs.events schema (monotonic seq, typed kinds);
3. ``scripts/obs_report.py`` folds the event log + metrics snapshot and
   exits 0.

One summary row lands in ``--out`` (``evidence/obs_smoke.json``, the
supervisor leg's done_file) with ``"failures": 0`` iff every gate held.
"""

from __future__ import annotations

import argparse
import json
import sys
import threading
import time
import urllib.request
from pathlib import Path

import _path  # noqa: F401  (repo root + JAX_PLATFORMS re-apply)


def main() -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--n", type=int, default=24, help="requests to push")
    ap.add_argument("--rows", type=int, default=48)
    ap.add_argument("--cols", type=int, default=64)
    ap.add_argument("--iters", type=int, default=2)
    ap.add_argument("--mesh", default="2x4")
    ap.add_argument("--events", default="evidence/obs_events.jsonl")
    ap.add_argument("--metrics-out", default="evidence/obs_metrics.json")
    ap.add_argument("--report-out", default="evidence/obs_report.json")
    ap.add_argument("--out", default="evidence/obs_smoke.json")
    args = ap.parse_args()

    import numpy as np

    from parallel_convolution_tpu.obs import events as obs_events, metrics
    from parallel_convolution_tpu.utils import imageio

    if not metrics.enabled():
        metrics.set_enabled(True)  # the smoke TESTS obs: force it on
    ev_path = Path(args.events)
    ev_path.parent.mkdir(parents=True, exist_ok=True)
    if ev_path.exists():
        ev_path.unlink()  # a fresh timeline per smoke run
    obs_events.configure(ev_path)

    from parallel_convolution_tpu.parallel.mesh import mesh_from_spec
    from parallel_convolution_tpu.serving.frontend import make_http_server
    from parallel_convolution_tpu.serving.service import ConvolutionService

    failures: list[str] = []
    service = ConvolutionService(mesh_from_spec(args.mesh), max_batch=8,
                                 max_delay_s=0.005, max_queue=64)
    server = make_http_server(service, port=0)
    port = server.server_address[1]
    threading.Thread(target=server.serve_forever, daemon=True).start()
    base = f"http://127.0.0.1:{port}"

    import base64

    img = imageio.generate_test_image(args.rows, args.cols, "grey", seed=0)
    body = json.dumps({
        "image_b64": base64.b64encode(
            np.ascontiguousarray(img).tobytes()).decode("ascii"),
        "rows": args.rows, "cols": args.cols, "mode": "grey",
        "filter": "blur3", "iters": args.iters, "backend": "shifted",
    }).encode()

    t0 = time.perf_counter()
    completed = 0
    for i in range(args.n):
        req = urllib.request.Request(
            f"{base}/v1/convolve", data=body,
            headers={"Content-Type": "application/json"})
        try:
            with urllib.request.urlopen(req, timeout=120) as resp:
                if json.loads(resp.read()).get("ok"):
                    completed += 1
        except Exception as e:  # noqa: BLE001 — counted, reported
            failures.append(f"request {i}: {e!r}")
    wall = time.perf_counter() - t0
    if completed != args.n:
        failures.append(f"only {completed}/{args.n} requests completed")

    # Gate 1: /metrics parses and carries the expected families.
    metrics_ok = False
    try:
        with urllib.request.urlopen(f"{base}/metrics", timeout=30) as resp:
            text = resp.read().decode()
        parsed = metrics.parse_text(text)
        missing = [n for n in (
            "pctpu_service_stats", "pctpu_engine_stats",
            "pctpu_batcher_stats", "pctpu_request_phase_seconds_bucket",
            "pctpu_halo_bytes_total", "pctpu_exchange_seconds_total",
            "pctpu_admission_total", "pctpu_plan_drift_ratio",
        ) if n not in parsed]
        if missing:
            failures.append(f"/metrics missing families: {missing}")
        else:
            metrics_ok = True
    except Exception as e:  # noqa: BLE001
        failures.append(f"/metrics: {e!r}")

    server.shutdown()
    service.close()

    # Gate 2: the event log validates line-by-line.
    events_ok = False
    try:
        recs = obs_events.read_events(ev_path)
        bad = [p for r in recs for p in obs_events.validate_event(r)]
        if not recs:
            failures.append("event log is empty")
        elif bad:
            failures.append(f"{len(bad)} event schema problems: {bad[:5]}")
        else:
            events_ok = True
    except Exception as e:  # noqa: BLE001
        failures.append(f"event log: {e!r}")

    # Gate 3: obs_report folds both and exits 0.
    metrics.dump(args.metrics_out)
    import subprocess

    rc = subprocess.run(
        [sys.executable, str(Path(__file__).parent / "obs_report.py"),
         "--events", str(ev_path), "--metrics", args.metrics_out,
         "--out", args.report_out, "--quiet"],
        capture_output=True, text=True).returncode
    report_ok = rc == 0
    if not report_ok:
        failures.append(f"obs_report.py exited {rc}")

    row = {
        "workload": (f"obs smoke blur3 {args.rows}x{args.cols} "
                     f"{args.iters} iters, {args.n} http requests"),
        "mesh": args.mesh,
        "completed": completed,
        "wall_s": round(wall, 3),
        "metrics_ok": metrics_ok,
        "events_ok": events_ok,
        "report_ok": report_ok,
        "event_count": len(recs) if events_ok else None,
        "failures": len(failures),
        **({"failure_sample": failures[:5]} if failures else {}),
    }
    out = Path(args.out)
    out.parent.mkdir(parents=True, exist_ok=True)
    out.write_text(json.dumps(row, indent=2))
    print(json.dumps(row), flush=True)
    return 1 if failures else 0


if __name__ == "__main__":
    sys.exit(main())
