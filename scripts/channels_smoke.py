#!/usr/bin/env python
"""Persistent/partitioned halo-channel smoke: the round-16 gates,
end-to-end on the CPU mesh.

The ``run_t1.sh --channels-smoke`` leg.  Gates, in order:

1. BYTE IDENTITY — every CPU-reachable cell of
   {serialized, r12 overlap, persistent+partitioned} x {packed, strided}
   is byte-identical to the oracle AND pairwise identical, both kernels,
   both boundaries (``scripts/rdma_fuse_ab.channels_proofs``: on a jax
   without the DMA-faithful interpreter the multi-device cells are typed
   capability skips and the degenerate 1x1 proofs — where the channel
   machinery must statically elide — carry the byte burden).
2. CHANNEL REUSE IS REAL — across a fused multi-chunk converge run the
   channel-plan build counter equals the number of DISTINCT exchange
   identities (one per (fuse-depth, kernel-form) the runner compiles)
   and stays FLAT across additional chunks and a second converge; the
   multigrid V-cycle level schedule likewise binds one plan per level,
   flat across repeat warms.
3. DISPATCH RESOLUTION — ``col_mode='auto'`` resolves deterministically
   through the cost model, the resolved value lands in bench rows, and
   both explicit modes produce oracle bytes through the full dispatch
   stack (``sharded_iterate``).
4. PERF SENTRY FOLD — the cell's bench row seeds and re-gates the
   smoke's OWN history through ``scripts/perf_gate.py``.

One summary row lands in ``--out`` (``evidence/channels_smoke.json``,
the supervisor leg's done_file) with ``"failures": 0`` iff every gate
held.
"""

from __future__ import annotations

import argparse
import json
import os
import subprocess
import sys
from pathlib import Path

import _path  # noqa: F401  (repo root + JAX_PLATFORMS re-apply)

SCRIPTS = Path(__file__).resolve().parent


def main() -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--rows", type=int, default=48)
    ap.add_argument("--cols", type=int, default=64)
    ap.add_argument("--mesh", default="2x4")
    ap.add_argument("--out", default="evidence/channels_smoke.json")
    ap.add_argument("--history",
                    default="evidence/channels_smoke_history.jsonl",
                    help="the smoke's OWN perf history, seeded fresh "
                         "each run; never the committed "
                         "evidence/perf_history.jsonl")
    args = ap.parse_args()

    # The byte proofs drive the overlapped program under interpreted
    # Pallas — the documented CI hatch.
    os.environ.setdefault("PCTPU_OVERLAP_INTERPRET", "1")

    import numpy as np

    import rdma_fuse_ab
    from parallel_convolution_tpu.ops import filters, oracle
    from parallel_convolution_tpu.parallel import channels, step as step_lib
    from parallel_convolution_tpu.parallel.mesh import (
        make_grid_mesh, mesh_from_spec,
    )
    from parallel_convolution_tpu.solvers import multigrid as mg
    from parallel_convolution_tpu.utils import bench, imageio, jax_compat

    import jax

    failures: list[str] = []
    mesh = mesh_from_spec(args.mesh)
    mesh_shape = tuple(int(v) for v in mesh.devices.shape)
    filt = filters.get_filter("blur3")

    # ---- 1: byte identity across tiers x transports (both kernels).
    rows = rdma_fuse_ab.channels_proofs(
        filt, [1, 2, 4], mesh_shape,
        rdma_capable=jax_compat.HAS_TPU_INTERPRET or mesh.size == 1)
    cells = [r for r in rows if "skipped" not in r]
    skips = [r for r in rows if "skipped" in r]
    for r in cells:
        if "error" in r:
            failures.append(f"channel cell errored: {r}")
        elif not (r.get("oracle_bytes_ok") and r.get("matches_serialized")):
            failures.append(f"channel cell bytes drifted: {r}")
    if not cells:
        failures.append("no channel byte-proof cell ran at all")

    # ---- 2: channel reuse — builds == distinct identities, flat across
    # chunks / repeat solves.  The degenerate 1x1 grid is the
    # CPU-reachable RDMA host; its (empty) plans still bind and count.
    one = make_grid_mesh(jax.devices()[:1], (1, 1))
    img = imageio.generate_test_image(args.rows, args.cols, "grey", seed=3)
    x = imageio.interleaved_to_planar(img).astype(np.float32)
    channels.reset()
    out1, it1 = step_lib.sharded_converge(
        x, filt, tol=0.0, max_iters=12, check_every=4, mesh=one,
        quantize=True, backend="pallas_rdma", fuse=2)
    after_first = channels.stats()
    out2, it2 = step_lib.sharded_converge(
        x, filt, tol=0.0, max_iters=24, check_every=4, mesh=one,
        quantize=True, backend="pallas_rdma", fuse=2)
    after_second = channels.stats()
    # One converge build compiles two step forms (fuse=1 pair step +
    # fuse=2 fused chunk) = two distinct exchange identities; every
    # later chunk and the longer re-run must never rebuild.
    if after_first["builds"] != 2:
        failures.append(
            f"converge run built {after_first['builds']} channel plans "
            "(expected 2: the fused chunk + the single-step identity)")
    if after_second["builds"] != after_first["builds"]:
        failures.append(
            f"channel builds grew across converge runs "
            f"({after_first['builds']} -> {after_second['builds']}): "
            "descriptor plans are being rebuilt, not reused")
    # A program VARIANT sharing the exchange identity (the overlapped
    # pipeline of the same fused chunk) must HIT the bound plan, not
    # rebuild it — the cross-trace reuse the channel layer exists for.
    _ = step_lib.sharded_iterate(x, filt, 4, mesh=one, quantize=True,
                                 backend="pallas_rdma", fuse=2,
                                 overlap=True)
    after_variant = channels.stats()
    if after_variant["builds"] != after_first["builds"]:
        failures.append(
            "the overlapped variant of an already-bound exchange "
            f"identity REBUILT its plan ({after_first['builds']} -> "
            f"{after_variant['builds']} builds)")
    if after_variant["hits"] <= after_second["hits"]:
        failures.append("the overlapped variant recorded no channel-plan "
                        "reuse")
    want = oracle.run_serial_u8(img, filt, 12)
    got = imageio.planar_to_interleaved(
        np.clip(np.rint(np.asarray(out1)), 0, 255).astype(np.uint8))
    if not np.array_equal(got, want):
        failures.append("converge-through-channels bytes drifted from "
                        "the oracle")
    # Multigrid: one plan per V-cycle level, bound on the schedule, flat
    # across repeat warms.
    levels = mg.plan_levels(one, (96, 64), filt.radius, "zero")
    channels.reset()
    keys = mg.warm_level_channels(levels, filt.radius, "zero", "packed")
    s1 = channels.stats()
    mg.warm_level_channels(levels, filt.radius, "zero", "packed")
    s2 = channels.stats()
    if s1["builds"] != len(set(keys)):
        failures.append(
            f"V-cycle schedule built {s1['builds']} plans for "
            f"{len(set(keys))} distinct level identities")
    if s2["builds"] != s1["builds"] or s2["hits"] < s1["hits"] + len(keys):
        failures.append("re-warming the V-cycle level schedule rebuilt "
                        "channel plans instead of hitting the cache")

    # ---- 3: dispatch resolution + bench-row stamping.
    dev0 = one.devices.flat[0]
    from parallel_convolution_tpu.tuning import costmodel

    hw = costmodel.hardware_for(dev0.platform,
                                getattr(dev0, "device_kind", "") or "")
    auto_pick = costmodel.pick_col_mode(mesh_shape, (args.rows, args.cols),
                                        filt.radius, 2, "f32", hw)
    if auto_pick not in ("packed", "strided"):
        failures.append(f"pick_col_mode returned {auto_pick!r}")
    for cm in ("packed", "strided", None):
        out = step_lib.sharded_iterate(
            x, filt, 4, mesh=one, quantize=True, backend="pallas_rdma",
            fuse=2, col_mode=cm)
        got = imageio.planar_to_interleaved(
            np.asarray(out).astype(np.uint8))
        if not np.array_equal(got, oracle.run_serial_u8(img, filt, 4)):
            failures.append(f"dispatch col_mode={cm!r} bytes drifted")
    row = bench.bench_iterate((args.rows, args.cols), filt, 2, mesh=one,
                              backend="pallas_rdma", reps=1, fuse=2)
    if row.get("col_mode") not in ("packed", "strided"):
        failures.append(f"bench row stamps col_mode={row.get('col_mode')!r}")

    # ---- 4: perf sentry fold — the smoke's own history, seed + re-gate.
    out_path = Path(args.out)
    out_path.parent.mkdir(parents=True, exist_ok=True)
    rows_path = out_path.with_suffix(".rows.json")
    rows_path.write_text(json.dumps([row]))
    hist = Path(args.history)
    hist.parent.mkdir(parents=True, exist_ok=True)
    hist.write_text("")   # the smoke's OWN history: truncate per run
    gate = [sys.executable, str(SCRIPTS / "perf_gate.py"),
            "--history", str(hist), "--row", str(rows_path), "--quiet"]
    rc_seed = subprocess.run([*gate, "--update"], check=False).returncode
    rc_pass = subprocess.run(gate, check=False).returncode
    if rc_seed != 0:
        failures.append(f"perf_gate seed run exited {rc_seed}")
    if rc_pass != 0:
        failures.append(f"perf_gate re-gate exited {rc_pass}")

    summary = {
        "probe": "channels_smoke",
        "workload": f"blur3 {args.rows}x{args.cols} mesh={args.mesh}",
        "cells": len(cells),
        "skipped_capability": len(skips),
        "channel_builds_converge": after_first["builds"],
        "channel_hits_total": after_variant["hits"],
        "mg_level_identities": len(keys),
        "auto_col_mode": auto_pick,
        "bench_col_mode": row.get("col_mode"),
        "converge_iters": [int(it1), int(it2)],
        "failures": len(failures),
        "failure_detail": failures[:8],
    }
    out_path.write_text(json.dumps(summary, indent=2))
    print(json.dumps(summary), flush=True)
    return 1 if failures else 0


if __name__ == "__main__":
    sys.exit(main())
