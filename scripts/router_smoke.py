#!/usr/bin/env python
"""Replica-router smoke: the ``run_t1.sh --router-smoke`` leg.

Boot THREE in-process replicas behind ``serving.router.ReplicaRouter``
(per-tenant token buckets armed), push 100 requests across 2 tenants —
one polite, one greedy enough to overrun its bucket — kill one KEY-HOME
replica mid-run, and assert the whole round-14 layer held together:

1. **zero non-rejected failures** — every request either completed or
   ended in a typed RETRYABLE rejection (client backoff honored, capped);
2. every completed response **byte-identical to the NumPy oracle**;
3. **>= 1 observed failover** — a request completed off its
   consistent-hash home after the kill (the serve-through-failure gate);
4. **tenant-quota sheds typed correctly** — the greedy tenant saw
   ``rejected: tenant_quota`` with ``retryable: true`` + a
   ``retry_after_s`` hint, and the polite tenant saw NONE (bucket
   isolation);
5. **warm caches partition** — before the kill, each of the distinct
   compile keys is resident on EXACTLY ONE replica (consistent-hash
   partitioning: no duplicate builds); after the kill + failover, a key
   may appear on at most its home + one re-home.

The summary row lands in ``--out`` (``evidence/router_smoke.json``, the
supervisor leg's done_file) with ``"failures": 0`` iff every gate held,
then feeds ``scripts/perf_gate.py`` against the smoke's OWN history file
(seed + re-gate — NOT the committed ``evidence/perf_history.jsonl``).
"""

from __future__ import annotations

import argparse
import base64
import json
import subprocess
import sys
import threading
import time
from pathlib import Path

import _path  # noqa: F401  (repo root + JAX_PLATFORMS re-apply)

SCRIPTS = Path(__file__).resolve().parent


def main() -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--n", type=int, default=100)
    ap.add_argument("--rows", type=int, default=48)
    ap.add_argument("--cols", type=int, default=64)
    ap.add_argument("--mesh", default="2x2", help="grid per replica")
    ap.add_argument("--out", default="evidence/router_smoke.json")
    ap.add_argument("--history",
                    default="evidence/router_smoke_history.jsonl",
                    help="the smoke's OWN perf history, seeded fresh each "
                         "run; never point this at the committed "
                         "evidence/perf_history.jsonl")
    args = ap.parse_args()

    import numpy as np

    from parallel_convolution_tpu.obs import events as obs_events
    from parallel_convolution_tpu.ops import filters, oracle
    from parallel_convolution_tpu.parallel.mesh import mesh_from_spec
    from parallel_convolution_tpu.serving.router import (
        InProcessReplica, ReplicaRouter, TenantQuotas, route_key,
    )
    from parallel_convolution_tpu.serving.service import ConvolutionService
    from parallel_convolution_tpu.utils import imageio

    obs_events.install_from_env()
    failures: list[str] = []
    t0 = time.time()

    img = imageio.generate_test_image(args.rows, args.cols, "grey", seed=7)
    b64 = base64.b64encode(np.ascontiguousarray(img).tobytes()).decode()
    iters_pool = [1, 2, 3]
    oracles = {it: oracle.run_serial_u8(img, filters.get_filter("blur3"),
                                        it) for it in iters_pool}

    def factory():
        return ConvolutionService(mesh_from_spec(args.mesh),
                                  max_delay_s=0.002, max_queue=256)

    replicas = [InProcessReplica(factory, name=f"r{i}") for i in range(3)]
    # The greedy tenant's bucket is sized to overrun under this run's
    # offered rate (it still completes via backoff); the polite tenant
    # is unlimited — its gate is seeing ZERO quota sheds (isolation).
    router = ReplicaRouter(
        replicas,
        quotas=TenantQuotas(rate=200.0, burst=16.0,
                            overrides={"greedy": (25.0, 4.0),
                                       "polite": (0.0, 1.0)}),
        breaker_threshold=2, breaker_cooldown_s=0.2, poll_interval_s=0.05)

    def body_for(i: int, tenant: str) -> dict:
        return {"image_b64": b64, "rows": args.rows, "cols": args.cols,
                "mode": "grey", "filter": "blur3",
                "iters": iters_pool[i % len(iters_pool)],
                "request_id": f"rs{i}", "tenant": tenant}

    # ---- phase 1: warm the key space, then check cache partitioning.
    # Distinct warm-phase request_ids: reusing rs0..rs2 would let the
    # replica dedup ledger serve 3 of phase 2's measured requests from
    # cache (zero-latency rows, a silently smaller real sample).
    for it in iters_pool:
        warm_body = dict(body_for(it - 1, "polite"),
                         request_id=f"warm{it}")
        status, wire = router.request(warm_body)
        if not wire.get("ok"):
            failures.append(f"warm request iters={it} failed: {wire}")
    # Residency by iters: read each replica's resident keys directly —
    # the consistent-hash partition gate (each key warm on EXACTLY one
    # replica; compile counters cannot hide a duplicate build).
    residency: dict[int, list[str]] = {it: [] for it in iters_pool}
    for rep in replicas:
        for key in rep.service.engine._entries:
            residency[key.iters].append(rep.name)
    partition_ok = all(len(v) == 1 for v in residency.values())
    if not partition_ok:
        failures.append(f"warm caches not partitioned: { {k: v for k, v in residency.items()} }")
    homes = {it: router.ring.candidates(
        route_key(body_for(it - 1, "polite")))[0] for it in iters_pool}
    for it, owner in residency.items():
        if owner and owner[0] != homes[it]:
            failures.append(
                f"key iters={it} resident on {owner[0]}, home {homes[it]}")

    # ---- phase 2: 100 requests across 2 tenants, kill a home mid-run.
    results, lock = [], threading.Lock()
    counter = [0]

    def one(i: int) -> None:
        tenant = "greedy" if i % 2 else "polite"
        body = body_for(i, tenant)
        quota_shed = False
        for attempt in range(5):
            status, wire = router.request(dict(body))
            if wire.get("rejected") == "tenant_quota":
                quota_shed = True
                if wire.get("retry_after_s") is None or not wire.get(
                        "retryable"):
                    with lock:
                        results.append({"i": i, "ok": False,
                                        "tenant": tenant,
                                        "bad_quota_shape": True,
                                        "wire": wire})
                    return
            if wire.get("ok") or not wire.get("retryable"):
                break
            time.sleep(min(float(wire.get("retry_after_s") or 0.02), 0.2))
        it = iters_pool[i % len(iters_pool)]
        byte_ok = None
        if wire.get("ok"):
            got = np.frombuffer(base64.b64decode(wire["image_b64"]),
                                np.uint8).reshape(args.rows, args.cols)
            byte_ok = bool(np.array_equal(got, oracles[it]))
        with lock:
            results.append({
                "i": i, "ok": bool(wire.get("ok")), "tenant": tenant,
                "byte_ok": byte_ok, "quota_shed_seen": quota_shed,
                "rejected": wire.get("rejected"),
                "retryable": wire.get("retryable"),
                "router": wire.get("router", {}),
            })

    def traffic() -> None:
        while True:
            with lock:
                i = counter[0]
                if i >= args.n:
                    return
                counter[0] += 1
            one(i)
            time.sleep(0.005)   # pace: the stream must span the kill

    workers = [threading.Thread(target=traffic, daemon=True)
               for _ in range(4)]
    for w in workers:
        w.start()
    time.sleep(0.5)
    victim = homes[iters_pool[0]]
    router.replica(victim).kill()
    obs_events.emit("router", event="kill", replica=victim)
    for w in workers:
        w.join(600)
    wall = time.time() - t0

    completed = [r for r in results if r["ok"]]
    byte_fails = [r for r in completed if not r["byte_ok"]]
    non_rejected = [r for r in results
                    if not r["ok"] and not r.get("retryable")
                    and not r.get("bad_quota_shape")]
    bad_quota = [r for r in results if r.get("bad_quota_shape")]
    failovers = sum(
        1 for r in completed
        if r["router"].get("failovers", 0) > 0
        or (r["router"].get("replica") and r["router"].get("home")
            and r["router"]["replica"] != r["router"]["home"]))
    greedy_quota_sheds = sum(1 for r in results
                             if r["tenant"] == "greedy"
                             and r.get("quota_shed_seen"))
    polite_quota_sheds = sum(1 for r in results
                             if r["tenant"] == "polite"
                             and r.get("quota_shed_seen"))
    snap = router.snapshot()

    if byte_fails:
        failures.append(f"{len(byte_fails)} oracle byte mismatches")
    if non_rejected:
        failures.append(
            f"{len(non_rejected)} non-rejected failures, e.g. "
            f"{non_rejected[0]}")
    if bad_quota:
        failures.append(
            f"{len(bad_quota)} tenant_quota sheds missing retryable/"
            "retry_after_s")
    if failovers < 1:
        failures.append("no failover observed despite a killed home")
    if greedy_quota_sheds < 1:
        failures.append("greedy tenant never hit its bucket")
    if polite_quota_sheds:
        failures.append(
            f"polite tenant saw {polite_quota_sheds} quota sheds "
            "(bucket isolation broken)")

    # Post-kill residency: a key may live on at most home + one re-home.
    post = {it: [] for it in iters_pool}
    for rep in replicas:
        if rep.service is None:
            continue
        for key in rep.service.engine._entries:
            post[key.iters].append(rep.name)
    for it, owners in post.items():
        if len(owners) > 2:
            failures.append(
                f"key iters={it} resident on {len(owners)} replicas "
                f"({owners}): duplicate builds beyond failover re-homing")

    channels = 1
    px = args.rows * args.cols * channels * sum(
        iters_pool[r["i"] % len(iters_pool)] for r in completed)
    row = {
        "workload": f"router-smoke blur3 {args.rows}x{args.cols} "
                    f"3 replicas kill-1",
        "n": args.n,
        "completed": len(completed),
        "failovers_observed": failovers,
        "tenant_quota_sheds_greedy": greedy_quota_sheds,
        "tenant_quota_sheds_polite": polite_quota_sheds,
        "partition_ok": partition_ok,
        "residency_pre_kill": {str(k): v for k, v in residency.items()},
        "residency_post_kill": {str(k): v for k, v in post.items()},
        "killed": victim,
        "router": snap["router"],
        "effective_backend": "shifted",
        "mesh": args.mesh,
        "wall_s": round(wall, 3),
        "gpixels_per_s": round(px / wall / 1e9, 6) if wall else None,
        "failures": len(failures),
        "failure_detail": failures[:6],
    }
    router.close()

    # ---- perf sentry feed: seed the smoke's own history, then re-gate.
    out = Path(args.out)
    out.parent.mkdir(parents=True, exist_ok=True)
    out.write_text(json.dumps(row, indent=2))
    hist = Path(args.history)
    hist.parent.mkdir(parents=True, exist_ok=True)
    hist.write_text("")   # the smoke's OWN history: truncate per run
    gate = [sys.executable, str(SCRIPTS / "perf_gate.py"),
            "--history", str(hist), "--row", str(out), "--quiet"]
    rc_seed = subprocess.run([*gate, "--update"], check=False).returncode
    rc_pass = subprocess.run(gate, check=False).returncode
    if rc_seed != 0:
        failures.append(f"perf_gate seed run exited {rc_seed}")
    if rc_pass != 0:
        failures.append(f"perf_gate re-gate exited {rc_pass}")
    row["failures"] = len(failures)
    row["failure_detail"] = failures[:8]
    out.write_text(json.dumps(row, indent=2))
    print(json.dumps(row), flush=True)
    return 1 if failures else 0


if __name__ == "__main__":
    sys.exit(main())
