#!/usr/bin/env python
"""A/B `jnp.rint` vs the magic-number round in the REAL fused kernel (TPU).

DESIGN.md's round-5 correction says the credible next levers cut FMA or
*rint* work.  The candidate: for f32 accumulators with |acc| < 2^22,

    rint(acc) == (acc + 1.5*2^23) - 1.5*2^23        (two f32 adds)

exactly — the add forces rounding to integer at ulp=1 with the
hardware's round-half-to-even, the subtract recovers the integer
losslessly.  Every quantize-mode accumulator here is bounded by
255 * L1(taps) << 2^22, so substitution is bit-exact by construction;
this script additionally PROVES it on device by byte-comparing a small
run, then prices it on the flagship configs.

Method: one subprocess per mode (fresh jit traces; separate processes
prevent any cached-executable crosstalk).  The kernels resolve their
round mode via `_round_mode_for` from module globals at trace time, so
the "rint" arm pins that selector to "rint" before first use, and the
"magic" arm is the stock library (the magic round became the default
after this script's first run measured +15.6%).  Each child runs
bench_iterate on the flagship configs and writes a 512x640 u8 10-iter
output for the parent to byte-compare across modes.

Usage:  python scripts/round_mode_ab.py            # parent: full A/B
        python scripts/round_mode_ab.py --child rint|magic <outdir>
"""

from __future__ import annotations

import json
import os
import subprocess
import sys

import _path  # noqa: F401

CONFIGS = [
    # (backend, storage, fuse, shape, iters) — the two flagship rows.
    ("pallas_sep", "u8", 32, (8192, 8192), 100),
    ("pallas_sep", "bf16", 32, (8192, 8192), 100),
]


def child(mode: str, outdir: str) -> int:
    from parallel_convolution_tpu.utils.platform import (
        apply_platform_env, enable_compile_cache,
    )

    apply_platform_env()
    enable_compile_cache()

    import jax.numpy as jnp
    import numpy as np

    from parallel_convolution_tpu.ops import pallas_stencil
    from parallel_convolution_tpu.ops.filters import get_filter
    from parallel_convolution_tpu.parallel import step as step_lib
    from parallel_convolution_tpu.parallel.mesh import make_grid_mesh
    from parallel_convolution_tpu.utils import bench

    # Since the A/B's first run (2026-07-31), the magic round IS the
    # library default (`_round_mode_for`), so the arms are: "rint" =
    # force the old behavior by pinning the mode selector; "magic" =
    # stock library.  (The original run predated the flip and patched
    # the magic side instead; the measured rows are identical either
    # way because both arms trace fresh in their own subprocess.)
    if mode == "rint":
        force_rint = lambda taps, interpret: "rint"  # noqa: E731
        pallas_stencil._round_mode_for = force_rint
        # pallas_rdma binds _round_mode_for by value at import — pin its
        # module-level reference too, so an RDMA config added to CONFIGS
        # cannot silently run magic-vs-magic.
        from parallel_convolution_tpu.ops import pallas_rdma
        pallas_rdma._round_mode_for = force_rint

    filt = get_filter("blur3")
    mesh = make_grid_mesh()

    # Byte-proof leg: small deterministic u8 run through the fused path.
    rng = np.random.default_rng(7)
    x = rng.integers(0, 256, size=(1, 512, 640)).astype(np.float32)
    xs, valid_hw, block_hw = step_lib._prepare(x, mesh, filt.radius, "u8")
    fn = step_lib._build_iterate(mesh, filt, 10, True, valid_hw, block_hw,
                                 "pallas_sep", 5)
    out = np.asarray(jnp.asarray(fn(xs)))
    np.save(os.path.join(outdir, f"proof_{mode}.npy"),
            out.astype(np.uint8))

    for backend, storage, fuse, shape, iters in CONFIGS:
        row = bench.bench_iterate(shape, filt, iters, mesh=mesh,
                                  backend=backend, storage=storage,
                                  fuse=fuse, reps=3)
        row["round_mode"] = mode
        print(json.dumps(row), flush=True)
    return 0


def main() -> int:
    if len(sys.argv) >= 2 and sys.argv[1] == "--child":
        return child(sys.argv[2], sys.argv[3])

    import tempfile

    import numpy as np

    # Fresh per-invocation dir: a fixed path let a child that died before
    # np.save silently byte-compare a STALE proof from an earlier run
    # (spurious bitexact=true), or crash the parent on first use.
    outdir = tempfile.mkdtemp(prefix="round_mode_ab_")
    rows = []
    for mode in ("rint", "magic"):
        p = subprocess.run(
            [sys.executable, os.path.abspath(__file__), "--child", mode,
             outdir],
            capture_output=True, text=True, timeout=3000,
            cwd=os.path.dirname(os.path.abspath(__file__)),
        )
        sys.stderr.write(p.stderr[-2000:])
        if p.returncode != 0:
            print(json.dumps({"mode": mode, "error": "child failed",
                              "rc": p.returncode}), flush=True)
            continue
        for line in p.stdout.splitlines():
            line = line.strip()
            if line.startswith("{"):
                rows.append(json.loads(line))
                print(line, flush=True)

    proofs, missing = {}, []
    for mode in ("rint", "magic"):
        path = os.path.join(outdir, f"proof_{mode}.npy")
        if os.path.exists(path):
            proofs[mode] = np.load(path)
        else:
            missing.append(mode)
    verdict = {"probe": "round_mode_ab byte-proof",
               "workload": "blur3 512x640 u8 10 iters fused fuse=5"}
    if missing:
        # A child died before writing its proof: there is no comparison —
        # say so (null verdict + the missing arms) instead of crashing or,
        # worse, comparing leftovers.
        bitexact = False
        verdict["bitexact_rint_vs_magic"] = None
        verdict["proof_missing"] = missing
    else:
        bitexact = bool(np.array_equal(proofs["rint"], proofs["magic"]))
        verdict["bitexact_rint_vs_magic"] = bitexact
    by = {}
    for r in rows:
        key = f'{r["backend"]}/{r["storage"]}/fuse{r["fuse"]}'
        by.setdefault(key, {})[r["round_mode"]] = r["gpixels_per_s_per_chip"]
    for key, d in by.items():
        if "rint" in d and "magic" in d and d["rint"]:
            verdict[f"speedup[{key}]"] = round(d["magic"] / d["rint"], 4)
    print(json.dumps(verdict), flush=True)
    return 0 if bitexact else 1


if __name__ == "__main__":
    sys.exit(main())
