#!/usr/bin/env python
"""Cross-validate the amortized halo-p50 metric against fuse wall deltas.

Two independent procedures should agree on the order of the per-exchange
cost (BASELINE.json "halo p50", round-5 definition):

1. **Direct differenced measure** (`bench_halo_p50`): per trial, a
   256-round chained LIVE exchange span (ghost-corner window carried
   forward so nothing is elidable) minus a local-roll control span,
   over 256 — what one exchange costs.
2. **Derived from the fuse saving** (this script): the same workload run
   with fuse=1 (N exchanges) and fuse=T (N/T deeper exchanges);
   ``(wall_1 - wall_T) / (N - N/T)`` is the realized saving per skipped
   exchange — what fuse=T actually buys.

The derived number is a LOWER bound on the direct one: the fused run
pays extra compute for the overlap rim and its surviving exchanges move
T×-deeper slabs, both of which shrink the delta.  ``consistent`` is
therefore strict: ``0 < derived <= 1.25 × direct`` (the 25% headroom is
wall noise, nothing more) — a derived value meaningfully ABOVE the
direct one falsifies a procedure.  It already did once: against the
first round-5 revision of the metric (un-differenced chained rounds,
which XLA cancelled to zero collective-permutes) this script read
derived = 44× "direct", which is how the elision bug was caught.

Runs anywhere with a multi-device mesh; on the 8-virtual-CPU mesh it is
a mechanism cross-check (like the halo proxy itself), on a real pod it
would be ICI.  Prints one JSON row.

  XLA_FLAGS=--xla_force_host_platform_device_count=8 JAX_PLATFORMS=cpu \
    python scripts/halo_cross_check.py
"""

from __future__ import annotations

import argparse
import json
import sys

import _path  # noqa: F401  (repo root onto sys.path)


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--block", type=int, default=512,
                    help="per-device block edge (the halo-p50 workload)")
    ap.add_argument("--iters", type=int, default=64)
    ap.add_argument("--fuse", type=int, default=8)
    ap.add_argument("--reps", type=int, default=5)
    args = ap.parse_args()

    import jax

    from parallel_convolution_tpu.ops.filters import get_filter
    from parallel_convolution_tpu.parallel.mesh import (
        grid_shape, make_grid_mesh,
    )
    from parallel_convolution_tpu.utils import bench

    mesh = make_grid_mesh(jax.devices())
    grid = grid_shape(mesh)
    if mesh.size < 2:
        print(json.dumps({"error": "needs a multi-device mesh "
                          "(1x1 has no exchange to price)"}))
        return 1

    filt = get_filter("blur3")
    H = args.block * grid[0]
    W = args.block * grid[1]
    N, T = args.iters, args.fuse

    def wall(fuse):
        row = bench.bench_iterate((H, W), filt, N, mesh=mesh,
                                  backend="shifted", storage="bf16",
                                  fuse=fuse, reps=args.reps)
        return row["wall_s"], row

    w1, row1 = wall(1)
    wT, rowT = wall(T)
    skipped = N - N // T
    derived_us = 1e6 * (w1 - wT) / skipped

    direct = bench.bench_halo_p50((args.block, args.block), r=filt.radius,
                                  mesh=mesh, trials=12)
    p50 = direct.get("p50_us")
    ratio = None if not p50 else round(derived_us / p50, 3)
    row = {
        "probe": "halo_cross_check",
        "mesh": "x".join(str(s) for s in grid),
        "block": f"{args.block}x{args.block}",
        "iters": N,
        "fuse": T,
        "wall_fuse1_s": w1,
        "wall_fuseT_s": wT,
        "derived_saving_us_per_exchange": round(derived_us, 1),
        "amortized_p50_us": p50,
        "derived_over_direct": ratio,
        "consistent": (None if ratio is None
                       else bool(0.0 < ratio <= 1.25)),
        "note": ("derived is a lower bound (rim recompute + deeper fused "
                 "slabs shrink the delta; compute noise can push it below "
                 "zero = inconsistent); consistent iff 0 < ratio <= 1.25"),
    }
    print(json.dumps(row), flush=True)
    return 0


if __name__ == "__main__":
    sys.exit(main())
