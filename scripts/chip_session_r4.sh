#!/bin/sh
# One-shot chip session: run every record that is waiting on real TPU
# silicon (BASELINE.md "Round-4 chip-session status note") and land the
# rows in evidence/.  Safe to re-run; each tool is independent.
#
#   sh scripts/chip_session_r4.sh
#
# Probe first — the axon tunnel dies transiently and jax then HANGS on
# backend init (memory: tpu-env-quirks):
#   timeout 60 python -c "import jax; print(jax.devices())"
set -x
cd "$(dirname "$0")/.."

python scripts/validate_walls.py > evidence/validate_walls.json \
  2> /tmp/vw.err && echo "validate_walls OK"
python scripts/converge_fuse_bench.py > evidence/converge_fuse_tpu.jsonl \
  2> /tmp/cf.err && echo "converge_fuse OK"
python scripts/rdma_on_silicon.py > evidence/rdma_silicon.json \
  2> /tmp/rs.err && echo "rdma_on_silicon (incl. tiled) OK"
python bench.py > /tmp/bench_r4_sanity.json 2> /tmp/bench_r4_sanity.err \
  && tail -c 400 /tmp/bench_r4_sanity.json
