#!/bin/sh
# One-shot chip session: run every record that is waiting on real TPU
# silicon (BASELINE.md "Round-4 chip-session status note") and land the
# rows in evidence/.  Safe to re-run; each tool is independent.
#
#   sh scripts/chip_session_r4.sh
#
#
# Outputs go through a temp file + rename so a failed (or interrupted)
# rerun can never leave a truncated/empty evidence row behind.
set -x
cd "$(dirname "$0")/.."

# Dead-tunnel guard: a dead tunnel makes jax HANG on backend init, which
# would eat the whole session window; fail fast instead.
timeout 60 python -c "import jax; print(jax.devices())"   || { echo "tunnel dead; aborting chip session" >&2; exit 1; }

run_to() {
  out="$1"; shift
  if "$@" > "$out.tmp" 2> "/tmp/$(basename "$out").err"; then
    mv "$out.tmp" "$out" && echo "$out OK"
  else
    # Never leave a stale .tmp in evidence/ — it reads like a record.
    rm -f "$out.tmp"
    echo "$out FAILED (stderr: /tmp/$(basename "$out").err)" >&2
  fi
}

run_to evidence/validate_walls.json python scripts/validate_walls.py
run_to evidence/converge_fuse_tpu.jsonl python scripts/converge_fuse_bench.py
run_to evidence/rdma_silicon.json python scripts/rdma_on_silicon.py
python bench.py > /tmp/bench_r4_sanity.json 2> /tmp/bench_r4_sanity.err \
  && tail -c 400 /tmp/bench_r4_sanity.json
