"""Make the repo root importable when a script runs as `python scripts/x.py`
(sys.path[0] is then scripts/, not the repo root)."""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
