"""Make the repo root importable when a script runs as `python scripts/x.py`
(sys.path[0] is then scripts/, not the repo root) — and honor a
``JAX_PLATFORMS`` env pin before any backend can initialize.

The second job matters because the site hook pins the tunnel platform
programmatically, which beats the env var: a script pinned to CPU would
otherwise still initialize the tunnel backend and HANG whenever the
tunnel is dead.  Doing it here makes every script hang-proof by
construction instead of each one remembering to call the shim (this is a
no-op — importing nothing — when JAX_PLATFORMS is unset).
"""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

if os.environ.get("JAX_PLATFORMS"):
    from parallel_convolution_tpu.utils.platform import apply_platform_env

    apply_platform_env()
