#!/usr/bin/env python
"""Multigrid smoke: the V-cycle's convergence claim + the registry
migration proof, end-to-end on the CPU mesh.

The ``run_t1.sh --mg-smoke`` leg.  Gates, in order:

1. CONVERGENCE WIN — converge the same seeded Poisson problem (random
   f32 field, ``jacobi3``, zero boundary) both ways on the 2x4 mesh
   with the SAME stopping measure (max-abs change of one fine-grid
   sweep).  Multigrid must reach tol in ≥10× fewer fine-grid work
   units than plain Jacobi (measured ~44× at 96x64/1e-6).
2. ORACLE AGREEMENT — the two final states agree to ``--oracle-tol``
   (1e-3; measured ~2e-4).  Both sit near the true fixed point, so the
   bound is an honest conditioning-adjusted gate, not a tautology.
3. REGISTRY MIGRATION — the kernel-form registry's smoother key set is
   EXACTLY the old ``backend ==`` ladder, and every registered backend
   still produces byte-identical output vs the serial oracle through
   the new dispatch (quantized u8 semantics, the round-1 contract).
4. WARM KEYS COMPILE FLAT — a second identical multigrid solve hits
   every compiled level program (lru misses flat) and reproduces the
   bytes exactly.
5. PERF SENTRY FOLD — the jacobi/multigrid convergence rows
   (``bench_converge``: solver, mg_levels, work_units_to_tol) seed and
   re-gate the smoke's OWN history through ``scripts/perf_gate.py`` —
   whose row key separates solvers, so the multigrid row is never
   judged against the jacobi baseline.

One summary row lands in ``--out`` (``evidence/mg_smoke.json``, the
supervisor leg's done_file) with ``"failures": 0`` iff every gate held.
"""

from __future__ import annotations

import argparse
import json
import subprocess
import sys
from pathlib import Path

import _path  # noqa: F401  (repo root + JAX_PLATFORMS re-apply)

SCRIPTS = Path(__file__).resolve().parent


def main() -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--rows", type=int, default=96)
    ap.add_argument("--cols", type=int, default=64)
    ap.add_argument("--mesh", default="2x4")
    ap.add_argument("--tol", type=float, default=1e-6,
                    help="stopping tolerance for BOTH solvers")
    ap.add_argument("--oracle-tol", type=float, default=1e-3,
                    help="max-abs agreement bound between the two "
                         "converged states")
    ap.add_argument("--min-ratio", type=float, default=10.0,
                    help="required jacobi/multigrid work-unit ratio")
    ap.add_argument("--max-iters", type=int, default=60000)
    ap.add_argument("--out", default="evidence/mg_smoke.json")
    ap.add_argument("--history", default="evidence/mg_smoke_history.jsonl",
                    help="the smoke's OWN perf history, seeded fresh "
                         "each run; never the committed "
                         "evidence/perf_history.jsonl")
    args = ap.parse_args()

    import numpy as np

    from parallel_convolution_tpu.ops import filters, oracle
    from parallel_convolution_tpu.parallel import kernels as kernel_forms
    from parallel_convolution_tpu.parallel import step as step_lib
    from parallel_convolution_tpu.parallel.mesh import mesh_from_spec
    from parallel_convolution_tpu.solvers import multigrid as mg
    from parallel_convolution_tpu.utils import bench, imageio
    from parallel_convolution_tpu.utils.config import BACKENDS, BOUNDARIES

    failures: list[str] = []
    mesh = mesh_from_spec(args.mesh)
    filt = filters.get_filter("jacobi3")
    H, W = args.rows, args.cols
    rng = np.random.default_rng(0)
    x = rng.standard_normal((1, H, W)).astype(np.float32)

    # ---- 1+2: convergence win + oracle agreement (bench_converge rows
    # carry the solver-comparable accounting the perf fold gates).
    row_mg = bench.bench_converge(
        (H, W), filt, tol=args.tol, max_iters=args.max_iters, mesh=mesh,
        solver="multigrid", seed=0)
    row_j = bench.bench_converge(
        (H, W), filt, tol=args.tol, max_iters=args.max_iters, mesh=mesh,
        solver="jacobi", check_every=200, seed=0)
    if not row_mg["converged"]:
        failures.append(f"multigrid did not reach tol={args.tol} within "
                        f"{args.max_iters} work units")
    if not row_j["converged"]:
        failures.append(f"jacobi did not reach tol={args.tol} within "
                        f"{args.max_iters} iterations")
    ratio = (row_j["work_units_to_tol"] / row_mg["work_units_to_tol"]
             if row_mg["work_units_to_tol"] else 0.0)
    if ratio < args.min_ratio:
        failures.append(
            f"work-unit ratio {ratio:.1f}x below the {args.min_ratio}x "
            f"gate (jacobi {row_j['work_units_to_tol']}, multigrid "
            f"{row_mg['work_units_to_tol']})")

    out_mg, _ = mg.mg_converge(x, filt, tol=args.tol,
                               max_iters=args.max_iters, mesh=mesh)
    out_j, _ = step_lib.sharded_converge(
        x, filt, tol=args.tol, max_iters=args.max_iters, check_every=200,
        mesh=mesh, quantize=False)
    oracle_diff = float(np.abs(np.asarray(out_j, np.float32)
                               - out_mg).max())
    if oracle_diff > args.oracle_tol:
        failures.append(f"final states disagree: max|mg - jacobi| = "
                        f"{oracle_diff:.3g} > {args.oracle_tol}")

    # ---- 3: registry migration proof.
    want_keys = frozenset((2, b, bd) for b in BACKENDS for bd in BOUNDARIES)
    got_keys = kernel_forms.registered_keys("smooth")
    if got_keys != want_keys:
        failures.append(
            f"registry smoother keys drifted from the old ladder: "
            f"extra={sorted(got_keys - want_keys)} "
            f"missing={sorted(want_keys - got_keys)}")
    img = np.random.default_rng(1).integers(
        0, 256, (48, 64)).astype(np.uint8)
    want_bytes = oracle.run_serial_u8(img, filters.get_filter("blur3"), 2)
    planar = imageio.interleaved_to_planar(img).astype(np.float32)
    backends_ok = []
    from parallel_convolution_tpu.parallel.mesh import make_grid_mesh
    from parallel_convolution_tpu.utils import jax_compat

    for b in BACKENDS:
        # The RDMA protocol's multi-device CPU simulation needs the
        # DMA-faithful TPU interpreter; without it (jax 0.4.x) tier-1's
        # own RDMA tests skip to the degenerate 1x1 grid, where extent-1
        # axes statically elide every RDMA construct but the full fused
        # compute path still runs.  Mirror that rule here.
        b_mesh, tag = mesh, b
        if b == "pallas_rdma" and not jax_compat.HAS_TPU_INTERPRET:
            import jax as _jax

            b_mesh = make_grid_mesh(_jax.devices()[:1], (1, 1))
            tag = f"{b}(degenerate-1x1: no faithful interpreter)"
        try:
            got = step_lib.sharded_iterate(
                planar, filters.get_filter("blur3"), 2, mesh=b_mesh,
                backend=b)
            got = np.asarray(got).astype(np.uint8)[0]
            if np.array_equal(got, want_bytes):
                backends_ok.append(tag)
            else:
                failures.append(f"backend {b} bytes drifted through the "
                                "registry")
        except Exception as e:  # noqa: BLE001 — per-backend, reported
            failures.append(f"backend {b} failed through the registry: "
                            f"{repr(e)[:200]}")

    # ---- 4: warm keys compile flat (and deterministically).
    misses = (mg._build_fine_smooth.cache_info().misses,
              mg._build_smooth_rhs.cache_info().misses,
              mg._build_residual_restrict.cache_info().misses,
              mg._build_prolong_correct.cache_info().misses)
    out_mg2, _ = mg.mg_converge(x, filt, tol=args.tol,
                                max_iters=args.max_iters, mesh=mesh)
    warm = (mg._build_fine_smooth.cache_info().misses,
            mg._build_smooth_rhs.cache_info().misses,
            mg._build_residual_restrict.cache_info().misses,
            mg._build_prolong_correct.cache_info().misses)
    warm_delta = sum(w - m for w, m in zip(warm, misses))
    if warm_delta:
        failures.append(f"warm multigrid re-run compiled {warm_delta} "
                        "fresh level programs (expected 0)")
    if not np.array_equal(out_mg, out_mg2):
        failures.append("warm multigrid re-run changed bytes")

    row = {
        "workload": f"mg-smoke jacobi3 {H}x{W} tol={args.tol} "
                    f"mesh={args.mesh}",
        "solver_rows": {"jacobi": row_j, "multigrid": row_mg},
        "work_units_jacobi": row_j["work_units_to_tol"],
        "work_units_multigrid": row_mg["work_units_to_tol"],
        "mg_cycles": row_mg.get("cycles"),
        "mg_levels": row_mg.get("mg_levels"),
        "work_unit_ratio": round(ratio, 2),
        "min_ratio_gate": args.min_ratio,
        "oracle_max_abs_diff": oracle_diff,
        "oracle_tol": args.oracle_tol,
        "registry_smooth_keys": len(got_keys),
        "backends_byte_identical": backends_ok,
        "warm_compile_delta": warm_delta,
    }

    # ---- 5: perf sentry fold — the smoke's own history, seed + re-gate.
    out_path = Path(args.out)
    out_path.parent.mkdir(parents=True, exist_ok=True)
    rows_path = out_path.with_suffix(".rows.json")
    rows_path.write_text(json.dumps([row_j, row_mg]))
    hist = Path(args.history)
    hist.parent.mkdir(parents=True, exist_ok=True)
    hist.write_text("")   # the smoke's OWN history: truncate per run
    gate = [sys.executable, str(SCRIPTS / "perf_gate.py"),
            "--history", str(hist), "--row", str(rows_path), "--quiet"]
    rc_seed = subprocess.run([*gate, "--update"], check=False).returncode
    rc_pass = subprocess.run(gate, check=False).returncode
    if rc_seed != 0:
        failures.append(f"perf_gate seed run exited {rc_seed}")
    if rc_pass != 0:
        failures.append(f"perf_gate re-gate exited {rc_pass}")

    row["failures"] = len(failures)
    row["failure_detail"] = failures[:8]
    out_path.write_text(json.dumps(row, indent=2))
    print(json.dumps({k: v for k, v in row.items()
                      if k != "solver_rows"}), flush=True)
    return 1 if failures else 0


if __name__ == "__main__":
    sys.exit(main())
