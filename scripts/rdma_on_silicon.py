#!/usr/bin/env python
"""Execute the fused RDMA halo kernel on the real attached TPU chip.

VERDICT r03 item 4: the module docstring's claim that the kernel
"compiles and runs there in its degenerate local form" had never been
executed for the record.  This script is that record: on a 1×1 mesh the
kernel's exchange degenerates to local ghost zeroing (no remote partner,
the neighbor barrier waits on zero signals), but Mosaic still compiles
the full program — remote-copy primitives, semaphores, barrier — for
real silicon, which interpret mode cannot prove (see the _sublane
history in ops/pallas_stencil.py for a Mosaic-only rejection).

Runs the kernel for several iterations on the attached device, checks
bit-exactness vs the NumPy oracle, and prints one JSON row for
BASELINE.md.  Exits 1 (with the row saying so) off-TPU.
"""

from __future__ import annotations

import json
import sys
import time

import _path  # noqa: F401


def main() -> int:
    from parallel_convolution_tpu.utils.platform import (
        apply_platform_env, enable_compile_cache, on_tpu,
    )

    apply_platform_env()
    enable_compile_cache()

    import jax
    import numpy as np

    row: dict = {"probe": "pallas_rdma on silicon"}
    if not on_tpu():
        row["skipped"] = "no TPU attached"
        print(json.dumps(row))
        return 1

    from parallel_convolution_tpu.ops import filters, oracle
    from parallel_convolution_tpu.parallel import mesh as mesh_lib, step
    from parallel_convolution_tpu.utils import bench, imageio

    d = jax.devices()[0]
    row["device"] = f"{d.device_kind} ({d.platform})"
    mesh = mesh_lib.make_grid_mesh(jax.devices()[:1], (1, 1))

    img = imageio.generate_test_image(512, 768, "grey", seed=13)
    filt = filters.get_filter("blur3")
    iters = 8
    x = imageio.interleaved_to_planar(img).astype(np.float32)

    t0 = time.perf_counter()
    out = step.sharded_iterate(x, filt, iters, mesh=mesh, quantize=True,
                               backend="pallas_rdma")
    bench.fence(out)
    compile_and_run_s = time.perf_counter() - t0

    got = imageio.planar_to_interleaved(np.asarray(out).astype(np.uint8))
    want = oracle.run_serial_u8(img, filt, iters)
    bitexact = bool(np.array_equal(got, want))

    # Timed re-run (compile cached): honest wall via the platform's
    # trusted scheme would need the slope machinery; a plain fenced wall
    # is enough for a correctness record and labeled as such.
    t0 = time.perf_counter()
    out2 = step.sharded_iterate(x, filt, iters, mesh=mesh, quantize=True,
                                backend="pallas_rdma")
    bench.fence(out2)
    warm_s = time.perf_counter() - t0

    row.update({
        "workload": f"blur3 512x768 grey {iters} iters, 1x1 mesh "
                    "(degenerate local form; no remote partner exists "
                    "on one chip)",
        "mosaic_compiled": True,
        "bitexact_vs_oracle": bitexact,
        "first_call_s": round(compile_and_run_s, 3),
        "warm_wall_s": round(warm_s, 4),
        "timing": "fence (plain; correctness record, not a benchmark)",
    })

    # Tiled variant (round 4): force it on an aligned block well beyond
    # the monolithic VMEM budget — HBM pad scratch, band copies, windowed
    # compute grid — through real Mosaic, degenerate 1x1 exchange.
    from functools import partial

    from jax.sharding import PartitionSpec as P

    from parallel_convolution_tpu.ops import pallas_rdma
    from parallel_convolution_tpu.parallel.mesh import AXES
    from parallel_convolution_tpu.utils.jax_compat import shard_map

    # Two sizes: a small block (fits the monolithic budget, still forced
    # through the tiled code path) and a block beyond the monolithic VMEM
    # budget.  If only the big one fails, the failure is size/VMEM-scaling;
    # if both fail, it's a construct the helper rejects.
    for key, (th_, tw_) in (("tiled_small", (512, 640)),
                            ("tiled_variant", (2048, 2048))):
        timg = imageio.generate_test_image(th_, tw_, "grey", seed=14)
        xt = imageio.interleaved_to_planar(timg).astype(np.float32)
        body = shard_map(
            partial(pallas_rdma.fused_rdma_step, filt=filt, grid=(1, 1),
                    boundary="zero", quantize=True, tiled=True),
            mesh=mesh, in_specs=P(None, *AXES), out_specs=P(None, *AXES),
            check_vma=False,
        )
        try:
            t0 = time.perf_counter()
            out_t = jax.jit(body)(xt)
            bench.fence(out_t)
            t_tiled = time.perf_counter() - t0
            got_t = np.asarray(out_t)[0].astype(np.uint8)
            want_t = oracle.run_serial_u8(timg, filt, 1)
            row[key] = {
                "workload": f"blur3 {th_}x{tw_} grey 1 iter, forced tiled "
                            "(HBM pad + windowed-DMA grid), 1x1 mesh",
                "mosaic_compiled": True,
                "bitexact_vs_oracle": bool(np.array_equal(got_t, want_t)),
                "first_call_s": round(t_tiled, 3),
            }
        except Exception as e:
            # Full head + tail: remote-compile failures bury the Mosaic
            # reason after a long transport preamble (an earlier 300-char
            # cut lost it and made the recorded row undiagnosable).
            msg = repr(e)
            if len(msg) > 4000:
                msg = msg[:2000] + " ...[elided]... " + msg[-2000:]
            row[key] = {"mosaic_compiled": False, "error": msg}

    print(json.dumps(row))
    # Exit 0 whenever the probe RAN and the row was emitted — the row IS
    # the record, including failures (an earlier version exited 1 on a
    # tiled failure, which made the chip-session's temp-file+rename
    # wrapper discard exactly the diagnostic row it existed to capture).
    # Nonzero is reserved for "no record produced" (off-TPU skip).
    for k in ("tiled_small", "tiled_variant"):
        row.setdefault(k, {})
    all_ok = bitexact and all(row[k].get("bitexact_vs_oracle")
                              for k in ("tiled_small", "tiled_variant"))
    row_status = "all bit-exact" if all_ok else "FAILURES RECORDED IN ROW"
    print(f"# probe status: {row_status}", file=sys.stderr)
    return 0


if __name__ == "__main__":
    sys.exit(main())
