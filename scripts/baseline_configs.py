#!/usr/bin/env python
"""Run the five BASELINE.json configs end-to-end (SURVEY.md §7 stage 5).

Sizes adapt to the attached hardware: ``--scale 1`` is the literal config
(needs a pod + disk for config 4); the default ``--scale auto`` shrinks
spatial dims on small hosts while keeping every config's *shape* (filter,
mode, mesh aspect, convergence semantics) intact.  Emits one JSON row per
config (stdout) and a markdown table (stderr) for BASELINE.md.
"""

from __future__ import annotations

import argparse
import json
import sys
import time

import _path  # noqa: F401  (repo root onto sys.path)


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--scale", default="auto",
                    help="'auto', or a divisor (1 = literal BASELINE sizes)")
    ap.add_argument("--platform", default=None)
    args = ap.parse_args()

    import jax

    if args.platform:
        from parallel_convolution_tpu.utils.platform import force_platform

        force_platform(args.platform, warn=True)

    import numpy as np

    from parallel_convolution_tpu.utils.platform import enable_compile_cache

    enable_compile_cache()

    from parallel_convolution_tpu.ops.filters import get_filter
    from parallel_convolution_tpu.parallel import step
    from parallel_convolution_tpu.parallel.mesh import make_grid_mesh
    from parallel_convolution_tpu.utils import bench

    from parallel_convolution_tpu.ops.pallas_stencil import on_tpu

    n_dev = len(jax.devices())
    platform = "tpu" if on_tpu() else jax.default_backend()
    if args.scale == "auto":
        scale = 1 if platform == "tpu" and n_dev >= 16 else (
            4 if platform == "tpu" else 16)
    else:
        scale = int(args.scale)

    def mesh_for(shape):
        r, c = shape
        if r * c > n_dev:
            # keep the aspect, shrink to available devices
            from parallel_convolution_tpu.parallel.mesh import dims_create

            r, c = dims_create(n_dev)
        return make_grid_mesh(jax.devices()[: r * c], (r, c))

    rows = []

    # Provenance: which rint implementation the Pallas kernels resolve for
    # THIS config's own filter taps on THIS platform — stamped only on rows
    # a Pallas kernel actually produces (ADVICE low: the blur3-resolved
    # mode was previously stamped on every row, including the serial C++
    # and jacobi rows that run no Pallas kernel at all, so the field could
    # misstate which kernel variant made a row).
    from parallel_convolution_tpu.ops.pallas_stencil import _round_mode_for

    _PALLAS_BACKENDS = ("pallas", "pallas_sep", "pallas_rdma")

    def round_mode_for_cfg(filter_name: str, backend: str) -> str | None:
        if backend not in _PALLAS_BACKENDS:
            return None  # no Pallas kernel runs: no rint provenance to claim
        taps = tuple(float(t)
                     for t in get_filter(filter_name).taps.reshape(-1))
        return _round_mode_for(taps, interpret=not on_tpu())

    def emit(name, row, round_mode=None):
        row = {"config": name,
               **({"round_mode": round_mode} if round_mode else {}), **row}
        rows.append(row)
        print(json.dumps(row), flush=True)

    # 1. serial CPU reference, 1920x2520 grey (never scaled: host-sized).
    # No round_mode: the serial oracle/C++ path runs no Pallas kernel.
    emit("1: serial 3x3 blur 1920x2520 grey",
         bench.bench_oracle_proxy((1920, 2520), iters=2))

    # Best-known backends per filter class (BASELINE.md measured table):
    # separable dyadic filters ride the rank-1 Pallas kernel, 5x5 edge
    # (not rank-1) the 2D tap kernel; off-TPU the XLA shifted path.
    sep_backend = "pallas_sep" if platform == "tpu" else "shifted"
    two_d_backend = "pallas" if platform == "tpu" else "shifted"

    # 2. 3x3 blur, 1920x2520 RGB, 2x2 mesh — the canonical image is small,
    # so the full 100 iterations always run (shrinking them only starves
    # the wall measurement).
    emit("2: 3x3 blur 1920x2520 rgb 2x2 mesh", bench.bench_iterate(
        (1920 // max(1, scale // 4), 2520 // max(1, scale // 4)),
        get_filter("blur3"), 100,
        mesh=mesh_for((2, 2)), channels=3, backend=sep_backend,
        storage="bf16", fuse=16 if platform == "tpu" else 4, reps=2),
        round_mode=round_mode_for_cfg("blur3", sep_backend))

    # 3. 5x5 edge-detect, 8192^2 grey, 100 iters, 4x4 mesh
    emit("3: 5x5 edge 8192^2 grey 4x4 mesh", bench.bench_iterate(
        (8192 // scale, 8192 // scale), get_filter("edge5"),
        100 if scale == 1 else 10, mesh=mesh_for((4, 4)),
        backend=two_d_backend, storage="bf16",
        fuse=4 if platform == "tpu" else 2, reps=2),
        round_mode=round_mode_for_cfg("edge5", two_d_backend))

    # 4. 3x3 blur, 65536^2 RGB, v5e-16, pallas kernel (the north star)
    emit("4: 3x3 blur 65536^2 rgb pallas", bench.bench_iterate(
        (65536 // scale, 65536 // scale), get_filter("blur3"),
        100 if scale == 1 else 5, mesh=mesh_for((4, 4)), channels=3,
        backend=sep_backend, storage="bf16",
        fuse=16 if platform == "tpu" else 2, reps=1),
        round_mode=round_mode_for_cfg("blur3", sep_backend))

    # 5. iterated 3x3 jacobi to convergence (psum), 32768^2
    size5 = 32768 // scale
    x = np.random.default_rng(0).random((1, size5, size5)).astype(np.float32)
    m5 = mesh_for((8, 8))
    # warm run compiles outside the timed span; bench.fence (not
    # block_until_ready, which lies on tunnel platforms) closes the span.
    bench.fence(step.sharded_converge(
        x, get_filter("jacobi3"), tol=1e-3, max_iters=200,
        check_every=10, mesh=m5)[0])
    t0 = time.perf_counter()
    out, iters = step.sharded_converge(
        x, get_filter("jacobi3"), tol=1e-3, max_iters=200, check_every=10,
        mesh=m5)
    bench.fence(out)
    secs = time.perf_counter() - t0
    emit("5: jacobi convergence 32768^2", {
        "workload": f"jacobi3 {size5}x{size5} tol=1e-3",
        "iters_run": iters, "wall_s": round(secs, 3),
        "iters_per_s": round(iters / secs, 2) if secs else None,
    })

    print("\n| config | result |", file=sys.stderr)
    print("|---|---|", file=sys.stderr)
    for r in rows:
        body = {k: v for k, v in r.items() if k != "config"}
        print(f"| {r['config']} | `{json.dumps(body)}` |", file=sys.stderr)
    return 0


if __name__ == "__main__":
    sys.exit(main())
