#!/usr/bin/env python
"""Cross-validate the slope-timed walls (DESIGN.md roofline §).

The headline Gpx/s numbers flow through one clever trick: chained-span
slope timing that cancels the tunnel's ~140 ms fence constant
(utils/bench.py).  VERDICT round 1 (Weak #6) rightly demanded an
independent check.  Three legs, most- to least-direct:

1. **Workload differencing** — wall(3N iters) − wall(N iters) between two
   separately-compiled runners, each measured with ONE plain fence (no
   chaining, no slope): the fence constant cancels across workloads
   instead of across chain lengths.  Agreement within ~10% validates the
   slope machinery with none of its code in the loop.
2. **Fuse-invariance** — per-iteration time from fuse=16 vs fuse=32 at
   equal total iterations must track the slope-timed ratio.
3. **jax.profiler device time** — captured for one headline call when the
   plugin stack can serialize it; parsed best-effort from the xplane
   protobuf (``protoc --decode_raw``).  Reported when available, skipped
   loudly when the proxy platform can't produce a trace.

Also derives the roofline figures for DESIGN.md: HBM GB/s and VPU
Gflop/s implied by the measured per-iteration wall.  Prints one JSON
object.
"""

from __future__ import annotations

import json
import subprocess
import sys
import tempfile
import time

import _path  # noqa: F401


def main() -> int:
    from parallel_convolution_tpu.utils.platform import (
        apply_platform_env, enable_compile_cache, on_tpu,
    )

    apply_platform_env()
    enable_compile_cache()

    import jax
    import numpy as np

    from parallel_convolution_tpu.ops.filters import get_filter
    from parallel_convolution_tpu.parallel import step as step_lib
    from parallel_convolution_tpu.parallel.mesh import make_grid_mesh
    from parallel_convolution_tpu.utils import bench

    mesh = make_grid_mesh()
    filt = get_filter("blur3")
    if on_tpu():
        shape, iters, storage, fuse = (8192, 8192), 96, "bf16", 32
    else:
        shape, iters, storage, fuse = (1024, 1024), 16, "f32", 4
    H, W = shape
    result = {"workload": f"blur3 {H}x{W} {storage} fuse{fuse}"}

    # Slope-timed reference (the number under test).
    row = bench.bench_iterate(shape, filt, iters, mesh=mesh,
                              backend="pallas_sep", storage=storage,
                              fuse=fuse, reps=3)
    slope_per_iter = row["wall_s"] / iters
    result["slope_wall_s"] = row["wall_s"]
    result["slope_us_per_iter"] = round(1e6 * slope_per_iter, 2)

    # Leg 1: workload differencing with plain single fences.
    rng = np.random.default_rng(0)
    x = rng.integers(0, 256, size=(1, H, W)).astype(np.float32)

    def plain_wall(n_iters, reps=3):
        xs, valid_hw, block_hw = step_lib._prepare(x, mesh, filt.radius,
                                                   storage)
        fn = step_lib._build_iterate(mesh, filt, n_iters, True, valid_hw,
                                     block_hw, "pallas_sep", fuse)
        out = bench.fence(fn(xs))  # compile + warm
        walls = []
        for _ in range(reps):
            t0 = time.perf_counter()
            out = fn(out)
            bench.fence(out)
            walls.append(time.perf_counter() - t0)
        return min(walls)

    t_small = plain_wall(iters)
    t_big = plain_wall(3 * iters)
    diff_per_iter = (t_big - t_small) / (2 * iters)
    result["diff_us_per_iter"] = round(1e6 * diff_per_iter, 2)
    result["diff_vs_slope_pct"] = round(
        100.0 * (diff_per_iter - slope_per_iter) / slope_per_iter, 1)

    # Leg 2: fuse-invariance (16 vs 32) under the slope machinery itself.
    row16 = bench.bench_iterate(shape, filt, iters, mesh=mesh,
                                backend="pallas_sep", storage=storage,
                                fuse=fuse // 2, reps=3)
    result["slope_us_per_iter_fuse_half"] = round(
        1e6 * row16["wall_s"] / iters, 2)

    # Leg 3: profiler device time (best-effort on the proxy platform).
    result["profiler_us_per_iter"] = None
    try:
        xs, valid_hw, block_hw = step_lib._prepare(x, mesh, filt.radius,
                                                   storage)
        fn = step_lib._build_iterate(mesh, filt, iters, True, valid_hw,
                                     block_hw, "pallas_sep", fuse)
        out = bench.fence(fn(xs))
        with tempfile.TemporaryDirectory() as td:
            with jax.profiler.trace(td):
                out = bench.fence(fn(out))
            import glob
            import pathlib

            total_ps = 0
            for pb in glob.glob(f"{td}/**/*.xplane.pb", recursive=True):
                with open(pb, "rb") as fh:
                    raw = subprocess.run(
                        ["protoc", "--decode_raw"],
                        stdin=fh, capture_output=True, text=True,
                        timeout=120,
                    ).stdout
                # xplane: device planes hold lines of events whose field 4
                # is duration_ps; crude but serviceable aggregate of the
                # longest single event (the fused iteration program).
                durs = [int(tok.split(":")[1])
                        for tok in raw.replace(" ", "").splitlines()
                        if tok.startswith("4:") and tok[2:].isdigit()]
                if durs:
                    total_ps = max(total_ps, max(durs))
            if total_ps:
                prof_us = total_ps / 1e6 / iters
                # The field-4 heuristic also matches non-duration varints
                # (observed: a "duration" of 9.8e10 µs/iter — 27 hours).
                # Only a value commensurate with the slope wall can be a
                # device-time reading; anything else is a parse artifact
                # and is reported as such, not as a measurement.
                if 0.2 * slope_per_iter <= prof_us / 1e6 <= 5 * slope_per_iter:
                    result["profiler_us_per_iter"] = round(prof_us, 2)
                else:
                    result["profiler_note"] = (
                        f"decode_raw field-4 max {prof_us:.3g} us/iter is "
                        "implausible vs the slope wall; xplane schema "
                        "parse unavailable on this platform")
    except Exception as e:
        result["profiler_error"] = repr(e)[:160]

    # Roofline figures implied by the slope wall.
    bytes_px = {"f32": 4, "bf16": 2, "u8": 1}[storage]
    hbm_gb_s = (H * W * 2 * bytes_px / fuse) / slope_per_iter / 1e9
    vpu_gflop_s = 12 * H * W / slope_per_iter / 1e9
    result["hbm_gb_per_s"] = round(hbm_gb_s, 1)
    result["vpu_gflop_per_s"] = round(vpu_gflop_s, 1)

    print(json.dumps(result))
    return 0


if __name__ == "__main__":
    sys.exit(main())
