#!/usr/bin/env python
"""Unified storage-chaos matrix: the ``run_t1.sh --storage-smoke`` leg
(round 24).

Round 18 drilled the network (chaos transport), round 19 the control
plane's death (WAL takeover); this leg drills the DISK under the whole
serving surface at once.  It crosses every storage fault mode

    {ENOSPC, EIO, torn-write, slow-write, process kill}

with every workload shape the stack serves

    {batch JSON, batch frames, converge resume, rank-3 volume stream,
     cross-shard takeover, cache hit/spill}

— one small, seeded cell per pair — and gates the STANDING invariants
in every cell:

* **zero non-typed failures** — every request either completed or shed
  with a typed retryable rejection; nothing raised into the client;
* **byte-identical or typed-retryable** — every completion matches the
  uninterrupted oracle bit-for-bit;
* **exactly-once finals** — one final row per request_id, across router
  lives where the cell kills one;
* **no stale-byte serves** — a torn spill / healed WAL tail / recovered
  cache never surfaces garbage as a completion;
* **the fault actually fired** — ``diskio.injected_counts()`` must grow
  for the cell's site x mode (a dead drill proves nothing).

Two site drills cover the telemetry/evidence ladders the matrix's
workloads don't route through: ``events_emit`` under ENOSPC counts
dropped lines instead of raising, and ``evidence_write`` under ENOSPC
fails typed BEFORE any byte of the shared curve moves.

The dedicated **ENOSPC degrade drill** (the acceptance drill) proves
the durability ladder end-to-end: sustained ``wal_write`` ENOSPC flips
the router into ``durability: degraded`` (stamped on every response)
while it KEEPS SERVING; the first healthy write re-arms durability with
a fresh compaction snapshot of the live state; and a takeover replay
after the healed window resumes from that snapshot — the job finalized
during the window is still finalized, nothing stale resurrects.

The summary row lands in ``--out`` (``evidence/storage_smoke.json``)
with ``"failures": 0`` iff every gate held, then feeds
``perf_gate.py --storage-smoke`` (report in
``evidence/storage_gate.json``).
"""

from __future__ import annotations

import argparse
import base64
import json
import subprocess
import sys
import tempfile
import time
from pathlib import Path

import _path  # noqa: F401  (repo root + JAX_PLATFORMS re-apply)

SCRIPTS = Path(__file__).resolve().parent

MODES = ("enospc", "eio", "torn_write", "slow_write", "kill")
WORKLOADS = ("batch_json", "batch_frames", "converge", "volume",
             "shard", "cache")


def run_matrix(seed: int = 0, mesh: str = "1x2", rows: int = 40,
               cols: int = 56, modes=MODES, workloads=WORKLOADS,
               log=print) -> dict:
    """Run the full matrix + site drills + the ENOSPC degrade drill;
    returns the summary row (``soak.py --chaos-matrix`` reuses this)."""
    import numpy as np

    from _chaos_common import (
        converge_body as _cbody, oracle_converge_final,
        request_with_backoff,
    )
    from parallel_convolution_tpu.obs import events as obs_events
    from parallel_convolution_tpu.ops import filters, oracle
    from parallel_convolution_tpu.parallel.mesh import mesh_from_spec
    from parallel_convolution_tpu.resilience import diskio, faults
    from parallel_convolution_tpu.serving import frames
    from parallel_convolution_tpu.serving.cache import ResultCache
    from parallel_convolution_tpu.serving.chaos import router_kill_due
    from parallel_convolution_tpu.serving.pricing import WorkPricer
    from parallel_convolution_tpu.serving.router import (
        InProcessReplica, ReplicaRouter, TenantQuotas, route_key,
    )
    from parallel_convolution_tpu.serving.service import ConvolutionService
    from parallel_convolution_tpu.utils import evidence_io, imageio
    from parallel_convolution_tpu.volumes import oracle3

    failures: list[str] = []
    t0 = time.time()
    tmp = Path(tempfile.mkdtemp(prefix="pctpu-storage-"))

    img = imageio.generate_test_image(rows, cols, "grey", seed=7)
    b64 = base64.b64encode(np.ascontiguousarray(img).tobytes()).decode()
    batch_iters = 2
    batch_oracle = oracle.run_serial_u8(
        img, filters.get_filter("blur3"), batch_iters)
    vol = np.random.default_rng(11).random((2, 4, 16, 16),
                                           dtype=np.float32)
    vol_b64 = base64.b64encode(vol.tobytes()).decode()

    def factory():
        return ConvolutionService(mesh_from_spec(mesh),
                                  max_delay_s=0.002, max_queue=256)

    def batch_body(rid: str) -> dict:
        return {"image_b64": b64, "rows": rows, "cols": cols,
                "mode": "grey", "filter": "blur3", "iters": batch_iters,
                "request_id": rid, "tenant": "drill"}

    def cbody(rid: str) -> dict:
        return _cbody(b64, rows, cols, rid, tenant="drill")

    def vbody(rid: str) -> dict:
        return {"rows": 16, "cols": 16, "depth": 4, "mode": "volume",
                "volume_b64": vol_b64, "filter": "wave",
                "boundary": "periodic", "tol": 0.0, "max_iters": 12,
                "check_every": 4, "request_id": rid, "tenant": "drill"}

    # Uninterrupted oracles, once (clean router, no faults).
    try:
        cv_oracle = oracle_converge_final(factory, cbody("oracle"))
        vol_oracle = oracle_converge_final(factory, vbody("oracle-v"))
    except RuntimeError as e:
        failures.append(f"oracle run failed: {e}")
        cv_oracle = vol_oracle = {}

    # The shared replica pool (plain services); cache cells build their
    # own cache-armed replica per cell.
    reps = [InProcessReplica(factory, name=f"s{i}") for i in range(2)]
    clock = [0.0]

    def mk_router(wal_path):
        return ReplicaRouter(
            reps, wal=str(wal_path),
            quotas=TenantQuotas(rate=1.0, burst=1e6,
                                clock=lambda: clock[0]),
            pricer=WorkPricer(min_units=1e-9),
            breaker_threshold=3, breaker_cooldown_s=0.2,
            start_health=False)

    def drain(rows_iter, finals: dict):
        out = []
        for r in rows_iter:
            out.append(r)
            if r.get("kind") == "final":
                rid = r.get("request_id", "")
                finals[rid] = finals.get(rid, 0) + 1
        return out

    def check_batch(wire, cell: str, errs: list[str]):
        if wire.get("ok"):
            if (base64.b64decode(wire["image_b64"])
                    != batch_oracle.tobytes()):
                errs.append(f"{cell}: batch bytes differ from oracle")
        elif not wire.get("retryable"):
            errs.append(f"{cell}: non-typed failure "
                        f"{wire.get('rejected')!r}")

    def frames_request(router, rid: str):
        """One batch request on the binary wire; returns (wire, bytes)."""
        header = {k: v for k, v in batch_body(rid).items()
                  if k != "image_b64"}
        env = frames.encode_envelope(header, {"image": img})
        hdr, raw = frames.split_envelope(env)
        body = dict(hdr)
        body["_frames_raw"] = bytes(raw)
        wire = request_with_backoff(router, body)
        out_raw = wire.pop("_frames_raw", b"")
        if not wire.get("ok"):
            return wire, b""
        _, arrays = frames.decode_envelope(
            frames.join_envelope(wire, out_raw))
        return wire, arrays["image"].tobytes()

    def check_frames(wire, got: bytes, cell: str, errs: list[str]):
        if wire.get("ok"):
            if got != batch_oracle.tobytes():
                errs.append(f"{cell}: framed bytes differ from oracle")
        elif not wire.get("retryable"):
            errs.append(f"{cell}: non-typed failure "
                        f"{wire.get('rejected')!r}")

    def check_stream(got: list, oracle_final: dict, cell: str,
                     errs: list[str], finals: dict):
        final = got[-1] if got else {}
        if final.get("kind") != "final":
            if not final.get("retryable"):
                errs.append(f"{cell}: stream ended non-typed: "
                            f"{final.get('rejected')!r}")
            return
        if final.get("image_b64") != oracle_final.get("image_b64"):
            errs.append(f"{cell}: final not byte-identical to oracle")
        dup = {r: n for r, n in finals.items() if n != 1}
        if dup:
            errs.append(f"{cell}: exactly-once finals violated: {dup}")

    # ------------------------------------------------------------ cells
    def cell_batch(kind: str, mode: str, cell: str,
                   errs: list[str]) -> None:
        """batch_json / batch_frames x one disk mode or kill."""
        wal = tmp / f"{cell}.wal"
        r1 = mk_router(wal)
        send = ((lambda rt, rid: check_frames(
                    *frames_request(rt, rid), cell, errs))
                if kind == "batch_frames"
                else (lambda rt, rid: check_batch(
                    request_with_backoff(rt, batch_body(rid)),
                    cell, errs)))
        if mode == "kill":
            for i in range(2):
                send(r1, f"{cell}-a{i}")
            r2 = mk_router(wal)   # fenced takeover of the same lineage
            if r2.epoch <= r1.epoch:
                errs.append(f"{cell}: takeover epoch did not bump")
            _, wz = r1.request(batch_body(f"{cell}-zombie"))
            if wz.get("rejected") != "stale_epoch" or wz.get("retryable"):
                errs.append(f"{cell}: zombie not fenced typed "
                            f"({wz.get('rejected')!r})")
            for i in range(2):
                send(r2, f"{cell}-b{i}")
            r1.close(close_replicas=False)
            r2.close(close_replicas=False)
            return
        diskio.install_modes({"wal_write": mode})
        try:
            with faults.injected("wal_write:1+", seed=seed):
                for i in range(3):
                    send(r1, f"{cell}-{i}")
        finally:
            diskio.uninstall_modes()
            r1.close(close_replicas=False)

    def cell_stream(body_fn, oracle_final: dict, mode: str, cell: str,
                    errs: list[str]) -> None:
        """converge / volume stream x one disk mode or kill."""
        wal = tmp / f"{cell}.wal"
        finals: dict[str, int] = {}
        r1 = mk_router(wal)
        rid = f"{cell}-cv"
        if mode == "kill":
            killed = False
            with faults.injected("router_kill:2", seed=seed):
                st, rows_it = r1.converge(body_fn(rid))
                if st != 200:
                    errs.append(f"{cell}: admission failed: {st}")
                else:
                    n_rows = 0
                    for row in rows_it:
                        drain([row], finals)
                        n_rows += 1
                        if router_kill_due():
                            killed = True
                            break   # abandoned un-closed: the crash
            if not killed:
                errs.append(f"{cell}: router_kill never fired")
            r2 = mk_router(wal)
            if r2.epoch <= r1.epoch:
                errs.append(f"{cell}: takeover epoch did not bump")
            r1.close(close_replicas=False)
            st, rows_it = r2.converge(body_fn(rid))
            got = drain(rows_it, finals) if st == 200 else []
            check_stream(got, oracle_final, cell, errs, finals)
            final = got[-1] if got else {}
            if (final.get("kind") == "final"
                    and final.get("router", {}).get("resume_count", 0)
                    < 1):
                errs.append(f"{cell}: takeover retry did not resume "
                            "from the ledger token")
            r2.close(close_replicas=False)
            return
        diskio.install_modes({"wal_write": mode})
        try:
            with faults.injected("wal_write:1+", seed=seed):
                st, rows_it = r1.converge(body_fn(rid))
                got = drain(rows_it, finals) if st == 200 else []
            check_stream(got, oracle_final, cell, errs, finals)
        finally:
            diskio.uninstall_modes()
            r1.close(close_replicas=False)

    def cell_shard(mode: str, cell: str, errs: list[str]) -> None:
        """Cross-shard control plane x one disk mode or kill."""
        from parallel_convolution_tpu.serving.peers import (
            InProcessPeer, ShardClient, ShardRouter, shard_of,
        )

        state_dir = tmp / cell
        state_dir.mkdir()
        names = ["rA", "rB"]
        assign = {"0": "rA", "1": "rB"}
        routers = {}
        for nm in names:
            routers[nm] = ShardRouter(
                nm, reps, n_shards=2,
                owned=[s for s, o in assign.items() if o == nm],
                state_dir=state_dir, assignments=assign,
                quotas=TenantQuotas(rate=1.0, burst=1e6,
                                    clock=lambda: clock[0]),
                pricer=WorkPricer(min_units=1e-9),
                start_sync=False, start_health=False,
                breaker_cooldown_s=0.2, clock=lambda: clock[0])
        for nm in names:
            routers[nm].peers = [InProcessPeer(routers[o])
                                 for o in names if o != nm]
        client = ShardClient(list(routers.values()))
        finals: dict[str, int] = {}
        body = cbody(f"{cell}-cv")
        try:
            if mode == "kill":
                shard = shard_of(route_key(dict(body)), 2)
                victim = routers[assign[shard]]
                survivor = [routers[n] for n in names
                            if n != assign[shard]][0]
                st, rows_it = client.converge(dict(body))
                if st != 200:
                    errs.append(f"{cell}: admission failed: {st}")
                    return
                drain([next(rows_it), next(rows_it)], finals)
                victim.hard_stop()
                for _ in range(survivor.suspect_after + 1):
                    survivor.sync_now()
                if survivor.stats.get("takeovers", 0) < 1:
                    errs.append(f"{cell}: no fenced takeover observed")
                client.refresh()
                st, rows_it = client.converge(dict(body))
                got = drain(rows_it, finals) if st == 200 else []
                check_stream(got, cv_oracle, cell, errs, finals)
                final = got[-1] if got else {}
                if (final.get("kind") == "final"
                        and final.get("router", {}).get(
                            "resume_count", 0) < 1):
                    errs.append(f"{cell}: cross-shard retry did not "
                                "resume from the ledger token")
                return
            diskio.install_modes({"wal_write": mode})
            try:
                with faults.injected("wal_write:1+", seed=seed):
                    st, rows_it = client.converge(dict(body))
                    got = drain(rows_it, finals) if st == 200 else []
                check_stream(got, cv_oracle, cell, errs, finals)
            finally:
                diskio.uninstall_modes()
        finally:
            for r in routers.values():
                try:
                    r.close(close_replicas=False)
                except (OSError, RuntimeError):
                    pass

    def cell_cache(mode: str, cell: str, errs: list[str]) -> None:
        """Cache hit/spill/promote x one disk mode or kill."""
        disk = tmp / f"{cell}-rc"

        def cache_factory():
            return ConvolutionService(
                mesh_from_spec(mesh), max_delay_s=0.002, max_queue=256,
                cache=ResultCache(capacity_entries=1, disk_dir=disk))

        rep = InProcessReplica(cache_factory, name="rc0")
        wal = tmp / f"{cell}.wal"

        def mk(wal_path):
            return ReplicaRouter(
                [rep], wal=str(wal_path),
                quotas=TenantQuotas(rate=1.0, burst=1e6,
                                    clock=lambda: clock[0]),
                pricer=WorkPricer(min_units=1e-9),
                breaker_threshold=3, breaker_cooldown_s=0.2,
                start_health=False)

        r1 = mk(wal)
        a = dict(batch_body(f"{cell}-a"))
        b = dict(batch_body(f"{cell}-b"), iters=1)
        b_oracle = oracle.run_serial_u8(img, filters.get_filter("blur3"),
                                        1)

        def send(rt, body, want):
            wire = request_with_backoff(rt, dict(body))
            if wire.get("ok"):
                if base64.b64decode(wire["image_b64"]) != want.tobytes():
                    errs.append(f"{cell}: served bytes differ from "
                                "oracle (stale/torn serve)")
            elif not wire.get("retryable"):
                errs.append(f"{cell}: non-typed failure "
                            f"{wire.get('rejected')!r}")
            return wire

        try:
            if mode == "kill":
                send(r1, a, batch_oracle)   # populate A
                send(r1, b, b_oracle)       # evict A -> disk spill
                r2 = mk(wal)                # takeover, same WAL + disk
                r1.close(close_replicas=False)
                # Post-takeover, A must come back CORRECT — from the
                # disk tier (CRC-verified) or recomputed; never stale.
                wire = send(r2, dict(a, request_id=f"{cell}-a2"),
                            batch_oracle)
                if not wire.get("ok"):
                    errs.append(f"{cell}: post-takeover request failed")
                r2.close(close_replicas=False)
                return
            dmodes = {"cache_spill": mode}
            spec = "cache_spill:1+"
            if mode in ("eio", "slow_write"):
                dmodes["cache_promote"] = mode
                spec += ",cache_promote:1"
            diskio.install_modes(dmodes)
            try:
                with faults.injected(spec, seed=seed):
                    send(r1, a, batch_oracle)               # miss
                    send(r1, dict(a, request_id=f"{cell}-a2"),
                         batch_oracle)                      # memory hit
                    send(r1, b, b_oracle)                   # spill fault
                    send(r1, dict(a, request_id=f"{cell}-a3"),
                         batch_oracle)   # promote path or clean recompute
            finally:
                diskio.uninstall_modes()
            r1.close(close_replicas=False)
        finally:
            rep.close()

    RUNNERS = {
        "batch_json": lambda m, c, e: cell_batch("batch_json", m, c, e),
        "batch_frames": lambda m, c, e: cell_batch("batch_frames",
                                                   m, c, e),
        "converge": lambda m, c, e: cell_stream(cbody, cv_oracle,
                                                m, c, e),
        "volume": lambda m, c, e: cell_stream(vbody, vol_oracle,
                                              m, c, e),
        "shard": cell_shard,
        "cache": cell_cache,
    }
    PRIMARY_SITE = {"cache": "cache_spill"}   # default: wal_write

    cells = []
    for wl in workloads:
        for mode in modes:
            cell = f"{wl}x{mode}"
            errs: list[str] = []
            before = diskio.injected_counts()
            try:
                RUNNERS[wl](mode, cell, errs)
            except Exception as e:  # noqa: BLE001 — the standing
                # zero-non-typed gate: ANY exception out of a cell is a
                # finding, recorded typed in the row, never a crash of
                # the whole matrix.
                errs.append(f"{cell}: raised {type(e).__name__}: "
                            f"{str(e)[:160]}")
            after = diskio.injected_counts()
            delta = {k: after.get(k, 0) - before.get(k, 0)
                     for k in after
                     if after.get(k, 0) > before.get(k, 0)}
            if mode != "kill":
                key = f"{PRIMARY_SITE.get(wl, 'wal_write')}={mode}"
                if delta.get(key, 0) < 1:
                    errs.append(
                        f"{cell}: fault never fired ({key} flat — a "
                        "dead drill proves nothing)")
            cells.append({"cell": cell, "workload": wl, "mode": mode,
                          "ok": not errs, "injected": delta,
                          **({"errors": errs[:3]} if errs else {})})
            failures.extend(errs)
            log(f"  cell {cell}: {'ok' if not errs else errs[0]}")

    # -------------------------------------------------- site drills
    site_drills = {}
    # events_emit under ENOSPC: dropped lines counted, never a raise.
    elog = obs_events.EventLog(tmp / "drill-events.ndjson")
    diskio.install_modes({"events_emit": "enospc"})
    try:
        with faults.injected("events_emit:2+", seed=seed):
            for i in range(4):
                elog.emit("heartbeat", i=i)
    except (OSError, Exception) as e:  # noqa: BLE001 — the contract
        # under test IS "never raises"; anything escaping is the finding.
        failures.append(f"events_emit drill raised {e!r}")
    finally:
        diskio.uninstall_modes()
        elog.close()
    written = len([ln for ln in (tmp / "drill-events.ndjson")
                   .read_text().splitlines() if ln.strip()])
    if elog.dropped < 1:
        failures.append("events_emit drill dropped nothing")
    if written + elog.dropped != 4:
        failures.append(f"events ledger drift: {written} written + "
                        f"{elog.dropped} dropped != 4 emitted")
    site_drills["events_emit"] = {"written": written,
                                  "dropped": elog.dropped}

    # evidence_write under ENOSPC: typed failure BEFORE any byte moves.
    curve = tmp / "drill-curve.jsonl"
    evidence_io.rewrite_shared_jsonl(curve, [{"a": 1}], lane="keep")
    before_bytes = curve.read_bytes()
    diskio.install_modes({"evidence_write": "enospc"})
    try:
        with faults.injected("evidence_write:1", seed=seed):
            try:
                evidence_io.rewrite_shared_jsonl(
                    curve, [{"b": 2}], lane="other")
                failures.append("evidence_write ENOSPC not surfaced")
                typed = False
            except OSError:
                typed = True
    finally:
        diskio.uninstall_modes()
    if curve.read_bytes() != before_bytes:
        failures.append("evidence_write fault tore the shared curve")
    site_drills["evidence_write"] = {
        "typed": typed, "curve_intact": curve.read_bytes() == before_bytes}

    # -------------------------------- the ENOSPC degrade ladder drill
    log("  enospc degrade drill: degrade -> serve -> re-arm -> replay")
    wal = tmp / "degrade.wal"
    r1 = mk_router(wal)
    finals: dict[str, int] = {}
    stamps = []
    diskio.install_modes({"wal_write": "enospc"})
    try:
        with faults.injected("wal_write:1+", seed=seed):
            for i in range(4):
                wire = request_with_backoff(r1, batch_body(f"deg-b{i}"))
                check_batch(wire, "degrade-drill", failures)
                stamps.append(wire.get("router", {}).get("durability"))
            # A whole converge job lives inside the degraded window:
            # served correctly, finalized in MEMORY only (every WAL
            # append fails) — the re-arm snapshot must carry it.
            st, rows_it = r1.converge(cbody("deg-cv"))
            got = drain(rows_it, finals) if st == 200 else []
            check_stream(got, cv_oracle, "degrade-drill", failures,
                         finals)
    finally:
        diskio.uninstall_modes()
    degraded_window = (r1.stats.get("wal_degraded_windows", 0) >= 1
                       and "degraded" in stamps)
    if not degraded_window:
        failures.append(
            f"no degraded window observed (stamps {stamps}, windows "
            f"{r1.stats.get('wal_degraded_windows')})")
    # Heal: the next successful append must re-arm with a fresh
    # compaction snapshot of the LIVE state.
    wire = request_with_backoff(r1, batch_body("heal-b0"))
    check_batch(wire, "degrade-drill-heal", failures)
    rearmed = (r1.stats.get("wal_rearms", 0) >= 1
               and wire.get("router", {}).get("durability") == "ok")
    if not rearmed:
        failures.append(
            f"durability did not re-arm on heal (rearms "
            f"{r1.stats.get('wal_rearms')}, stamp "
            f"{wire.get('router', {}).get('durability')!r})")
    snap1 = r1.snapshot()
    # Replay after the healed window: the takeover reads the re-arm
    # snapshot — the degraded-window job is STILL finalized (exactly
    # once), and no stale pre-degrade state resurrects as live.
    r2 = mk_router(wal)
    r1.close(close_replicas=False)
    jobs2, finalized2 = r2.jobs.export()
    finalized_carried = "drill\x1fdeg-cv" in finalized2
    if not finalized_carried:
        failures.append(
            "re-arm snapshot lost the degraded-window finalization — "
            "replay would re-run a finished job")
    stale_live = [lid for lid in jobs2 if lid.startswith("drill\x1f")]
    if stale_live:
        failures.append(
            f"replay resurrected stale live jobs: {stale_live}")
    st, rows_it = r2.converge(cbody("post-heal-cv"))
    got = drain(rows_it, finals) if st == 200 else []
    check_stream(got, cv_oracle, "degrade-drill-replay", failures,
                 finals)
    enospc_drill = {
        "degraded_window": degraded_window,
        "stamps": stamps,
        "degraded_windows": snap1["router"].get("wal_degraded_windows"),
        "rearmed": rearmed,
        "wal_rearms": snap1["router"].get("wal_rearms"),
        "finalized_carried": finalized_carried,
        "stale_live_jobs": len(stale_live),
        "replay": r2.recovery,
    }
    r2.close(close_replicas=False)

    for rep in reps:
        rep.close()
    wall = time.time() - t0
    bad_cells = [c["cell"] for c in cells if not c["ok"]]
    return {
        "workload": f"storage-chaos-matrix {len(modes)}x"
                    f"{len(workloads)} blur3+jacobi3+wave "
                    f"{rows}x{cols} mesh {mesh}",
        "seed": seed,
        "cells_total": len(cells),
        "cells_failed": len(bad_cells),
        "cells": cells,
        "site_drills": site_drills,
        "enospc_drill": enospc_drill,
        "injected_counts": diskio.injected_counts(),
        "wall_s": round(wall, 3),
        "failures": len(failures),
        "failure_detail": failures[:12],
    }


def main() -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--rows", type=int, default=40)
    ap.add_argument("--cols", type=int, default=56)
    ap.add_argument("--mesh", default="1x2", help="grid per replica")
    ap.add_argument("--seed", type=int, default=0)
    ap.add_argument("--out", default="evidence/storage_smoke.json")
    ap.add_argument("--gate-out", default="evidence/storage_gate.json")
    args = ap.parse_args()

    from parallel_convolution_tpu.obs import events as obs_events

    obs_events.install_from_env()
    row = run_matrix(seed=args.seed, mesh=args.mesh, rows=args.rows,
                     cols=args.cols)

    out = Path(args.out)
    out.parent.mkdir(parents=True, exist_ok=True)
    out.write_text(json.dumps(row, indent=2))

    # The storage lane gate re-reads the row it just wrote — missing or
    # failing evidence is a flag there too, so the leg can't silently
    # pass on a row that never landed.
    rc_gate = subprocess.run(
        [sys.executable, str(SCRIPTS / "perf_gate.py"),
         "--storage-smoke", str(out), "--out", args.gate_out,
         "--quiet"], check=False).returncode
    failures = row["failures"]
    if rc_gate != 0:
        row["failure_detail"] = (row["failure_detail"]
                                 + [f"perf_gate --storage-smoke exited "
                                    f"{rc_gate}"])[:12]
        failures += 1
    row["failures"] = failures
    out.write_text(json.dumps(row, indent=2))
    print(json.dumps({k: v for k, v in row.items() if k != "cells"}),
          flush=True)
    return 1 if failures else 0


if __name__ == "__main__":
    sys.exit(main())
