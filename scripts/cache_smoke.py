#!/usr/bin/env python
"""Result-cache smoke: the ``run_t1.sh --cache-smoke`` leg (round 22).

Prove the content-addressed result cache (serving/cache.py) end to end
on the CPU mesh, in five phases:

1. **Byte-identity + flat device counters** — one miss executes a
   request on device; a 100%-duplicate tail of the SAME request must
   then be served entirely from the cache: every response stamped
   ``cache: "hit"`` with the miss's digest, byte-identical to the
   NumPy oracle, while the engine's ``compiles`` / ``batches`` /
   ``images`` counters stay EXACTLY flat (a hit that touches a lane or
   a chip is a miss with extra steps).
2. **Convergence finals** — a converge job's final row is cached keyed
   on the fixed point's identity (rhs digest, tol, solver, mg_levels —
   NOT max_iters/check_every); a re-submitted job must stream exactly
   ONE final row, stamped hit, byte-identical to the first run's.
3. **WAL-recovery drill** — an entry's death is journaled (the new
   ``cache`` WAL record kind) and the process "crashes" BEFORE the
   disk bytes are unlinked — the worst crash point.  A fresh WAL
   replay + cache rebuild over the recovered ``cache_dead`` set must
   REFUSE the surviving bytes (re-executes, then re-caches live),
   while a never-invalidated neighbor entry IS adopted from disk and
   served as a hit — proving the refusal is the tombstone, not a
   broken disk tier.
4. **Hit-rate-vs-skew curve** — zipf(S) traffic over a pool of
   distinct same-config images at several skews, every response
   byte-checked against its pool member's oracle; one
   ``lane: "cache_skew"`` row per skew plus an all-unique cache
   on/off A/B pair land in the SHARED curve file
   (``evidence/scale_curve.jsonl``) via the evidence_io helper.
5. **Perf gate** — ``perf_gate.py --cache-lane`` holds: hit rate
   rising with skew and clearing the bar at the top, hit p99
   decisively under miss p99, the all-unique arm untaxed; and a
   synthetic flat-hit-rate lane must DEMONSTRABLY fail the gate.

The summary row lands in ``--out`` (``evidence/cache_smoke.json``,
the supervisor leg's done_file) with ``"failures": 0`` iff every gate
held; the lane gate report in ``evidence/cache_gate.json``.
"""

from __future__ import annotations

import argparse
import base64
import json
import random
import subprocess
import sys
import tempfile
import time
from pathlib import Path

import _path  # noqa: F401  (repo root + JAX_PLATFORMS re-apply)

from parallel_convolution_tpu.utils.evidence_io import rewrite_shared_jsonl

SCRIPTS = Path(__file__).resolve().parent


def _pct(vals, q):
    if not vals:
        return None
    vs = sorted(vals)
    return vs[min(len(vs) - 1, int(round(q * (len(vs) - 1))))]


def main() -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--rows", type=int, default=40)
    ap.add_argument("--cols", type=int, default=56)
    ap.add_argument("--iters", type=int, default=2)
    ap.add_argument("--filter", dest="filter_name", default="blur3")
    ap.add_argument("--mesh", default=None)
    ap.add_argument("--seed", type=int, default=0)
    ap.add_argument("--dup-n", type=int, default=16,
                    help="length of the 100%%-duplicate tail")
    ap.add_argument("--pool", type=int, default=48,
                    help="zipf pool size (distinct same-config images)")
    ap.add_argument("--zipf-n", type=int, default=90,
                    help="requests per zipf skew step")
    ap.add_argument("--skews", default="0.3,1.1,2.0",
                    help="comma-separated zipf S values (rising)")
    ap.add_argument("--unique-n", type=int, default=24,
                    help="requests per all-unique A/B arm")
    ap.add_argument("--out", default="evidence/cache_smoke.json")
    ap.add_argument("--curve-out", default="evidence/scale_curve.jsonl")
    ap.add_argument("--gate-out", default="evidence/cache_gate.json")
    args = ap.parse_args()

    import numpy as np

    from parallel_convolution_tpu.ops import oracle
    from parallel_convolution_tpu.ops.filters import get_filter
    from parallel_convolution_tpu.serving.cache import ResultCache
    from parallel_convolution_tpu.serving.frontend import InProcessClient
    from parallel_convolution_tpu.serving.service import ConvolutionService
    from parallel_convolution_tpu.serving.wal import RouterWAL
    from parallel_convolution_tpu.utils import imageio

    mesh = None
    if args.mesh:
        from parallel_convolution_tpu.parallel.mesh import mesh_from_spec

        mesh = mesh_from_spec(args.mesh)

    t0 = time.time()
    failures: list[str] = []
    filt = get_filter(args.filter_name)

    def mkimg(seed: int):
        return imageio.generate_test_image(args.rows, args.cols, "grey",
                                           seed=seed)

    def mkbody(img, rid: str) -> dict:
        return {
            "image_b64": base64.b64encode(
                np.ascontiguousarray(img).tobytes()).decode("ascii"),
            "rows": args.rows, "cols": args.cols, "mode": "grey",
            "filter": args.filter_name, "iters": args.iters,
            "backend": "shifted", "storage": "f32", "fuse": 1,
            "boundary": "zero", "request_id": rid,
        }

    def want(img) -> bytes:
        return oracle.run_serial_u8(img, filt, args.iters,
                                    boundary="zero").tobytes()

    def mkservice(cache):
        return ConvolutionService(mesh, max_batch=4, max_delay_s=0.002,
                                  max_queue=64, cache=cache)

    # ---- phase 1+2+3 share one WAL lineage + disk tier ---------------------
    tmp = tempfile.TemporaryDirectory(prefix="cache_smoke_")
    wal_path = Path(tmp.name) / "cache-shard.wal"
    disk_dir = Path(tmp.name) / "rc"
    wal1 = RouterWAL(wal_path, fsync=False, shard="s0")
    cache1 = ResultCache(
        capacity_entries=1,   # second store spills the first to disk
        disk_dir=disk_dir, shard="s0",
        journal=lambda op, ckey: wal1.append("cache", op=op, ckey=ckey),
        dead=wal1.state.cache_dead)
    svc1 = mkservice(cache1)
    client1 = InProcessClient(svc1)

    # ---- phase 1: duplicate tail -------------------------------------------
    dup_img = mkimg(args.seed)
    dup_want = want(dup_img)
    status, r0 = client1.request(mkbody(dup_img, "dup0"), timeout=60)
    digest = r0.get("digest", "")
    if status != 200 or not r0.get("ok"):
        failures.append(f"seed miss failed: {status} {r0.get('detail')}")
    else:
        if r0.get("cache") != "miss":
            failures.append(f"seed request stamped {r0.get('cache')!r}, "
                            "want 'miss'")
        if len(digest) != 64:
            failures.append(f"seed digest malformed: {digest!r}")
        if base64.b64decode(r0.get("image_b64", "")) != dup_want:
            failures.append("seed miss not byte-identical to oracle")
    eng_before = dict(svc1.snapshot().get("engine") or {})
    hit_stamps = 0
    for i in range(args.dup_n):
        status, r = client1.request(mkbody(dup_img, f"dup{i + 1}"),
                                    timeout=60)
        if status != 200 or not r.get("ok"):
            failures.append(f"dup {i}: {status} {r.get('detail')}")
            continue
        if r.get("cache") == "hit":
            hit_stamps += 1
        if r.get("digest") != digest:
            failures.append(f"dup {i}: digest drifted")
        if base64.b64decode(r.get("image_b64", "")) != dup_want:
            failures.append(f"dup {i}: hit bytes != oracle")
    if hit_stamps != args.dup_n:
        failures.append(f"duplicate tail: {hit_stamps}/{args.dup_n} "
                        "hits (want all)")
    eng_after = dict(svc1.snapshot().get("engine") or {})
    flat = {k: (eng_before.get(k), eng_after.get(k))
            for k in ("compiles", "batches", "images")}
    for k, (b, a) in flat.items():
        if b != a:
            failures.append(f"100% duplicate tail moved engine {k}: "
                            f"{b} -> {a} (hits touched the device)")

    # Two more distinct entries: with capacity_entries=1, storing the
    # neighbor spills the dup entry to disk, and storing the filler
    # spills the neighbor — so BOTH drill subjects have disk-tier bytes
    # at "crash" time.  The neighbor is the drill's post-restart
    # positive control.
    nb_img = mkimg(args.seed + 7001)
    nb_want = want(nb_img)
    status, rn = client1.request(mkbody(nb_img, "nb0"), timeout=60)
    if status != 200 or not rn.get("ok"):
        failures.append(f"neighbor miss failed: {status}")
    nb_digest = rn.get("digest", "")
    status, _rf = client1.request(mkbody(mkimg(args.seed + 7002), "fill0"),
                                  timeout=60)
    if status != 200:
        failures.append(f"filler miss failed: {status}")

    # ---- phase 2: convergence finals ---------------------------------------
    def cvbody(rid: str) -> dict:
        b = mkbody(dup_img, rid)
        b.pop("iters")
        b.update(tol=1.0, max_iters=400, check_every=10,
                 quantize=False, solver="jacobi")
        return b

    status, rows = client1.converge(cvbody("cv0"), timeout=120)
    rows = list(rows)
    finals = [r for r in rows if r.get("kind") == "final"]
    cv_b64 = ""
    if status != 200 or not finals or not finals[-1].get("converged"):
        failures.append(f"converge seed run: status {status}, "
                        f"finals {len(finals)}")
    else:
        cv_b64 = finals[-1].get("image_b64", "")
        if finals[-1].get("cache") != "miss":
            failures.append("converge seed final stamped "
                            f"{finals[-1].get('cache')!r}, want 'miss'")
    status, rows2 = client1.converge(cvbody("cv1"), timeout=120)
    rows2 = list(rows2)
    if status != 200 or len(rows2) != 1:
        failures.append(f"cached converge: status {status}, "
                        f"{len(rows2)} rows (want exactly 1 final)")
    else:
        f2 = rows2[0]
        if f2.get("cache") != "hit" or not f2.get("converged"):
            failures.append(f"cached converge final: cache="
                            f"{f2.get('cache')!r} converged="
                            f"{f2.get('converged')!r}")
        if f2.get("image_b64") != cv_b64:
            failures.append("cached converge final not byte-identical "
                            "to the first run's")

    # ---- phase 3: WAL-recovery drill ---------------------------------------
    # Journal the dup entry dead, then "crash" WITHOUT dropping its
    # disk bytes — the worst crash point (write-ahead means the journal
    # lands first; the bytes survive).  Recovery must refuse them.
    dup_ckey = next((k for k in cache1.keys() if k.startswith(digest)
                     and "-cv" not in k), None)
    drill = {"ckey": (dup_ckey or "")[:24]}
    if dup_ckey is None:
        failures.append("drill: dup entry key not resident")
    else:
        wal1.append("cache", op="dead", ckey=dup_ckey)
        dup_file = disk_dir / f"{dup_ckey}.rc"
        drill["disk_bytes_survive_crash"] = dup_file.exists()
        if not dup_file.exists():
            failures.append("drill: dup entry has no disk-tier file to "
                            "survive the crash (spill did not happen)")
    svc1.close()
    wal1.close()

    wal2 = RouterWAL(wal_path, fsync=False, shard="s0")
    drill["recovered_dead"] = len(wal2.state.cache_dead)
    if dup_ckey is not None and dup_ckey not in wal2.state.cache_dead:
        failures.append("drill: replay lost the cache-dead record")
    cache2 = ResultCache(
        capacity_entries=8, disk_dir=disk_dir, shard="s0",
        journal=lambda op, ckey: wal2.append("cache", op=op, ckey=ckey),
        dead=wal2.state.cache_dead)
    if dup_ckey is not None and (disk_dir / f"{dup_ckey}.rc").exists():
        failures.append("drill: adoption left the dead entry's bytes "
                        "on disk")
    if dup_ckey is not None and cache2.get(dup_ckey) is not None:
        failures.append("drill: RESURRECTED a journaled-dead entry "
                        "after restart")
    svc2 = mkservice(cache2)
    client2 = InProcessClient(svc2)
    status, rd = client2.request(mkbody(dup_img, "drill0"), timeout=60)
    drill["post_restart_dup"] = rd.get("cache")
    if rd.get("cache") != "miss":
        failures.append("drill: post-restart duplicate served "
                        f"{rd.get('cache')!r}, want a re-executed miss")
    if base64.b64decode(rd.get("image_b64", "")) != dup_want:
        failures.append("drill: post-restart re-execution != oracle")
    status, rd2 = client2.request(mkbody(dup_img, "drill1"), timeout=60)
    drill["post_restore_dup"] = rd2.get("cache")
    if rd2.get("cache") != "hit":
        failures.append("drill: re-stored entry not serving hits "
                        "(live record did not lift the tombstone)")
    status, rnb = client2.request(mkbody(nb_img, "drill2"), timeout=60)
    drill["neighbor_post_restart"] = rnb.get("cache")
    if rnb.get("cache") != "hit":
        failures.append("drill: never-invalidated neighbor not adopted "
                        f"from disk (got {rnb.get('cache')!r})")
    elif base64.b64decode(rnb.get("image_b64", "")) != nb_want:
        failures.append("drill: disk-adopted neighbor bytes != oracle")
    if rnb.get("digest") != nb_digest:
        failures.append("drill: neighbor digest drifted across restart")
    drill["cache"] = cache2.snapshot()
    svc2.close()
    wal2.close()

    # ---- phase 4: hit-rate-vs-skew curve -----------------------------------
    skews = [float(s) for s in args.skews.split(",") if s.strip()]
    pool_imgs = [mkimg(args.seed + k) for k in range(args.pool)]
    pool_wants = [want(im) for im in pool_imgs]
    pool_bodies = [mkbody(im, "p") for im in pool_imgs]

    def zipf_pick(i: int, s: float) -> int:
        cum, acc = [], 0.0
        for r in range(1, args.pool + 1):
            acc += 1.0 / (r ** s)
            cum.append(acc)
        rng = random.Random((args.seed << 24) ^ (1000003 * (i + 1)))
        return rng.choices(range(args.pool), cum_weights=cum)[0]

    def drive(n: int, pick, cache) -> dict:
        svc = mkservice(cache)
        cl = InProcessClient(svc)
        lats: list[tuple[float, str]] = []
        fails = 0
        for i in range(n):
            j = pick(i)
            b = dict(pool_bodies[j], request_id=f"z{i}")
            t = time.perf_counter()
            status, r = cl.request(b, timeout=60)
            lat = time.perf_counter() - t
            if status != 200 or not r.get("ok"):
                fails += 1
                continue
            if base64.b64decode(r.get("image_b64", "")) != pool_wants[j]:
                fails += 1
                failures.append(f"curve: response {i} != pool member "
                                f"{j}'s oracle")
                continue
            lats.append((lat, r.get("cache", "")))
        svc.close()
        hits = [l for l, c in lats if c == "hit"]
        miss = [l for l, c in lats if c != "hit"]
        return {
            "n": n, "completed": len(lats), "failures": fails,
            "cache_hit_rate": round(len(hits) / len(lats), 4) if lats
            else 0.0,
            "p99_ms": round(1e3 * (_pct([l for l, _ in lats], 0.99)
                                   or 0.0), 3),
            "hit_p99_ms": round(1e3 * (_pct(hits, 0.99) or 0.0), 3),
            "miss_p99_ms": round(1e3 * (_pct(miss, 0.99) or 0.0), 3),
        }

    lane_rows = []
    for s in skews:
        m = drive(args.zipf_n, lambda i, s=s: zipf_pick(i, s),
                  ResultCache())
        lane_rows.append({
            "mode": "zipf", "zipf_s": s, "pool": args.pool,
            "workload": f"cache-skew blur3 {args.rows}x{args.cols} "
                        f"zipf={s} pool={args.pool}", **m})
        if m["failures"]:
            failures.append(f"zipf s={s}: {m['failures']} failures")
    # All-unique A/B: the 0%-hit workload must not pay for the cache.
    uniq = min(args.unique_n, args.pool)
    for arm, cache in (("off", None), ("on", ResultCache())):
        m = drive(uniq, lambda i: i, cache)
        lane_rows.append({
            "mode": "unique", "cache": arm,
            "workload": f"cache-unique blur3 {args.rows}x{args.cols} "
                        f"cache={arm}", **m})
        if m["failures"]:
            failures.append(f"unique cache={arm}: {m['failures']} "
                            "failures")
        if arm == "on" and m["cache_hit_rate"]:
            failures.append("unique cache=on arm reported hits "
                            f"({m['cache_hit_rate']})")
    rates = [r["cache_hit_rate"] for r in lane_rows
             if r["mode"] == "zipf"]
    if rates != sorted(rates):
        failures.append(f"hit rate not monotone with skew: {rates}")

    curve_path = Path(args.curve_out)
    rewrite_shared_jsonl(curve_path, lane_rows, lane="cache_skew")

    # ---- phase 5: the lane gate, and its demonstrable teeth ----------------
    rc_gate = subprocess.run(
        [sys.executable, str(SCRIPTS / "perf_gate.py"),
         "--cache-lane", str(curve_path), "--out", args.gate_out,
         "--quiet"], check=False).returncode
    if rc_gate != 0:
        failures.append(f"perf_gate --cache-lane exited {rc_gate}")
    bad = [dict(r, cache_hit_rate=0.01) for r in lane_rows]
    bad_path = Path(tmp.name) / "bad_lane.jsonl"
    bad_path.write_text("".join(
        json.dumps(dict(r, lane="cache_skew")) + "\n" for r in bad))
    rc_bad = subprocess.run(
        [sys.executable, str(SCRIPTS / "perf_gate.py"),
         "--cache-lane", str(bad_path), "--quiet"],
        check=False, stdout=subprocess.DEVNULL).returncode
    if rc_bad == 0:
        failures.append("perf_gate --cache-lane PASSED a synthetic "
                        "flat-hit-rate lane (the gate has no teeth)")

    wall = time.time() - t0
    row = {
        "workload": f"cache-smoke blur3 {args.rows}x{args.cols} "
                    f"dup-tail+converge+wal-drill+zipf-curve",
        "dup_n": args.dup_n, "dup_hits": hit_stamps,
        "engine_flat": {k: v[1] for k, v in flat.items()},
        "wal_drill": drill,
        "skew_hit_rates": dict(zip((str(s) for s in skews), rates)),
        "lane_rows": len(lane_rows),
        "effective_backend": "shifted",
        "mesh": args.mesh,
        "wall_s": round(wall, 3),
        "failures": len(failures),
        "failure_detail": failures[:12],
    }
    out = Path(args.out)
    out.parent.mkdir(parents=True, exist_ok=True)
    out.write_text(json.dumps(row, indent=2))
    print(json.dumps(row), flush=True)
    tmp.cleanup()
    return 1 if failures else 0


if __name__ == "__main__":
    sys.exit(main())
